# seaweedfs-tpu node image: one image, every role selected by command
# (reference docker/Dockerfile — `weed` single binary, role by args).
FROM python:3.12-slim

RUN apt-get update \
 && apt-get install -y --no-install-recommends g++ make \
 && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY native/ native/
COPY seaweedfs_tpu/ seaweedfs_tpu/
# jax is only needed for the TPU EC backend; the storage/gateway roles
# run without it (ec.backend=cpu|native)
RUN pip install --no-cache-dir requests grpcio protobuf numpy pillow cryptography \
 && make -C native

ENV PYTHONUNBUFFERED=1
EXPOSE 9333 8080 8888 8333 2022 7333 17777
ENTRYPOINT ["python", "-m", "seaweedfs_tpu.server"]
CMD ["server", "-ip", "0.0.0.0", "-dir", "/data", "-filer"]
