"""Filer HTTP server: file API over the Filer core.

Reference: weed/server/filer_server_handlers_{read,write}.go — file
CRUD at path URLs, JSON directory listings, mv.from rename, recursive
delete. gRPC metadata API joins when the mount/S3 layers need it.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

from ..filer.entry import normalize_path
from ..filer.filer import Filer, FilerError
from ..filer.filer_store import NotFound


class FilerServer:
    def __init__(
        self,
        filer: Filer,
        ip: str = "localhost",
        port: int = 8888,
        meta_log=None,
        grpc_port: int = 0,
        peers: list[str] | None = None,
        tls=None,
        http_workers: int = 32,
        http_queue: int = 128,
    ):
        """meta_log: a filer.meta_log.MetaLog; when present it is
        subscribed to the filer, served at GET /~meta/tail (long-poll
        JSON batches) and over the gRPC SubscribeMetadata stream.

        grpc_port: port for the SeaweedFiler gRPC service (0 = pick an
        ephemeral port; exposed as .grpc_port).
        peers: other filers' gRPC addresses — starts a MetaAggregator
        that converges this store with theirs.
        http_workers/http_queue: bounded worker-pool HTTP front end
        (utils/http_pool.py); saturation answers 503 + Retry-After with
        a JSON error body. 0 workers = unbounded stdlib threading
        server (also the TLS path)."""
        self.filer = filer
        self.ip = ip
        self.port = port
        self.meta_log = meta_log
        if meta_log is not None:
            filer.subscribe(meta_log)
        from ..utils.http_pool import build_http_server

        self._http = build_http_server(
            (ip, port),
            self._handler_class(),
            server_kind="filer",
            workers=http_workers,
            accept_queue=http_queue,
            tls=tls,
            reject_body=lambda: (
                "application/json",
                b'{"error": "filer saturated: worker pool and accept '
                b'queue are full"}',
            ),
        )
        # Long-poll budget for /~meta/tail on the POOLED front end: a
        # full-length wait pins a worker, so only a quarter of the pool
        # may sit in long-polls at once — excess subscribers get their
        # wait clamped short (an early empty batch is legal long-poll
        # protocol; they re-poll) instead of starving the data plane.
        # The unbounded threaded server needs no budget (None).
        self._tail_slots = (
            threading.BoundedSemaphore(max(1, http_workers // 4))
            if http_workers and tls is None
            else None
        )
        self.tls = tls
        if tls is not None:
            tls.wrap_server(self._http)
        self._thread = threading.Thread(target=self._http.serve_forever, daemon=True)
        # gRPC metadata service (reference weed/pb/filer.proto service)
        from concurrent import futures as _futures

        import grpc as _grpc

        from ..filer.grpc_service import FilerGrpcService
        from ..pb import rpc as _rpc

        self._grpc = _grpc.server(_futures.ThreadPoolExecutor(max_workers=16))
        self._grpc_service = FilerGrpcService(filer, meta_log)
        _rpc.add_service(self._grpc, _rpc.FILER_SERVICE, self._grpc_service)
        self.grpc_port = self._grpc.add_insecure_port(f"{ip}:{grpc_port}")
        # distributed lock ring over the filer peer set (reference
        # weed/cluster/lock_manager); peers are gRPC addresses, same as
        # the MetaAggregator's
        from ..filer.lock_ring import LockRing

        self.lock_ring = LockRing(
            f"{ip}:{self.grpc_port}", list(peers or [])
        )
        self._grpc_service.lock_ring = self.lock_ring
        from ..filer.tus import TusManager

        self.tus = TusManager(filer)
        self.aggregator = None
        if peers:
            from ..filer.meta_aggregator import MetaAggregator

            self.aggregator = MetaAggregator(
                filer, peers, client_name=f"{ip}:{port}"
            )

    def _handler_class(self):
        filer = self.filer
        server_ref = self

        from ..utils.request_id import RequestTracingMixin

        class Handler(RequestTracingMixin, BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            trace_server_kind = "filer"

            def log_message(self, *a):
                pass

            def _path(self) -> str:
                return normalize_path(unquote(urlparse(self.path).path))

            def _send(self, code: int, body: bytes, ctype="application/json"):
                self.send_response(code)
                if code == 204:  # RFC 9110: no body on 204
                    self.end_headers()
                    return
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def _json(self, code: int, obj):
                self._send(code, json.dumps(obj).encode())

            def do_GET(self):
                q = parse_qs(urlparse(self.path).query)
                if self.serve_slo_endpoint(urlparse(self.path).path):
                    return
                if urlparse(self.path).path == "/~meta/tail":
                    return self._meta_tail(q)
                self._sw_op = "read"
                path = self._path()
                try:
                    entry = filer.find_entry(path)
                except NotFound:
                    return self._json(404, {"error": f"{path} not found"})
                if entry.is_directory:
                    try:
                        limit = int(q.get("limit", ["1024"])[0])
                    except ValueError:
                        limit = 1024
                    last = q.get("lastFileName", [""])[0]
                    entries = [
                        {
                            "FullPath": e.full_path,
                            "IsDirectory": e.is_directory,
                            "FileSize": e.file_size,
                            "Mtime": e.attr.mtime,
                            "Mime": e.attr.mime,
                        }
                        for e in filer.list_entries(path, start_from=last, limit=limit)
                    ]
                    body = json.dumps(
                        {
                            "Path": path,
                            "Entries": entries,
                            "ShouldDisplayLoadMore": len(entries) >= limit,
                        }
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("X-Filer-Listing", "true")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    if self.command != "HEAD":
                        self.wfile.write(body)
                    return
                if q.get("chunks", [""])[0] == "true":
                    # chunk manifest for fsck/ops tooling
                    body = json.dumps(
                        {"chunks": [c.fid for c in entry.chunks]}
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("X-Filer-Chunks", "true")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    if self.command != "HEAD":
                        self.wfile.write(body)
                    return
                total = entry.file_size
                # HEAD never touches the data plane: size/type come from
                # the metadata entry alone.
                if self.command == "HEAD":
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        entry.attr.mime or "application/octet-stream",
                    )
                    self.send_header("Content-Length", str(total))
                    self.send_header("Accept-Ranges", "bytes")
                    if entry.attr.md5:
                        self.send_header("ETag", f'"{entry.attr.md5.hex()}"')
                    self.end_headers()
                    return
                # range requests; a malformed Range falls back to 200-full
                offset, size = 0, -1
                status = 200
                rng = self.headers.get("Range", "")
                if rng.startswith("bytes="):
                    try:
                        spec = rng[6:].split(",")[0]
                        lo_s, _, hi_s = spec.partition("-")
                        lo = int(lo_s) if lo_s else max(total - int(hi_s), 0)
                        hi = int(hi_s) if hi_s and lo_s else total - 1
                        if lo > hi or lo >= max(total, 1):
                            body = b""
                            self.send_response(416)
                            self.send_header(
                                "Content-Range", f"bytes */{total}"
                            )
                            self.send_header("Content-Length", "0")
                            self.end_headers()
                            return
                        offset, size = lo, hi - lo + 1
                        status = 206
                    except ValueError:
                        offset, size, status = 0, -1, 200
                try:
                    data = filer.read_entry(entry, offset, size)
                except FilerError as e:
                    return self._json(500, {"error": str(e)})
                self.send_response(status)
                self.send_header(
                    "Content-Type", entry.attr.mime or "application/octet-stream"
                )
                self.send_header("Content-Length", str(len(data)))
                if status == 206:
                    self.send_header(
                        "Content-Range", f"bytes {offset}-{offset + len(data) - 1}/{total}"
                    )
                self.send_header("Accept-Ranges", "bytes")
                if entry.attr.md5:
                    self.send_header("ETag", f'"{entry.attr.md5.hex()}"')
                self.end_headers()
                if self.command != "HEAD":
                    # native body egress on the pooled front end
                    # (utils/http_pool.send_body), wfile fallback
                    from ..utils.http_pool import send_body

                    send_body(self, data)

            def do_HEAD(self):
                # TUS (resumable upload) offset probe
                path = self._path()
                if path.startswith("/.tus/") and "Tus-Resumable" in self.headers:
                    from ..filer.tus import TusError

                    try:
                        state = server_ref.tus.head(path[len("/.tus/") :])
                    except TusError as e:
                        return self._tus_status(e.status)
                    self.send_response(200)
                    self.send_header("Tus-Resumable", "1.0.0")
                    self.send_header("Upload-Offset", str(state["offset"]))
                    self.send_header("Upload-Length", str(state["length"]))
                    self.send_header("Cache-Control", "no-store")
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                return self.do_GET()

            def _tus_status(self, code: int, offset: int | None = None):
                self.send_response(code)
                self.send_header("Tus-Resumable", "1.0.0")
                if offset is not None:
                    self.send_header("Upload-Offset", str(offset))
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_OPTIONS(self):
                self.send_response(204)
                self.send_header("Tus-Resumable", "1.0.0")
                self.send_header("Tus-Version", "1.0.0")
                self.send_header("Tus-Extension", "creation,termination")
                self.send_header("Tus-Max-Size", str(1 << 40))
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_PATCH(self):
                path = self._path()
                # drain the body FIRST: a keep-alive connection must
                # stay framed even when the request is rejected
                try:
                    n = int(self.headers.get("Content-Length", "0") or "0")
                except ValueError:
                    n = 0
                body = self.rfile.read(n)
                if not path.startswith("/.tus/"):
                    return self._json(405, {"error": "PATCH is TUS-only"})
                from ..filer.tus import TusError

                try:
                    offset = int(self.headers.get("Upload-Offset", "-1"))
                    new_off = server_ref.tus.patch(
                        path[len("/.tus/") :], offset, body
                    )
                except TusError as e:
                    return self._tus_status(e.status)
                except ValueError:
                    return self._tus_status(400)
                except FilerError:
                    # e.g. the target path is a directory: surfaced as
                    # an HTTP status, never a dropped connection
                    return self._tus_status(409)
                self._tus_status(204, offset=new_off)

            def _meta_tail(self, q):
                """Long-poll metadata subscription: events after sinceNs,
                blocking up to waitSeconds for fresh ones."""
                srv_log = server_ref.meta_log
                if srv_log is None:
                    return self._json(404, {"error": "no metadata log"})
                try:
                    since = int(q.get("sinceNs", ["0"])[0])
                    limit = int(q.get("limit", ["10000"])[0])
                    wait_s = min(float(q.get("waitSeconds", ["0"])[0]), 60.0)
                except ValueError:
                    return self._json(400, {"error": "bad parameters"})
                events = srv_log.read_since(since, limit)
                if not events and wait_s > 0:
                    slots = server_ref._tail_slots
                    got_slot = (
                        True if slots is None
                        else slots.acquire(blocking=False)
                    )
                    try:
                        if not got_slot:
                            # long-poll budget exhausted: answer fast
                            # with an empty batch rather than pinning
                            # another pool worker for up to a minute
                            wait_s = min(wait_s, 0.5)
                        srv_log.wait_for_events(since, timeout=wait_s)
                    finally:
                        if slots is not None and got_slot:
                            slots.release()
                    events = srv_log.read_since(since, limit)
                last = events[-1]["tsNs"] if events else since
                import time as _time

                self._json(
                    200,
                    {
                        "events": events,
                        "lastTsNs": last,
                        # gap detection + clock anchoring for subscribers
                        "droppedBeforeTsNs": srv_log.dropped_before_ts,
                        "nowNs": _time.time_ns(),
                    },
                )

            def _remote_op(self, op: str):
                """Remote-mount control plane (reference shell
                remote.configure/mount/cache/uncache/unmount)."""
                import json as _json

                from ..remote import mount as rm

                n = int(self.headers.get("Content-Length", "0") or "0")
                try:
                    body = _json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    return self._json(400, {"error": "bad json"})
                try:
                    if op == "configure":
                        rm.configure(filer, body.pop("name"), body)
                        return self._json(200, {"configured": True})
                    if op == "mount":
                        n_objs = rm.mount(
                            filer,
                            body["dir"],
                            body["remote"],
                            body["bucket"],
                            body.get("prefix", ""),
                        )
                        return self._json(200, {"mounted": n_objs})
                    if op == "unmount":
                        rm.unmount(filer, body["dir"])
                        return self._json(200, {"unmounted": True})
                    if op == "cache":
                        e = rm.cache(filer, body["path"])
                        return self._json(
                            200, {"cached": True, "chunks": len(e.chunks)}
                        )
                    if op == "uncache":
                        rm.uncache(filer, body["path"])
                        return self._json(200, {"uncached": True})
                    if op == "mount.buckets":
                        out = rm.mount_buckets(
                            filer,
                            body["dir"],
                            body["remote"],
                            body.get("prefix", ""),
                        )
                        return self._json(
                            200,
                            {"mounted": out, "buckets": len(out)},
                        )
                    if op == "meta.sync":
                        added, updated, removed = rm.meta_sync(
                            filer, body["dir"]
                        )
                        return self._json(
                            200,
                            {
                                "added": added,
                                "updated": updated,
                                "removed": removed,
                            },
                        )
                except (FilerError, NotFound, KeyError) as e:
                    return self._json(409, {"error": str(e)})
                except Exception as e:  # remote endpoint failures
                    return self._json(502, {"error": str(e)})
                return self._json(404, {"error": f"unknown op {op}"})

            def _write(self):
                u = urlparse(self.path)
                q = parse_qs(u.query)
                path = self._path()
                if path.startswith("/~remote/") and self.command == "POST":
                    return self._remote_op(path[len("/~remote/") :])
                if (
                    self.command == "POST"
                    and "Tus-Resumable" in self.headers
                    and "Upload-Length" in self.headers
                ):
                    # TUS creation: the request path is the target.
                    # Drain any body (creation-with-upload clients) so
                    # the keep-alive stream stays framed.
                    self.rfile.read(
                        int(self.headers.get("Content-Length", "0") or "0")
                    )
                    from ..filer.tus import TusError

                    try:
                        upload_id = server_ref.tus.create(
                            path, int(self.headers["Upload-Length"])
                        )
                    except (TusError, ValueError, FilerError):
                        return self._tus_status(400)
                    self.send_response(201)
                    self.send_header("Tus-Resumable", "1.0.0")
                    self.send_header("Location", f"/.tus/{upload_id}")
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                if "mv.from" in q:
                    src = normalize_path(q["mv.from"][0])
                    try:
                        filer.rename(src, path)
                    except NotFound:
                        return self._json(404, {"error": f"{src} not found"})
                    except FilerError as e:
                        return self._json(409, {"error": str(e)})
                    return self._json(200, {"from": src, "to": path})
                # trailing slash on the RAW url means mkdir (normalize_path
                # strips it, so check the unnormalized form)
                raw_is_dir = unquote(u.path).rstrip() not in ("", "/") and unquote(
                    u.path
                ).endswith("/")
                if raw_is_dir or q.get("mkdir", [""])[0] == "true":
                    from ..filer.entry import new_entry

                    filer.create_entry(new_entry(path, is_directory=True, mode=0o755))
                    return self._json(201, {"path": path})
                if "chunked" in (
                    self.headers.get("Transfer-Encoding", "")
                ).lower():
                    # streaming clients (curl -T, shell fs.cp) send
                    # chunked bodies with no Content-Length
                    parts = []
                    while True:
                        line = self.rfile.readline(1024).strip()
                        try:
                            size = int(line.split(b";")[0], 16)
                        except ValueError:
                            break
                        if size == 0:
                            self.rfile.readline(1024)  # trailing CRLF
                            break
                        parts.append(self.rfile.read(size))
                        self.rfile.read(2)  # chunk CRLF
                    body = b"".join(parts)
                else:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = self.rfile.read(length)
                from .volume_server import _parse_upload

                name, mime, data = _parse_upload(self.headers, body)
                ttl_sec = 0
                if q.get("ttl", [""])[0]:
                    spec = q["ttl"][0]
                    mult = {"s": 1, "m": 60, "h": 3600, "d": 86400}.get(
                        spec[-1], 0
                    )
                    try:
                        ttl_sec = (
                            int(spec[:-1]) * mult if mult else int(spec)
                        )
                    except ValueError:
                        return self._json(400, {"error": f"bad ttl {spec!r}"})
                try:
                    entry = filer.write_file(
                        path, data, mime=mime, ttl_sec=ttl_sec
                    )
                except FilerError as e:
                    return self._json(500, {"error": str(e)})
                self._json(
                    201, {"name": entry.name, "size": entry.file_size}
                )

            do_PUT = _write
            do_POST = _write

            def do_DELETE(self):
                path = self._path()
                if path.startswith("/.tus/") and "Tus-Resumable" in self.headers:
                    from ..filer.tus import TusError

                    try:
                        server_ref.tus.terminate(path[len("/.tus/") :])
                    except TusError as e:
                        return self._tus_status(e.status)
                    return self._tus_status(204)
                q = parse_qs(urlparse(self.path).query)
                recursive = q.get("recursive", [""])[0] == "true"
                try:
                    filer.delete_entry(path, recursive=recursive)
                except FilerError as e:
                    return self._json(409, {"error": str(e)})
                self._json(204, {})

        return Handler

    def start(self) -> None:
        self._thread.start()
        self._grpc.start()
        if self.lock_ring.members != [self.lock_ring.self_addr]:
            self.lock_ring.start()  # probing only matters with peers
        if self.aggregator is not None:
            self.aggregator.start()

    def stop(self) -> None:
        self.lock_ring.stop()
        if self.aggregator is not None:
            self.aggregator.stop()
        self._grpc.stop(grace=0.5)
        self._http.shutdown()
        self._http.server_close()
        self.filer.close()
        if self.meta_log is not None:
            self.meta_log.close()
