"""`python -m seaweedfs_tpu.server` — node launcher (weed-style).

Subcommands: master | volume | server (all-in-one master + volume,
reference `weed server` / `weed mini`).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

from ..utils.glog import logger

log = logger("launcher")


def _add_tls_flags(sp) -> None:
    """Reference security.toml https.* keys as flags: a cert/key pair
    turns the node's HTTP listener(s) into TLS listeners with hot
    cert-reload (utils/tls.py)."""
    sp.add_argument("-tls.cert", dest="tls_cert", default="")
    sp.add_argument("-tls.key", dest="tls_key", default="")
    sp.add_argument(
        "-tls.ca", dest="tls_ca", default="",
        help="when set, require and verify client certificates (mTLS)",
    )


def _tls_from(a):
    if not getattr(a, "tls_cert", ""):
        return None
    from ..utils.tls import TlsConfig

    return TlsConfig(
        cert_file=a.tls_cert,
        key_file=a.tls_key,
        ca_file=a.tls_ca or None,
        client_auth=bool(a.tls_ca),
    )


def _add_ec_trace_flags(sp) -> None:
    sp.add_argument(
        "-ec.trace", dest="ec_trace", action="store_true",
        help="arm the EC flight recorder (per-stage spans, "
        "/debug/traces ring, sw_ec_stage_seconds histograms)",
    )
    sp.add_argument(
        "-ec.traceRing", dest="ec_trace_ring", type=int, default=0,
        help="completed traces kept in the /debug/traces ring "
        "(0 = default 256)",
    )
    sp.add_argument(
        "-ec.slowOpSeconds", dest="ec_slow_op_s", type=float, default=0.0,
        help="log the full span tree of any EC op slower than this "
        "(arms the flight recorder; 0 = off)",
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="seaweedfs_tpu.server")
    sub = p.add_subparsers(dest="mode", required=True)

    m = sub.add_parser("master")
    m.add_argument("-ip", default="localhost")
    m.add_argument("-port", type=int, default=9333)
    m.add_argument("-volumeSizeLimitMB", type=int, default=30 * 1024)
    m.add_argument("-jwt.key", dest="jwt_key", default="")
    m.add_argument(
        "-ec.autoFullness", dest="ec_auto", type=float, default=None,
        help="auto-submit ec_encode for volumes at this fraction of the size limit (0=off)",
    )
    m.add_argument(
        "-ec.scrubInterval", dest="ec_scrub_interval", type=float, default=0.0,
        help="fleet scrub period in seconds: every EC volume verified "
        "once per period via ec_scrub worker tasks (0=off)",
    )
    m.add_argument(
        "-ec.rebalanceInterval", dest="ec_rebalance_interval", type=float,
        default=0.0,
        help="data-gravity period in seconds: rank hot EC volumes vs "
        "holder chip-deficit and dispatch bounded ec_migrate worker "
        "tasks toward chip-rich low-load nodes (0=off; "
        "SEAWEED_EC_REBALANCE_* knobs bound each sweep)",
    )
    m.add_argument(
        "-peers", default="",
        help="comma-separated HA master group incl. this node (host:port,...)",
    )
    m.add_argument(
        "-mdir", default="",
        help="meta dir for the durable raft log (required for HA restarts)",
    )
    m.add_argument(
        "-telemetry.url", dest="telemetry_url", default="",
        help="opt-in phone-home endpoint (leader posts count aggregates)",
    )
    _add_tls_flags(m)

    v = sub.add_parser("volume")
    v.add_argument("-ip", default="localhost")
    v.add_argument("-port", type=int, default=8080)
    v.add_argument("-dir", action="append", required=True)
    v.add_argument("-master", default="localhost:9333")
    v.add_argument("-max", type=int, default=None)
    v.add_argument("-ec.backend", dest="ec_backend", default=None)
    v.add_argument(
        "-index",
        default=None,
        choices=["memory", "sqlite"],
        help="needle map kind (sqlite = durable, O(delta) restart)",
    )
    v.add_argument("-dataCenter", default="")
    v.add_argument("-rack", default="")
    v.add_argument("-jwt.key", dest="jwt_key", default="")
    _add_ec_trace_flags(v)
    _add_tls_flags(v)

    f = sub.add_parser("filer")
    f.add_argument("-ip", default="localhost")
    f.add_argument("-port", type=int, default=8888)
    f.add_argument("-master", default="localhost:9333")
    f.add_argument("-dir", default=None)
    f.add_argument("-collection", default="")
    f.add_argument("-replication", default="")
    f.add_argument("-jwt.key", dest="jwt_key", default="")
    f.add_argument("-notify.webhook", dest="notify_webhook", default="")
    f.add_argument("-notify.mq", dest="notify_mq", default="")
    f.add_argument(
        "-store",
        default="sqlite",
        choices=["sqlite", "sstable", "memory"],
        help="metadata backend (sstable = embedded WAL+SSTable engine)",
    )
    f.add_argument("-grpcPort", type=int, default=0, help="gRPC metadata API port (0 = port+10000)")
    f.add_argument("-peers", default="", help="comma-separated peer filer gRPC addrs for multi-filer")
    _add_tls_flags(f)

    ts = sub.add_parser(
        "telemetry", help="telemetry collector server (reference telemetry/server)"
    )
    ts.add_argument("-ip", default="localhost")
    ts.add_argument("-port", type=int, default=9999)
    ts.add_argument("-file", default="", help="JSONL persistence path")

    b = sub.add_parser("mq.broker")
    b.add_argument("-ip", default="localhost")
    b.add_argument("-port", type=int, default=17777)
    b.add_argument("-filer", default="", help="filer host:port for durable segments")
    b.add_argument("-segmentRecords", type=int, default=4096)
    b.add_argument(
        "-kafkaPort", type=int, default=-1,
        help="also speak the Kafka wire protocol on this port (-1 = off)",
    )
    b.add_argument(
        "-pgPort", type=int, default=-1,
        help="serve PostgreSQL clients a SQL view over topics (-1 = off)",
    )
    b.add_argument(
        "-pgUser", default="",
        help="user:password for PG auth (empty = trust)",
    )
    b.add_argument(
        "-peers", default="",
        help="comma-separated broker group (grpc host:port,...) for "
        "partition balancing + follower replication",
    )
    b.add_argument(
        "-statusPort", type=int, default=-1,
        help="HTTP operator plane: /status JSON (gateway pool, parity "
        "lag, broker loads) + /metrics prometheus text (-1 = off)",
    )
    b.add_argument(
        "-parityDir", default="",
        help="local dir for streaming-EC durable-parity log streams: "
        "topics get parity trailing the append head by a bounded lag "
        "(SEAWEED_EC_STREAM_* knobs) instead of waiting for segment "
        "seal, and the unsealed tail is crash-recoverable",
    )
    # broker dials the filer: it needs the https switch from
    # security.toml even though it has no HTTP listener of its own
    _add_tls_flags(b)

    ag = sub.add_parser(
        "mq.agent",
        help="MQ agent: session facade for thin publish/subscribe "
        "clients (reference weed mq.agent)",
    )
    ag.add_argument("-ip", default="localhost")
    ag.add_argument("-port", type=int, default=16777)
    ag.add_argument("-broker", default="localhost:17777")

    s = sub.add_parser("server")
    s.add_argument("-ip", default="localhost")
    s.add_argument("-masterPort", type=int, default=9333)
    s.add_argument("-port", type=int, default=8080)
    s.add_argument("-filerPort", type=int, default=8888)
    s.add_argument("-filer", action="store_true", help="also run a filer")
    s.add_argument("-s3", action="store_true", help="also run the S3 gateway")
    s.add_argument("-s3Port", type=int, default=8333)
    s.add_argument("-s3AccessKey", default="")
    s.add_argument(
        "-s3Config",
        default="",
        help="identities/roles JSON (reference -s3.config identities.json)",
    )
    s.add_argument("-s3SecretKey", default="")
    s.add_argument("-dir", action="append", required=True)
    s.add_argument("-max", type=int, default=None)
    s.add_argument("-ec.backend", dest="ec_backend", default=None)
    s.add_argument("-jwt.key", dest="jwt_key", default="")
    s.add_argument("-notify.webhook", dest="notify_webhook", default="")
    s.add_argument("-notify.mq", dest="notify_mq", default="")
    s.add_argument("-webdav", action="store_true", help="also run WebDAV")
    s.add_argument(
        "-ec.autoFullness", dest="ec_auto", type=float, default=None,
        help="auto-submit ec_encode for volumes at this fraction of the size limit (0=off)",
    )
    s.add_argument(
        "-ec.scrubInterval", dest="ec_scrub_interval", type=float, default=0.0,
        help="fleet scrub period in seconds (0=off)",
    )
    s.add_argument("-webdavPort", type=int, default=7333)
    s.add_argument("-sftp", action="store_true", help="also run the SFTP gateway")
    s.add_argument("-sftpPort", type=int, default=2022)
    s.add_argument(
        "-sftpUser", action="append", default=[],
        help="user:password[:home[:ro]] (repeatable)",
    )
    s.add_argument(
        "-admin", action="store_true",
        help="also run the admin dashboard (reference `weed admin`)",
    )
    s.add_argument("-adminPort", type=int, default=23646)
    s.add_argument(
        "-adminIp", default="localhost",
        help="admin dashboard bind address (default localhost: the "
        "maintenance plane is unauthenticated unless -adminSecret is set)",
    )
    s.add_argument(
        "-adminSecret", default="",
        help="require X-Admin-Token on admin POSTs (reference adminPassword)",
    )
    _add_ec_trace_flags(s)
    _add_tls_flags(s)

    sc = sub.add_parser(
        "scaffold", help="emit a commented config template (weed scaffold)"
    )
    sc.add_argument("-config", dest="config", default="security")
    sc.add_argument(
        "-output", default="",
        help="directory to write <name>.toml into (default: stdout)",
    )

    a = p.parse_args(argv)

    if a.mode == "scaffold":
        from ..utils.scaffold import scaffold

        text = scaffold(a.config)
        if a.output:
            path = os.path.join(a.output, f"{a.config}.toml")
            with open(path, "w") as fh:
                fh.write(text)
            print(path)
        else:
            print(text, end="")
        return 0

    # security.toml supplies defaults for flags the operator left unset
    # (reference weed/util/config.go viper load; flags win)
    from ..utils.config import load_config

    sec = load_config("security")
    if sec:
        if not getattr(a, "jwt_key", ""):
            a.jwt_key = sec.get_str("jwt.signing.key")
        # per-field merge: an explicitly-passed -tls.ca must survive a
        # security.toml that only sets cert/key (flags win field-wise)
        for attr, key in (
            ("tls_cert", "https.default.cert"),
            ("tls_key", "https.default.key"),
            ("tls_ca", "https.default.ca"),
        ):
            if hasattr(a, attr) and not getattr(a, attr):
                setattr(a, attr, sec.get_str(key))
    if getattr(a, "tls_cert", ""):
        # internal hops (client→volume, filer→volume, replication) must
        # speak https too, trusting the cluster CA (or the cert itself
        # for single-cert self-signed setups)
        from ..utils.urls import enable_https

        enable_https(getattr(a, "tls_ca", "") or a.tls_cert)

    # mode-specific TOML defaults: a flag left unset parses as the None
    # sentinel and is filled from config, then from the built-in
    # default — an EXPLICIT flag always wins, even at the default value
    if a.mode in ("volume", "server"):
        vcfg = load_config("volume")
        if getattr(a, "index", None) is None:
            a.index = vcfg.get_str("volume.index", "memory") or "memory"
        if a.ec_backend is None:
            a.ec_backend = (
                vcfg.get_str("volume.ec_backend", "auto") or "auto"
            )
        if a.max is None:
            a.max = int(vcfg.get("volume.store.max_volumes", 8))
    if a.mode in ("master", "server"):
        mcfg = load_config("master")
        if getattr(a, "ec_auto", None) is None:
            a.ec_auto = float(
                mcfg.get("master.maintenance.ec_auto_fullness", 0.0)
            )
        a.garbage_threshold = float(
            mcfg.get("master.vacuum.garbage_threshold", 0.3)
        )
        a.vacuum_interval = float(
            mcfg.get("master.vacuum.interval_seconds", 60)
        )
    if a.mode in ("filer", "server"):
        fcfg = load_config("filer")
        if getattr(a, "dir", None) is None and a.mode == "filer":
            db = (
                fcfg.get_str("sqlite.dbFile")
                if fcfg.get("sqlite.enabled")
                else ""
            )
            a.dir = (os.path.dirname(db) or ".") if db else "./filerdb"
        ncfg = load_config("notification")
        if ncfg:
            if not getattr(a, "notify_webhook", "") and ncfg.get(
                "notification.webhook.enabled"
            ):
                a.notify_webhook = ncfg.get_str(
                    "notification.webhook.endpoint"
                )
            if not getattr(a, "notify_mq", "") and ncfg.get(
                "notification.mq.enabled"
            ):
                a.notify_mq = ncfg.get_str("notification.mq.broker")
    if a.mode == "server" and getattr(a, "s3", False):
        scfg = load_config("s3")
        if scfg and not a.s3Config:
            a.s3Config = scfg.get_str("s3.config")
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *x: stop.set())
    signal.signal(signal.SIGINT, lambda *x: stop.set())

    servers = []
    if a.mode == "mq.agent":
        from ..mq.agent import MqAgentServer

        agent = MqAgentServer(a.broker, ip=a.ip, port=a.port)
        agent.start()
        log.info("mq agent on %s:%s -> broker %s", a.ip, agent.port, a.broker)
        stop.wait()  # SIGTERM/SIGINT set it (handlers above)
        agent.stop()
        return 0

    if a.mode == "telemetry":
        from ..utils.telemetry_server import TelemetryServer

        tsrv = TelemetryServer(
            ip=a.ip, port=a.port, persist_path=a.file or None
        )
        tsrv.start()
        log.info("telemetry collector on %s:%s", a.ip, tsrv.port)
        stop.wait()  # SIGTERM/SIGINT set it (handlers above)
        tsrv.stop()
        return 0

    if a.mode == "mq.broker":
        from ..mq.broker import MqBrokerServer

        pg_users = None
        if a.pgUser:
            user, _, pw = a.pgUser.partition(":")
            pg_users = {user: pw}
        bs = MqBrokerServer(
            ip=a.ip,
            grpc_port=a.port,
            filer=a.filer,
            segment_records=a.segmentRecords,
            kafka_port=a.kafkaPort,
            pg_port=a.pgPort,
            pg_users=pg_users,
            peers=[p.strip() for p in a.peers.split(",") if p.strip()],
            parity_dir=a.parityDir,
            status_port=a.statusPort,
        )
        bs.start()
        servers.append(bs)
        log.info(
            "mq broker on %s:%s (filer=%s%s%s%s)",
            a.ip, a.port, a.filer or "memory-only",
            f", kafka on :{bs.kafka.port}" if bs.kafka else "",
            f", pg on :{bs.pg.port}" if bs.pg else "",
            f", status on :{bs.status_port}"
            if bs._status_httpd is not None else "",
        )

    if a.mode in ("master", "server"):
        from .master import MasterServer

        port = a.port if a.mode == "master" else a.masterPort
        limit = (
            a.volumeSizeLimitMB * 1024 * 1024
            if a.mode == "master"
            else 30 * 1024**3
        )
        ms = MasterServer(
            ip=a.ip, port=port, volume_size_limit=limit,
            jwt_key=getattr(a, "jwt_key", ""),
            ec_auto_fullness=getattr(a, "ec_auto", 0.0),
            peers=getattr(a, "peers", "") or None,
            meta_dir=getattr(a, "mdir", "") or None,
            tls=_tls_from(a),
            telemetry_url=getattr(a, "telemetry_url", ""),
            garbage_threshold=getattr(a, "garbage_threshold", 0.3),
            vacuum_interval=getattr(a, "vacuum_interval", 60.0),
            ec_scrub_interval=getattr(a, "ec_scrub_interval", 0.0),
            ec_rebalance_interval=getattr(a, "ec_rebalance_interval", 0.0),
        )
        ms.start()
        servers.append(ms)
        log.info("master listening on %s:%s (grpc %s)", a.ip, port, ms.grpc_port)

    if a.mode in ("volume", "server"):
        from .volume_server import VolumeServer

        master = (
            a.master if a.mode == "volume" else f"{a.ip}:{a.masterPort}"
        )
        vs = VolumeServer(
            directories=a.dir,
            master=master,
            ip=a.ip,
            port=a.port,
            max_volume_count=a.max,
            ec_backend=a.ec_backend,
            data_center=getattr(a, "dataCenter", ""),
            rack=getattr(a, "rack", ""),
            jwt_key=getattr(a, "jwt_key", ""),
            needle_map_kind=getattr(a, "index", "memory"),
            tls=_tls_from(a),
            ec_trace=getattr(a, "ec_trace", False),
            ec_trace_ring=getattr(a, "ec_trace_ring", 0),
            ec_slow_op_s=getattr(a, "ec_slow_op_s", 0.0),
        )
        vs.start()
        servers.append(vs)
        log.info("volume server on %s:%s (grpc %s)", a.ip, a.port, vs.grpc_port)

    if a.mode == "server" and getattr(a, "admin", False):
        from ..admin import AdminServer

        adm = AdminServer(
            master=f"{a.ip}:{a.masterPort}",
            ip=a.adminIp,
            port=a.adminPort,
            config_path=os.path.join(a.dir[0], "admin_maintenance.json"),
            auth_token=a.adminSecret or None,
        )
        adm.start()
        servers.append(adm)
        log.info("admin dashboard on %s:%s", a.adminIp, a.adminPort)

    if a.mode == "filer" or (
        a.mode == "server" and (a.filer or a.s3 or a.webdav or a.sftp)
    ):
        from ..filer.filer import Filer
        from ..filer.filer_store import SqliteStore
        from .filer_server import FilerServer

        if a.mode == "filer":
            master, fport, dbdir = a.master, a.port, a.dir
        else:
            master, fport = f"{a.ip}:{a.masterPort}", a.filerPort
            dbdir = os.path.join(a.dir[0], "filerdb")
        store_kind = getattr(a, "store", "sqlite")
        if store_kind == "sstable":
            from ..filer.sstable_store import SSTableStore

            store = SSTableStore(os.path.join(dbdir, "filer.sst"))
        elif store_kind == "memory":
            from ..filer.filer_store import MemoryStore

            store = MemoryStore()
        else:
            store = SqliteStore(os.path.join(dbdir, "filer.db"))
        filer = Filer(
            store,
            master=master,
            collection=getattr(a, "collection", ""),
            replication=getattr(a, "replication", ""),
            jwt_key=getattr(a, "jwt_key", ""),
        )
        if getattr(a, "notify_webhook", ""):
            from ..filer.notification import WebhookNotifier

            filer.subscribe(WebhookNotifier(a.notify_webhook))
            log.info("filer events -> webhook %s", a.notify_webhook)
        if getattr(a, "notify_mq", ""):
            from ..filer.notification import MqNotifier

            filer.subscribe(MqNotifier(a.notify_mq))
            log.info("filer events -> mq %s", a.notify_mq)
        from ..filer.meta_log import MetaLog

        fgrpc = getattr(a, "grpcPort", 0) or fport + 10000
        peers = [
            p.strip()
            for p in getattr(a, "peers", "").split(",")
            if p.strip()
        ]
        fs = FilerServer(
            filer,
            ip=a.ip,
            port=fport,
            meta_log=MetaLog(os.path.join(dbdir, "metalog")),
            grpc_port=fgrpc,
            peers=peers,
            tls=_tls_from(a),
        )
        fs.start()
        servers.append(fs)
        log.info(
            "filer on %s:%s (grpc %s%s)",
            a.ip,
            fport,
            fs.grpc_port,
            f", peers={peers}" if peers else "",
        )

        if a.mode == "server" and a.s3:
            from ..s3 import Identity, IdentityStore, S3Server

            sts = oidc = ldap = None
            if getattr(a, "s3Config", ""):
                from ..s3.config import load_s3_config

                idents, sts, oidc, ldap = load_s3_config(a.s3Config)
            else:
                idents = IdentityStore()
            if a.s3AccessKey:
                idents.add(Identity("admin", a.s3AccessKey, a.s3SecretKey))
            s3srv = S3Server(
                filer, ip=a.ip, port=a.s3Port, identities=idents, sts=sts,
                tls=_tls_from(a), oidc=oidc, ldap=ldap,
            )
            s3srv.start()
            servers.append(s3srv)
            log.info("s3 gateway on %s:%s", a.ip, a.s3Port)

        if a.mode == "server" and getattr(a, "sftp", False):
            from ..sftpd import SftpServer
            from ..sftpd.sftp_server import SftpUser

            users = {}
            for spec in a.sftpUser:
                parts = spec.split(":")
                if len(parts) < 2:
                    continue
                users[parts[0]] = SftpUser(
                    name=parts[0],
                    password=parts[1],
                    home=parts[2] if len(parts) > 2 and parts[2] else "/",
                    read_only=len(parts) > 3 and parts[3] == "ro",
                )
            sftp_srv = SftpServer(
                filer, ip=a.ip, port=a.sftpPort, users=users
            )
            sftp_srv.start()
            servers.append(sftp_srv)
            log.info("sftp on %s:%s (%d users)", a.ip, a.sftpPort, len(users))

        if a.mode == "server" and getattr(a, "webdav", False):
            from .webdav_server import WebDavServer

            wd = WebDavServer(
                filer, ip=a.ip, port=a.webdavPort, tls=_tls_from(a)
            )
            wd.start()
            servers.append(wd)
            log.info("webdav on %s:%s", a.ip, a.webdavPort)

    stop.wait()
    for srv in servers:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
