"""Volume server: HTTP data plane + gRPC control/EC plane + heartbeats.

Reference: weed/server/volume_server.go, HTTP handlers
(volume_server_handlers_read.go:142 GetOrHeadHandler,
_write.go:20 PostHandler -> topology.ReplicatedWrite store_replicate.go:32),
gRPC EC RPCs (volume_grpc_erasure_coding.go), heartbeat stream
(volume_grpc_client_to_master.go).
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
from concurrent import futures
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import grpc

from ..ec import context as ec_context
from ..ec import fleet
from ..ec.context import ECError
from ..ec.decoder import ec_decode_volume
from ..ec.encoder import ec_encode_volume
from ..ec.rebuild import rebuild_ec_files
from ..ec.volume_info import VolumeInfo
from ..storage.file_id import FileId, FileIdError
from ..storage.needle import CrcError, Needle
from ..storage.store import Store
from ..storage.volume import (
    CookieMismatch,
    NotFoundError,
    ReadOnlyError,
    Volume,
    VolumeError,
)
from ..pb import cluster_pb2 as pb
from ..pb import rpc
from ..utils import metrics as M
from ..utils import request_id as _rid
from ..utils import trace
from ..utils.glog import logger

log = logger("volume")

_EC_STREAM_CHUNK = 256 * 1024


def _shard_bits(ids) -> int:
    bits = 0
    for i in ids:
        bits |= 1 << i
    return bits


class VolumeService:
    """gRPC servicer over one Store."""

    def __init__(self, server: "VolumeServer"):
        self.server = server
        self.store = server.store

    def _rpc_span(self, op: str, request, context, **attrs):
        """Server-side end of cross-RPC tracing for the EC RPCs: adopt
        the caller's X-Request-ID (minting one at chain start) and —
        when the flight recorder is armed — continue the caller's trace
        as a local root, so a fleet-dispatched rebuild and every peer
        shard-read it triggers share ONE trace id. Returns None when
        the tracer is disarmed; request-id adoption always runs (it is
        one contextvar set)."""
        md = trace.metadata_dict(context)
        _rid.ensure(md.get(trace.REQUEST_ID_KEY))
        return trace.start_from_metadata(
            op, md,
            server=f"{self.server.ip}:{self.server.port}",
            volume=request.volume_id,
            **attrs,
        )

    # ------------------------------------------------------------ admin

    def AllocateVolume(self, request, context):
        self.store.allocate_volume(
            request.volume_id,
            collection=request.collection,
            replica_placement=request.replication or "000",
            ttl=request.ttl,
            disk_type=request.disk_type,
        )
        self.server.notify_new_volume(request.volume_id)
        return pb.AllocateVolumeResponse()

    def VolumeDelete(self, request, context):
        try:
            self.store.delete_volume(request.volume_id)
            self.server.notify_deleted_volume(request.volume_id)
            return pb.VolumeCommandResponse()
        except NotFoundError as e:
            return pb.VolumeCommandResponse(error=str(e))

    def VolumeMount(self, request, context):
        """Load an existing .dat/.idx pair from disk into the store
        (used after VolumeCopy pulled the files from a peer)."""
        try:
            self.store.mount_volume(request.volume_id, request.collection)
        except NotFoundError as e:
            return pb.VolumeCommandResponse(error=str(e))
        self.server.notify_new_volume(request.volume_id)
        return pb.VolumeCommandResponse()

    def VolumeCopy(self, request, context):
        """Pull a whole volume (.dat + .idx + .vif) from a peer, then
        load it (reference VolumeCopy volume_grpc_copy.go). All files
        land as temps and publish together — a half-copied volume never
        becomes loadable."""
        if self.store.find_volume(request.volume_id) is not None:
            return pb.VolumeCommandResponse(error="volume already here")
        loc = self.store._pick_location()
        base = Volume.base_file_name(
            loc.directory, request.collection, request.volume_id
        )
        exts = (".dat", ".idx", ".vif")
        tmps: dict[str, str] = {}
        try:
            with grpc.insecure_channel(request.source_url) as ch:
                stub = rpc.volume_stub(ch)
                for ext in exts:
                    tmp = base + ext + ".copying"
                    try:
                        with open(tmp, "wb") as f:
                            for chunk in stub.CopyFile(
                                pb.CopyFileRequest(
                                    volume_id=request.volume_id,
                                    collection=request.collection,
                                    ext=ext,
                                )
                            ):
                                f.write(chunk.data)
                            f.flush()
                            os.fsync(f.fileno())
                        tmps[ext] = tmp
                    except grpc.RpcError as e:
                        if os.path.exists(tmp):
                            os.unlink(tmp)
                        if ext == ".vif":  # optional sidecar
                            continue
                        raise RuntimeError(
                            f"copy {ext}: {e.details()}"
                        ) from None
            for ext, tmp in tmps.items():
                os.replace(tmp, base + ext)
            tmps.clear()
        except RuntimeError as e:
            return pb.VolumeCommandResponse(error=str(e))
        finally:
            for tmp in tmps.values():
                if os.path.exists(tmp):
                    os.unlink(tmp)
        self.store.mount_volume(request.volume_id, request.collection)
        self.server.notify_new_volume(request.volume_id)
        return pb.VolumeCommandResponse()

    def VolumeUnmount(self, request, context):
        """Release the volume, keep its files (reference
        volume_grpc_admin.go VolumeUnmount)."""
        try:
            self.store.unmount_volume(request.volume_id)
        except NotFoundError as e:
            return pb.VolumeCommandResponse(error=str(e))
        self.server.notify_deleted_volume(request.volume_id)
        return pb.VolumeCommandResponse()

    def VolumeConfigure(self, request, context):
        """Rewrite replica placement in place (reference
        VolumeConfigure); the next heartbeat reports the new value."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            return pb.VolumeCommandResponse(error="volume not found")
        try:
            v.set_replica_placement(request.replication)
        except (ValueError, VolumeError) as e:
            return pb.VolumeCommandResponse(error=str(e))
        self.server.notify_new_volume(request.volume_id)
        return pb.VolumeCommandResponse()

    def VolumeMarkReadonly(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            return pb.VolumeCommandResponse(error="not found")
        v.set_read_only(True)
        return pb.VolumeCommandResponse()

    def VolumeMarkWritable(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            return pb.VolumeCommandResponse(error="not found")
        v.set_read_only(False)
        return pb.VolumeCommandResponse()

    def VacuumVolume(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        ratio = v.garbage_ratio()
        if request.garbage_threshold and ratio < request.garbage_threshold:
            return pb.VacuumResponse(reclaimed_bytes=0, garbage_ratio=ratio)
        reclaimed = v.vacuum()
        return pb.VacuumResponse(reclaimed_bytes=reclaimed, garbage_ratio=ratio)

    # --------------------------------------------------------------- io

    def _grpc_jwt_ok(self, context, vid: int, needle_id: int) -> bool:
        """gRPC writes must not bypass the HTTP JWT gate: when the
        cluster has a key, peer callers attach a self-signed token in
        metadata. context None = internal call from the already-verified
        HTTP handler."""
        if not self.server.jwt_key or context is None:
            return True
        from ..storage.file_id import FileId
        from ..utils.security import JwtError, verify_jwt

        token = ""
        for k, v in context.invocation_metadata():
            if k == "authorization":
                token = v[7:] if v.startswith("Bearer ") else v
        try:
            # needle-scoped tokens carry a cookie we don't know here;
            # accept volume-scoped tokens (what peers sign)
            verify_jwt(self.server.jwt_key, token, str(vid))
            return True
        except JwtError:
            return False

    def WriteNeedle(self, request, context):
        if not self._grpc_jwt_ok(context, request.volume_id, request.needle_id):
            return pb.WriteNeedleResponse(error="unauthorized")
        with M.request_seconds.time(server="volume", op="write"):
            resp = self._write_needle(request)
        M.request_total.inc(
            server="volume", op="write", code="err" if resp.error else "ok"
        )
        return resp

    def _write_needle(self, request):
        n = Needle(
            cookie=request.cookie,
            needle_id=request.needle_id,
            data=request.data,
            flags=request.flags,
        )
        if request.name:
            n.set_name(request.name.encode())
        if request.mime:
            n.set_mime(request.mime.encode())
        try:
            size = self.store.write_needle(request.volume_id, n)
        except (NotFoundError, ReadOnlyError, VolumeError, ValueError, OSError) as e:
            return pb.WriteNeedleResponse(error=str(e))
        if not request.is_replicate:
            err = self.server.replicate_write(request)
            if err:
                return pb.WriteNeedleResponse(error=err)
        return pb.WriteNeedleResponse(size=size)

    def ReadNeedle(self, request, context):
        with M.request_seconds.time(server="volume", op="read"):
            resp = self._read_needle(request)
        M.request_total.inc(
            server="volume", op="read", code="err" if resp.error else "ok"
        )
        return resp

    def _read_needle(self, request):
        try:
            n = self.store.read_needle(
                request.volume_id,
                request.needle_id,
                request.cookie or None,
            )
        except (NotFoundError, ECError) as e:
            return pb.ReadNeedleResponse(error=f"not found: {e}")
        except (CookieMismatch, CrcError, VolumeError, ValueError, OSError) as e:
            return pb.ReadNeedleResponse(error=str(e))
        return pb.ReadNeedleResponse(
            data=n.data,
            name=n.name.decode(errors="replace"),
            mime=n.mime.decode(errors="replace"),
            last_modified=n.last_modified,
        )

    def DeleteNeedle(self, request, context):
        if not self._grpc_jwt_ok(context, request.volume_id, request.needle_id):
            return pb.DeleteNeedleResponse(error="unauthorized")
        try:
            freed = self.store.delete_needle(request.volume_id, request.needle_id)
        except NotFoundError as e:
            return pb.DeleteNeedleResponse(error=str(e))
        except (ECError, VolumeError, ValueError, OSError) as e:
            # a volume mid-conversion/close must yield an error RESPONSE,
            # never an escaped exception that aborts the connection
            return pb.DeleteNeedleResponse(error=f"volume busy: {e}")
        if not request.is_replicate:
            ev = self.store.find_ec_volume(request.volume_id)
            if ev is not None:
                # EC tombstones must reach every shard holder's .ecj
                # (reference ec_volume_delete distribution), or a later
                # decode/serve from another holder resurrects the blob
                err = self.server.replicate_ec_delete(
                    request.volume_id, ev.collection, request.needle_id
                )
                if err:
                    return pb.DeleteNeedleResponse(
                        freed_bytes=freed, error=err
                    )
            else:
                self.server.replicate_delete(request)
        return pb.DeleteNeedleResponse(freed_bytes=freed)

    # ---------------------------------------------------------------- ec

    def VolumeEcShardsGenerate(self, request, context):
        """Reference volume_grpc_erasure_coding.go:45 — wipe stale EC
        artifacts, mark the volume readonly, encode (ecx first), persist
        sidecars."""
        sp = self._rpc_span("rpc.ec_shards_generate", request, context)
        try:
            with trace.activate(sp):
                return self._ec_shards_generate(request, context)
        finally:
            trace.finish(sp)

    def _ec_shards_generate(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        if request.collection and v.collection != request.collection:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, "collection mismatch")
        base = v.dat_path[:-4]
        for i in range(ec_context.MAX_SHARD_COUNT):
            stale = base + f".ec{i:02d}"
            if os.path.exists(stale):
                os.unlink(stale)
        v.set_read_only(True)
        v.flush()
        ctx = ec_context.ECContext(
            request.data_shards or ec_context.DATA_SHARDS,
            request.parity_shards or ec_context.PARITY_SHARDS,
        )
        from ..ec.backend import get_backend

        backend = get_backend(
            request.backend or self.server.store.ec_backend,
            ctx.data_shards,
            ctx.parity_shards,
        )
        backend_name = request.backend or self.server.store.ec_backend
        dat_size = os.path.getsize(base + ".dat")
        from ..ec.encoder import DEFAULT_BATCH

        batch = (request.batch_mb << 20) if request.batch_mb else DEFAULT_BATCH
        with M.request_seconds.time(server="volume", op="ec_encode"):
            vi = ec_encode_volume(
                base, ctx, backend, batch_size=batch,
                scheduler=self.store.ec_scheduler,
            )
        M.ec_ops_total.inc(op="encode", backend=backend_name)
        M.ec_bytes_total.inc(dat_size, op="encode", backend=backend_name)
        return pb.EcShardsGenerateResponse(generation=vi.encode_ts_ns)

    def VolumeEcShardsRebuild(self, request, context):
        sp = self._rpc_span(
            "rpc.ec_shards_rebuild", request, context,
            from_peers=bool(request.from_peers),
        )
        try:
            with trace.activate(sp):
                return self._ec_shards_rebuild(request, context)
        finally:
            trace.finish(sp)

    def _ec_shards_rebuild(self, request, context):
        loc_base = self._ec_base(request.volume_id, request.collection)
        if loc_base is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "ec volume not found")
        if request.from_peers:
            # Cluster-level rebuild: a subset holder (< k local shards)
            # streams sibling shards from peer holders, rebuilds on the
            # local device, and distributes regenerated cluster-lost
            # shards to planned holders (server.peer_fetch_rebuild).
            try:
                out = self.server.peer_fetch_rebuild(
                    request.volume_id,
                    collection=request.collection,
                    backend_name=request.backend,
                )
            except ECError as e:
                context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
            return pb.EcShardsRebuildResponse(
                rebuilt_shard_ids=out["rebuilt"],
                fetched_shard_ids=out["fetched"],
                distributed_shard_ids=out["distributed"],
                repaired_shard_ids=out["repaired"],
            )
        from ..ec.backend import get_backend
        from ..ec.volume_info import VolumeInfo

        vi = VolumeInfo.maybe_load(loc_base + ".vif")
        ctx = (vi.ec_ctx if vi else None) or ec_context.ECContext()
        backend = get_backend(
            request.backend or self.server.store.ec_backend,
            ctx.data_shards,
            ctx.parity_shards,
        )
        # Regenerate absent shards only within this server's legitimate
        # set (mounted + quarantined) PLUS shards the master knows no
        # location for (lost cluster-wide — ec.rebuild's restore-
        # redundancy contract). A shard absent here but alive on a peer
        # is excluded: minting a local copy would create a duplicate
        # the master never placed. Present-but-corrupt shards are
        # always replaced. An unmounted volume (offline repair) or an
        # unreachable master keeps the unrestricted file-level behavior.
        ev = self.store.find_ec_volume(request.volume_id)
        only = None
        if ev is not None:
            try:
                located = self.server._master_client().lookup_ec(
                    request.volume_id, refresh=True
                )
                lost = {
                    sid
                    for sid in range(ctx.total)
                    if not located.get(sid)
                }
            except Exception:
                lost = set(range(ctx.total))  # no topology: old behavior
            only = sorted(set(ev.legitimate_shards()) | lost)
        try:
            with M.request_seconds.time(server="volume", op="ec_rebuild"):
                rebuilt = rebuild_ec_files(
                    loc_base, backend=backend, only_shards=only,
                    scheduler=self.store.ec_scheduler,
                )
        except ECError as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        M.ec_ops_total.inc(
            op="rebuild", backend=request.backend or self.server.store.ec_backend
        )
        # swap a mounted volume's fds onto the regenerated inodes — the
        # pre-rename fds still read the old (possibly corrupt) bytes
        # (quarantined shards re-enter service here too)
        if ev is not None and rebuilt:
            ev.reopen_shards(rebuilt)
        return pb.EcShardsRebuildResponse(rebuilt_shard_ids=rebuilt)

    def VolumeEcShardsCopy(self, request, context):
        """Pull shards (and index files) from a peer.

        Metadata files (.ecx/.ecj/.vif/.ecsum) land FIRST over the
        gRPC CopyFile stream, so the generation fence and the bitrot
        sidecar exist locally before any shard byte moves. Shard files
        then prefer the source's native shard plane
        (ec/net_plane.ShardNetPlane: sendfile egress, generation-fenced
        by the .vif's encode_ts_ns, bytes attributed
        plane=native) with CopyFile as the bit-identical fallback —
        this is the byte path `ec.balance` moves and `ec_migrate`
        hot-volume migrations ride. Every landed shard is verified
        against the local .ecsum sidecar when one covers this
        generation: a mismatch unlinks the file and aborts the copy
        (DATA_LOSS) — a migration can never mount rot."""
        _rid.ensure(trace.metadata_dict(context).get(trace.REQUEST_ID_KEY))
        loc = self.store._pick_location()
        base = Volume.base_file_name(
            loc.directory, request.collection, request.volume_id
        )
        meta_exts = []
        if request.copy_ecx:
            meta_exts.append(".ecx")
        if request.copy_ecj:
            meta_exts.append(".ecj")
        if request.copy_vif:
            meta_exts.append(".vif")
        if request.copy_ecsum:
            meta_exts.append(".ecsum")
        with grpc.insecure_channel(request.source_url) as ch:
            stub = rpc.volume_stub(ch)

            def copy_file(ext: str) -> None:
                tmp = base + ext + ".copying"
                try:
                    with open(tmp, "wb") as f:
                        for chunk in stub.CopyFile(
                            pb.CopyFileRequest(
                                volume_id=request.volume_id,
                                collection=request.collection,
                                ext=ext,
                            ),
                            metadata=trace.grpc_metadata(),
                        ):
                            f.write(chunk.data)
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(tmp, base + ext)
                except grpc.RpcError as e:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
                    if ext == ".ecj":  # journal may legitimately not exist
                        return
                    context.abort(
                        grpc.StatusCode.UNAVAILABLE,
                        f"copy {ext}: {e.details()}",
                    )

            for ext in meta_exts:
                copy_file(ext)
            # Generation fence + sidecar, from whatever .vif/.ecsum is
            # now local (just copied, or already here from an earlier
            # shard of this volume).
            generation = 0
            vi = VolumeInfo.maybe_load(base + ".vif")
            if vi is not None:
                generation = vi.encode_ts_ns
            prot = None
            try:
                from ..ec.bitrot import BitrotProtection

                prot = BitrotProtection.load(base + ".ecsum")
                if generation and prot.generation not in (0, generation):
                    prot = None  # stale sidecar: no ground truth
            except Exception:  # absent/unreadable: verification off
                prot = None
            for sid in request.shard_ids:
                ext = f".ec{sid:02d}"
                if not self._copy_shard_native(
                    request, base, ext, sid, generation
                ):
                    copy_file(ext)
                if prot is not None and 0 <= sid < len(prot.shard_crcs):
                    bad = prot.verify_shard_file(
                        base + ext, sid, stop_early=True
                    )
                    if bad:
                        os.unlink(base + ext)
                        context.abort(
                            grpc.StatusCode.DATA_LOSS,
                            f"shard {sid} from {request.source_url} "
                            f"fails .ecsum verification; copy refused",
                        )
        return pb.EcShardsCopyResponse()

    def _copy_shard_native(
        self, request, base: str, ext: str, sid: int, generation: int
    ) -> bool:
        """Try to land one shard file over the source's shard net
        plane (sendfile -> pooled buffer -> local file, atomic
        replace). False = caller takes the gRPC CopyFile path (plane
        disabled, armed faults, peer without a sidecar, refusal)."""
        from .. import faults
        from ..ec import native_io
        from ..ec import net_plane as _netp

        if not native_io.enabled() or faults.active():
            return False
        tmp = base + ext + ".copying"
        try:
            client = self.server._net_plane_client()
            with open(tmp, "wb") as f:
                n = client.fetch_shard_to_file(
                    _netp.net_addr(request.source_url),
                    request.volume_id, sid, generation, f,
                )
                f.flush()
                os.fsync(f.fileno())
            if n <= 0:
                os.unlink(tmp)
                return False
            os.replace(tmp, base + ext)
            return True
        except (_netp.NetPlaneError, _netp.NetPlaneUnavailable, OSError):
            try:
                if os.path.exists(tmp):
                    os.unlink(tmp)
            except OSError:
                pass
            return False

    def VolumeEcShardsDelete(self, request, context):
        for loc in self.store.locations:
            base = Volume.base_file_name(
                loc.directory, request.collection, request.volume_id
            )
            for sid in request.shard_ids:
                p = base + f".ec{sid:02d}"
                if os.path.exists(p):
                    os.unlink(p)
            # drop index files when no shards remain anywhere local
            if not any(
                os.path.exists(base + f".ec{i:02d}")
                for i in range(ec_context.MAX_SHARD_COUNT)
            ):
                for ext in (".ecx", ".ecj", ".ecsum", ".heat"):
                    if os.path.exists(base + ext):
                        os.unlink(base + ext)
        self.server.notify_deleted_ec_shards(
            request.volume_id, request.collection, list(request.shard_ids)
        )
        return pb.EcShardsDeleteResponse()

    def VolumeEcShardsMount(self, request, context):
        try:
            ev = self.store.mount_ec_volume(request.volume_id, request.collection)
        except NotFoundError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        self.server.notify_new_ec_shards(request.volume_id, request.collection)
        return pb.EcShardsMountResponse()

    def VolumeEcShardsUnmount(self, request, context):
        self.store.unmount_ec_shards(request.volume_id, list(request.shard_ids))
        return pb.EcShardsUnmountResponse()

    def VolumeEcShardRead(self, request, context):
        from .. import faults

        # Streaming RPC: the span covers the whole response stream (the
        # "stream" stage includes time blocked on a slow consumer) and,
        # because the trace id arrives in metadata, a peer-fetch
        # rebuild's every shard-read stream lands in the DISPATCHER's
        # trace — one id from master task to this peer.
        sp = self._rpc_span(
            "rpc.ec_shard_read", request, context,
            shard=request.shard_id, offset=request.offset,
            size=request.size,
        )
        t0 = time.perf_counter()
        try:
            ev = self.store.find_ec_volume(request.volume_id)
            if ev is None:
                context.abort(grpc.StatusCode.NOT_FOUND, "ec volume not mounted")
            if request.generation and ev.encode_ts_ns != request.generation:
                # generation fence (reference store_ec.go:627)
                context.abort(grpc.StatusCode.FAILED_PRECONDITION, "stale generation")
            fd = ev.shard_fds.get(request.shard_id)
            if fd is None:
                context.abort(grpc.StatusCode.NOT_FOUND, "shard not local")
            try:
                # Named point for peer-read chaos: a raised IOError aborts
                # the stream (client falls back to other peers/recovery); a
                # mutate tears or corrupts the streamed bytes, which the
                # CLIENT must catch (short-read check / needle CRC /
                # sidecar-verified reconstruction) — never serve silently.
                faults.fire(
                    "server.ec_shard_read",
                    volume=request.volume_id, shard=request.shard_id,
                )
            except IOError as e:
                context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
            remaining = request.size
            off = request.offset
            while remaining > 0:
                # The Python-plane stream: every chunk is materialized
                # as bytes for the protobuf message (counted against
                # bytes_copied_per_byte_served). The native twin of
                # this loop is ec/net_plane.ShardNetPlane, which
                # sendfile(2)s the same fd range with zero Python-side
                # byte handling — clients prefer it and fall back here.
                chunk = os.pread(fd, min(_EC_STREAM_CHUNK, remaining), off)
                if not chunk:
                    break
                orig_len = len(chunk)
                M.net_bytes_copied_total.inc(orig_len, plane="python", direction="read")
                chunk = faults.mutate(
                    "server.ec_shard_read", chunk,
                    volume=request.volume_id, shard=request.shard_id, offset=off,
                )
                if chunk:
                    yield pb.EcShardReadChunk(data=chunk)
                    M.net_bytes_sent_total.inc(len(chunk), plane="python", direction="read")
                if len(chunk) < orig_len:
                    break  # torn stream: client sees a short read
                off += orig_len
                remaining -= orig_len
        finally:
            trace.add_stage(sp, "stream", time.perf_counter() - t0)
            trace.finish(sp)

    def VolumeEcBlobDelete(self, request, context):
        # a mutation: on keyed clusters it needs the same peer token the
        # gRPC write path demands (fan-out attaches it)
        if not self._grpc_jwt_ok(context, request.volume_id, request.needle_id):
            context.abort(grpc.StatusCode.PERMISSION_DENIED, "unauthorized")
        ev = self.store.find_ec_volume(request.volume_id)
        if ev is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "ec volume not mounted")
        ev.delete_needle(request.needle_id)
        return pb.EcBlobDeleteResponse()

    def VolumeEcShardsToVolume(self, request, context):
        sp = self._rpc_span("rpc.ec_shards_to_volume", request, context)
        try:
            with trace.activate(sp):
                return self._ec_shards_to_volume(request, context)
        finally:
            trace.finish(sp)

    def _ec_shards_to_volume(self, request, context):
        base = self._ec_base(request.volume_id, request.collection)
        if base is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "ec volume not found")
        self.store.unmount_ec_volume(request.volume_id)
        try:
            ec_decode_volume(base, scheduler=self.store.ec_scheduler)
        except ECError as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        # register the decoded normal volume
        self.store.mount_volume(request.volume_id, request.collection)
        self.server.notify_new_volume(request.volume_id)
        return pb.EcShardsToVolumeResponse()

    def CopyFile(self, request, context):
        """Stream a volume/EC file, optionally from start_offset — the
        tail form backs incremental remote backup (reference
        VolumeTailSender / VolumeIncrementalCopy)."""
        base = self._ec_base(request.volume_id, request.collection, require=False)
        path = (base or "") + request.ext
        if base is None or not os.path.exists(path):
            context.abort(grpc.StatusCode.NOT_FOUND, f"no {request.ext}")
        v = self.store.find_volume(request.volume_id)
        if v is not None and request.ext in (".dat", ".idx"):
            v.flush()  # a tail read must see every acknowledged write
        stop = request.stop_offset or os.path.getsize(path)
        with open(path, "rb") as f:
            sent = request.start_offset
            f.seek(sent)
            while sent < stop:
                chunk = f.read(min(_EC_STREAM_CHUNK, stop - sent))
                if not chunk:
                    break
                yield pb.CopyFileChunk(data=chunk)
                sent += len(chunk)

    def VolumeTierUpload(self, request, context):
        """Move a sealed volume's .dat to the cold tier (reference
        volume_grpc_tier_upload.go); .idx stays local so lookups never
        touch the backend."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            return pb.TierResponse(error="volume not found")
        try:
            moved = v.tier_upload(request.dest_url, keep_local=request.keep_local)
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            return pb.TierResponse(error=str(e))
        return pb.TierResponse(moved_bytes=moved)

    def VolumeTierDownload(self, request, context):
        """Bring a cold-tiered .dat back onto local disk (reference
        volume_grpc_tier_download.go)."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            return pb.TierResponse(error="volume not found")
        try:
            moved = v.tier_download(delete_remote=request.delete_remote)
        except Exception as e:  # noqa: BLE001
            return pb.TierResponse(error=str(e))
        return pb.TierResponse(moved_bytes=moved)

    # ---------------------------------------- tail / incremental sync
    # Reference: weed/server/volume_grpc_tail.go (VolumeTailSender /
    # VolumeTailReceiver) and weed/storage/volume_backup.go
    # (VolumeIncrementalCopy) — replica catch-up after downtime pulls
    # only the records appended since the replica's own appendAtNs.

    _TAIL_POLL_S = 0.25  # follow-loop poll (ref uses 2s; tests want fast)

    def VolumeTailSender(self, request, context):
        """Stream needle records appended after since_ns; keep following
        until no new appends for idle_timeout_seconds (0 = forever)."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"volume {request.volume_id} not found",
            )
        try:
            v._require_v3()  # v2 has no appendAtNs: refuse, never
            #                  stream garbage-timestamped silence
            # position once (idx binary search); every later poll just
            # compares the cached .dat position against the append end
            # — O(1) while idle, no idx re-reads
            pos = v._walk_start_for(request.since_ns)
        except VolumeError as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        draining = float(request.idle_timeout_seconds)
        while True:
            end = v._append_end()
            progressed = False
            if pos < end:
                for _n, raw, ts in v.scan_records_between(pos, end):
                    if ts <= request.since_ns:
                        continue  # first segment may start at an older put
                    header, rest = raw[:16], raw[16:]
                    first = True
                    for i in range(0, max(len(rest), 1), _EC_STREAM_CHUNK):
                        yield pb.VolumeTailChunk(
                            needle_header=header if first else b"",
                            needle_body=rest[i : i + _EC_STREAM_CHUNK],
                            version=v.version,
                        )
                        first = False
                    progressed = True
                pos = end
            # heartbeat: flushes the client's pending needle and keeps
            # the connection provably alive while idle
            yield pb.VolumeTailChunk(is_last_chunk=True, version=v.version)
            if request.idle_timeout_seconds == 0:
                time.sleep(self._TAIL_POLL_S)
                continue
            if progressed:
                draining = float(request.idle_timeout_seconds)
            else:
                draining -= self._TAIL_POLL_S
                if draining <= 0:
                    return
            time.sleep(self._TAIL_POLL_S)

    def VolumeTailReceiver(self, request, context):
        """Pull the tail FROM a source server into the local replica
        (server-side of `volume.sync`). since_ns=0 derives the resume
        point from the local volume's own last appendAtNs."""
        from ..client.volume_sync import tail_volume

        v = self.store.find_volume(request.volume_id)
        if v is None:
            return pb.VolumeTailReceiverResponse(
                error=f"volume {request.volume_id} not found"
            )
        since = request.since_ns or v.last_append_at_ns()
        count = 0
        try:
            for n in tail_volume(
                request.source_volume_server,
                request.volume_id,
                since,
                request.idle_timeout_seconds or 3,
            ):
                if n.is_tombstone or (
                    not n.data and not n.flags and n.cookie == 0
                ):
                    # propagate the SOURCE's tombstone bytes verbatim.
                    # The 0x40 flag marks new-format tombstones; the
                    # legacy marker this codebase ever wrote is exactly
                    # Needle(cookie=0, data=b'') — an empty-body put
                    # with a NONZERO cookie is legitimate data and must
                    # replicate as a put, not a delete.
                    v.delete_needle(n.needle_id, tombstone=n)
                else:
                    v.write_needle(n)  # append_at_ns preserved -> same bytes
                count += 1
        except Exception as e:  # noqa: BLE001
            return pb.VolumeTailReceiverResponse(received=count, error=str(e))
        return pb.VolumeTailReceiverResponse(received=count)

    def VolumeIncrementalCopy(self, request, context):
        """Raw .dat bytes from the first record newer than since_ns to
        the current append point. First chunk carries start_offset so a
        byte-prefix follower (weed backup analog) can verify alignment
        before appending."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"volume {request.volume_id} not found",
            )
        try:
            off = v.offset_after_ns(request.since_ns)
        except VolumeError as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        end = v._append_end()
        if off >= end:
            yield pb.VolumeIncrementalCopyChunk(
                has_start=True, start_offset=end
            )
            return
        first = True
        with open(v.dat_path, "rb") as f:
            f.seek(off)
            sent = off
            while sent < end:
                data = f.read(min(_EC_STREAM_CHUNK, end - sent))
                if not data:
                    break
                yield pb.VolumeIncrementalCopyChunk(
                    file_content=data,
                    start_offset=off if first else 0,
                    has_start=first,
                )
                first = False
                sent += len(data)

    def ReadVolumeFileStatus(self, request, context):
        """Size/revision/version/lastAppendAtNs of a volume's files
        (reference volume_grpc_admin.go ReadVolumeFileStatus) — the
        handshake half of incremental backup."""
        v = self.store.find_volume(request.volume_id)
        if v is None:
            return pb.VolumeFileStatusResponse(error="volume not found")
        v.flush()
        try:
            last_ns = v.last_append_at_ns()
        except VolumeError:
            last_ns = 0  # v2 volume: no appendAtNs footer
        return pb.VolumeFileStatusResponse(
            dat_size=os.path.getsize(v.dat_path),
            idx_size=os.path.getsize(v.idx_path),
            compaction_revision=v.super_block.compaction_revision,
            version=v.version,
            last_append_at_ns=last_ns,
            collection=v.collection,
        )

    def ScrubVolume(self, request, context):
        """CRC-verify every live needle (reference volume_grpc_scrub.go).
        Reads go through the lock-free scan of the sealed portion; the
        volume stays online."""
        # task RPC: adopt the dispatcher's request id so this holder's
        # scrub log lines correlate with the fleet task that drove them
        _rid.ensure(trace.metadata_dict(context).get(trace.REQUEST_ID_KEY))
        v = self.store.find_volume(request.volume_id)
        if v is None:
            return pb.ScrubResponse(error="volume not found")
        v.flush()
        checked = 0
        bad: list[int] = []
        try:  # native mmap scanner (~3x the Python walk)
            from ..utils import native

            ids, offs, sizes, ok = native.scan_dat(v.dat_path)
            # iterate the arrays directly: no boxed-list copies of a
            # potentially many-million-record volume
            records = (
                (int(a), int(b), int(c), bool(d))
                for a, b, c, d in zip(ids, offs, sizes, ok)
            )
        except Exception:  # .so missing AND unbuildable included
            records = None
        if records is None:
            from ..storage.volume_scan import scan_volume_file

            _, items = scan_volume_file(v.dat_path)
            records = (
                (i.needle.needle_id, i.offset // 8, i.body_size, i.crc_ok)
                for i in items
            )
        for nid, stored_off, body_size, crc_ok in records:
            if body_size <= 0:
                continue
            nv = v.needle_map.get(nid)
            if nv is None or nv.is_deleted:
                continue  # dead record, vacuum's problem
            if nv.offset != stored_off:
                continue  # superseded copy; the live one is elsewhere
            checked += 1
            if not crc_ok:
                bad.append(nid)
        return pb.ScrubResponse(checked=checked, bad_needles=bad)

    def ScrubEcVolume(self, request, context):
        """Verify local shards against the .ecsum bitrot sidecar
        (reference ec_volume_scrub.go / store_ec_scrub.go)."""
        sp = self._rpc_span("rpc.scrub_ec_volume", request, context)
        try:
            with trace.activate(sp):
                return self._scrub_ec_volume(request, context)
        finally:
            trace.finish(sp)

    def _scrub_ec_volume(self, request, context):
        base = self._ec_base(request.volume_id, request.collection)
        if base is None:
            return pb.ScrubResponse(error="ec volume not found")
        from ..ec.bitrot import BitrotError, BitrotProtection

        if not os.path.exists(base + ".ecsum"):
            return pb.ScrubResponse(error="no bitrot sidecar")
        try:
            prot = BitrotProtection.load(base + ".ecsum")
        except BitrotError as e:
            return pb.ScrubResponse(error=f"sidecar unreadable: {e}")
        # Crash recovery BEFORE verification: replay (or roll back) any
        # pending <shard>.repair journal so this pass judges fully-old
        # or fully-new bytes, never a half-applied leaf patch — the
        # fleet scrub's recovery hook for holders with no local daemon.
        from ..ec.repair_journal import (
            patched_byte_ranges,
            recover_volume_journals,
        )

        rec = recover_volume_journals(base, prot.ctx, prot)
        journal_recovered = len(rec["replayed"]) + len(rec["rolled_back"])
        if rec["replayed"]:
            ev = self.store.find_ec_volume(request.volume_id)
            if ev is not None and prot.has_leaves:
                # in-place patches keep the inode: no fd swap, but any
                # cached reconstruction over the patched bytes is stale
                for sid, leaves in rec["replayed"].items():
                    ev.invalidate_shard_ranges(
                        sid, patched_byte_ranges(prot, sid, leaves)
                    )
        checked: list[int] = []
        bad: list[int] = []
        for i in range(prot.ctx.total):
            p = base + prot.ctx.to_ext(i)
            if not os.path.exists(p):
                continue
            checked.append(i)
            try:
                if prot.verify_shard_file(p, i):
                    bad.append(i)
            except OSError:
                bad.append(i)
        # checked_shards lets the shell do a real per-sid set difference
        # against the master's advertised placement; the bare count can
        # be masked by non-advertised local shard files. Quarantined
        # shards (renamed .bad, unmounted, so never "advertised") ride
        # along — the fleet scrub loop needs them to spot a holder that
        # is quarantined-but-unrebuildable and route a peer-fetch
        # rebuild at it. A quarantine whose canonical shard is back on
        # disk and verified good THIS pass is healed, not hurt: the
        # .bad file stays for forensics (bad_retention_s ages it out),
        # but reporting it would have the fleet loop dispatch a no-op
        # rebuild at this holder every scrub period forever.
        healed = set(checked) - set(bad)
        quarantined = [
            i
            for i in range(prot.ctx.total)
            if i not in healed
            and os.path.exists(
                base + prot.ctx.to_ext(i) + ec_context.QUARANTINE_SUFFIX
            )
        ]
        return pb.ScrubResponse(
            checked=len(checked),
            bad_shards=bad,
            checked_shards=checked,
            quarantined_shards=quarantined,
            repair_journal_recovered=journal_recovered,
        )

    def VolumeServerStatus(self, request, context):
        st = self.store.status()
        return pb.VolumeServerStatusResponse(
            volumes=[
                pb.VolumeInfoMsg(
                    id=v["id"],
                    collection=v["collection"],
                    size=v["size"],
                    file_count=v["file_count"],
                    deleted_count=v["deleted_count"],
                    deleted_bytes=v["deleted_bytes"],
                    read_only=v["read_only"],
                    replica_placement=v["replica_placement"],
                    version=v["version"],
                    ttl=v.get("ttl", ""),
                    disk_type=v.get("disk_type", "hdd"),
                )
                for v in st["volumes"]
            ],
            ec_shards=[
                pb.EcShardInfoMsg(
                    id=e["id"],
                    collection=e["collection"],
                    shard_bits=_shard_bits(e["shards"]),
                    shard_size=e["shard_size"],
                    data_shards=e["data_shards"],
                    parity_shards=e["parity_shards"],
                    generation=e["generation"],
                )
                for e in st["ec_volumes"]
            ],
        )

    # ------------------------------------------------------------ helpers

    def _ec_base(self, vid: int, collection: str, require: bool = True):
        """Directory base for a volume's EC artifacts on this server."""
        for loc in self.store.locations:
            base = Volume.base_file_name(loc.directory, collection, vid)
            if (
                os.path.exists(base + ".ecx")
                or os.path.exists(base + ".dat")
                or any(
                    os.path.exists(base + f".ec{i:02d}")
                    for i in range(ec_context.MAX_SHARD_COUNT)
                )
            ):
                return base
        return None


class VolumeServer:
    def __init__(
        self,
        directories: list[str],
        master: str = "localhost:9333",
        ip: str = "localhost",
        port: int = 8080,
        grpc_port: int = 0,
        max_volume_count: int = 8,
        ec_backend: str = "auto",
        data_center: str = "",
        rack: str = "",
        jwt_key: str = "",
        needle_map_kind: str = "memory",
        tls=None,
        ec_scrub_interval: float = 0.0,
        ec_scrub_bytes_per_sec: float = 64 << 20,
        ec_scrub_bad_retention: float = 0.0,
        ec_interval_cache_mb: int | None = None,
        ec_device_queue: bool = True,
        ec_queue_window: int | None = None,
        ec_queue_recovery_share: float | None = None,
        ec_queue_scrub_share: float | None = None,
        ec_placement: str = "auto",
        ec_trace: bool = False,
        ec_trace_ring: int = 0,
        ec_slow_op_s: float = 0.0,
        http_workers: int = 32,
        http_queue: int = 128,
    ):
        # Shared per-chip device-queue scheduler (ec/device_queue.py):
        # every EC producer on this server submits priority-tagged batch
        # streams (foreground encode/degraded reads > recovery rebuild/
        # decode > scrub) instead of owning a private device window.
        # `ec_device_queue=False` restores the PR 3 per-call-site
        # windows; the share knobs set each background class's minimum
        # fraction of admitted COST (output rows x bytes) under
        # contention. `ec_placement` picks the multi-chip stream routing
        # (ec/chip_pool.py): "auto" places whole streams on the
        # least-loaded chip (mesh only for a lone wide encode), "chip"
        # always places, "mesh" restores the PR 4 column-sliced shape.
        # The whole config lives in a PER-STORE QueueScope (threaded to
        # every producer below, like the interval cache) instead of the
        # old process-wide configure(): two servers embedded in one
        # process no longer clobber each other's scheduler knobs.
        shares = {}
        if ec_queue_recovery_share is not None:
            shares["recovery"] = ec_queue_recovery_share
        if ec_queue_scrub_share is not None:
            shares["scrub"] = ec_queue_scrub_share
        # Flight recorder (utils/trace.py): the tracer/ring/slow-op
        # threshold are process-wide (spans cross server objects in
        # embedded tests), so arming is strictly OPT-IN here — a second
        # server constructed with the defaults must not disarm the
        # first's recorder.
        if ec_trace or ec_trace_ring > 0 or ec_slow_op_s > 0:
            trace.configure(
                # slow-op logging needs spans recorded, so it arms too
                enabled=True if (ec_trace or ec_slow_op_s > 0) else None,
                ring_size=ec_trace_ring if ec_trace_ring > 0 else None,
                slow_op_s=ec_slow_op_s if ec_slow_op_s > 0 else None,
            )
        self.jwt_key = jwt_key
        self.ip = ip
        self.port = port
        self.grpc_port = grpc_port or (port + 10000)
        # `master` may be a comma-separated HA group; heartbeats follow
        # the raft leader via HeartbeatResponse.leader redirects
        self.master_addrs = [m.strip() for m in master.split(",") if m.strip()]
        self.master_addr = master
        self.master_grpc_addr = self._master_grpc(self.master_addrs[0])
        self.max_volume_count = max_volume_count
        self.data_center = data_center
        self.rack = rack
        self._mc = None
        self._mc_lock = threading.Lock()
        self._np_client = None
        self._peer_channels: dict[str, grpc.Channel] = {}
        # vid -> Lock: serializes peer-fetch rebuild per volume (the
        # staging dir is per-volume; concurrent runs would wipe each
        # other). dict.setdefault is atomic under the GIL.
        self._peer_rebuild_busy: dict[int, threading.Lock] = {}
        # Learned from HeartbeatResponse: the master's per-volume size
        # limit, the denominator for capacity-aware shard placement
        # (0 = not yet known -> slot-only planning).
        self.volume_size_limit = 0
        self.store = Store(
            directories,
            ip=ip,
            port=port,
            ec_backend=ec_backend,
            ec_remote_reader_factory=self._remote_reader_factory,
            needle_map_kind=needle_map_kind,
            # degraded-read reconstructed-interval cache budget shared
            # across ALL EC volumes on this server (one ChunkCache at
            # the Store); None keeps the store default, 0 disables
            ec_interval_cache_bytes=(
                None if ec_interval_cache_mb is None
                else int(ec_interval_cache_mb) << 20
            ),
            ec_device_queue=ec_device_queue,
            ec_queue_window=ec_queue_window,
            ec_queue_shares=shares,
            ec_placement=ec_placement,
        )
        self.service = VolumeService(self)

        # bulk-read fast path: a native Unix-socket sendfile server per
        # disk location (the RDMA sidecar analog, SURVEY §2.10); local
        # clients resolve ?locate=true then pull bytes kernel-to-kernel
        self.fastread_sockets: dict[str, str] = {}
        try:
            from ..utils.fastread import start_server as _fr_start

            for loc in self.store.locations:
                sock = os.path.join(loc.directory, ".fastread.sock")
                _fr_start(sock, loc.directory)
                self.fastread_sockets[
                    os.path.abspath(loc.directory)
                ] = sock
        except Exception as e:  # native toolchain absent: HTTP only
            logger("volume").warning("fastread sidecar disabled: %s", e)

        # Native shard byte plane (ec/net_plane.py): a TCP sidecar on
        # grpc_port + 10000 serving EC shard ranges with sendfile
        # egress — peers derive the address from the holder map's gRPC
        # address and fall back to the VolumeEcShardRead stream when
        # the port refuses. Runs even without the native .so (Python
        # egress), so the wire protocol is capability-stable.
        self.net_plane = None
        try:
            from ..ec import net_plane as _netp

            self.net_plane = _netp.ShardNetPlane(
                ip, _netp.derive_port(self.grpc_port),
                self._net_plane_resolve,
                server_label=f"{ip}:{port}",
                resolve_needle=self._net_plane_resolve_needle,
                resolve_write=self._net_plane_resolve_write,
                resolve_blob=self._net_plane_resolve_blob,
            )
        except Exception as e:  # port collision etc: gRPC-only peer
            logger("volume").warning("shard net plane disabled: %s", e)

        self._grpc = grpc.server(futures.ThreadPoolExecutor(max_workers=32))
        rpc.add_service(self._grpc, rpc.VOLUME_SERVICE, self.service)
        self._grpc.add_insecure_port(f"{ip}:{self.grpc_port}")
        # Bounded worker-pool HTTP data plane (utils/http_pool.py):
        # `http_workers` request workers + an `http_queue`-deep
        # connection budget; saturation answers an explicit 503 +
        # Retry-After instead of spawning unbounded threads.
        # `http_workers=0` (or TLS) restores ThreadingHTTPServer.
        from ..utils.http_pool import build_http_server

        self._http = build_http_server(
            (ip, port),
            self._handler_class(),
            server_kind="volume",
            workers=http_workers,
            accept_queue=http_queue,
            tls=tls,
            reject_body=lambda: (
                "application/json",
                b'{"error": "volume server saturated: worker pool and '
                b'accept queue are full"}',
            ),
        )
        self.tls = tls
        if tls is not None:
            tls.wrap_server(self._http)
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, daemon=True
        )
        self._hb_queue: "queue.Queue[pb.Heartbeat]" = queue.Queue()
        self._hb_stop = threading.Event()
        self._hb_thread = threading.Thread(target=self._heartbeat_loop, daemon=True)

        # Background EC scrub/self-heal loop (ec/scrub.py). Off by
        # default (interval 0): enabling it is an operator decision —
        # with it off there is zero new background I/O or behavior.
        self.scrub_daemon = None
        if ec_scrub_interval > 0:
            from ..ec.scrub import ScrubDaemon

            self.scrub_daemon = ScrubDaemon(
                self.store,
                interval=ec_scrub_interval,
                bytes_per_sec=ec_scrub_bytes_per_sec,
                # 0 = keep quarantined .bad files forever (default)
                bad_retention_s=ec_scrub_bad_retention or None,
            )

    @staticmethod
    def _master_grpc(master: str) -> str:
        host, _, port = master.partition(":")
        return f"{host}:{int(port) + 10000}"

    def _net_plane_resolve(self, vid: int, sid: int, generation: int):
        """Shard fd + size for the native byte plane — the same checks
        (mounted, generation fence, shard local) as the gRPC servicer,
        refusals surfacing as protocol error messages."""
        from ..ec.net_plane import NetPlaneError

        ev = self.store.find_ec_volume(vid)
        if ev is None:
            raise NetPlaneError("ec volume not mounted")
        if generation and ev.encode_ts_ns != generation:
            raise NetPlaneError("stale generation")
        fd = ev.shard_fds.get(sid)
        if fd is None:
            raise NetPlaneError("shard not local")
        return fd, os.fstat(fd).st_size

    def _net_plane_resolve_needle(self, vid: int, nid: int, cookie: int):
        """Needle payload location for the net plane's chunk-read
        opcode (ISSUE 13) — the same control-plane checks as
        ``?locate=true`` (replicated volumes only; TTL'd/tiered/EC
        volumes refuse so those reads keep the locked, validated HTTP
        path). The fd is opened per request against the CURRENT .dat
        path — a vacuum commit mid-flight surfaces as the client's CRC
        mismatch, exactly like the fastread sidecar."""
        from ..ec.net_plane import NetPlaneError, NetPlaneVolumeRefusal

        vol = self.store.find_volume(vid)
        if vol is None:
            # EC or not mounted here: no needle on this volume will
            # ever serve — status 2 lets clients negative-cache the vid
            raise NetPlaneVolumeRefusal("volume not here (or EC)")
        try:
            path, off, size, crc = vol.locate_payload(nid, cookie)
        except VolumeError as e:
            # TTL'd/tiered/broken: volume-level, clients stop probing
            raise NetPlaneVolumeRefusal(str(e)) from None
        except Exception as e:
            # needle-level (not found, cookie mismatch): other needles
            # on the volume may still serve
            raise NetPlaneError(str(e)) from None
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError as e:
            raise NetPlaneError(str(e)) from None
        return fd, off, size, crc, True

    def _net_plane_resolve_write(
        self, vid: int, nid: int, cookie: int, data: bytes, md: dict
    ) -> tuple[int, int]:
        """Land one needle for the net plane's write opcode (ISSUE 18)
        — the exact Needle construction as the gRPC ``WriteNeedle``
        servicer so a plane write and a gRPC/HTTP write produce
        bit-identical records. JWT: keyed clusters require a
        volume-scoped token in ``x-sw-w-jwt`` (the same tokens peers
        sign for gRPC replication). Replica fan-out runs here unless
        the client marked the write ``x-sw-w-replicate: 0`` (it IS a
        replication leg)."""
        from ..ec.net_plane import (
            NetPlaneError,
            NetPlaneVolumeRefusal,
            _unb64,
        )

        if self.jwt_key:
            from ..utils.security import JwtError, verify_jwt

            try:
                # same scope rule as the HTTP gate: fid-scoped assign
                # tokens and volume-scoped peer tokens both pass
                verify_jwt(
                    self.jwt_key,
                    md.get("x-sw-w-jwt", ""),
                    str(FileId(vid, nid, cookie)),
                )
            except JwtError:
                raise NetPlaneError("unauthorized") from None
        try:
            flags = int(md.get("x-sw-w-flags", "0") or "0")
        except ValueError:
            flags = 0
        n = Needle(cookie=cookie, needle_id=nid, data=data, flags=flags)
        name = _unb64(md.get("x-sw-w-name", ""))
        if name:
            n.set_name(name)
        mime = _unb64(md.get("x-sw-w-mime", ""))
        if mime:
            n.set_mime(mime)
        fsync = True if md.get("x-sw-w-fsync") == "1" else None
        with M.request_seconds.time(server="volume", op="write"):
            try:
                size = self.store.write_needle(vid, n, fsync=fsync)
            except NotFoundError as e:
                # volume not mounted here: no needle will ever land —
                # status 2 lets clients negative-cache the vid
                raise NetPlaneVolumeRefusal(str(e)) from None
            except (ReadOnlyError, VolumeError, ValueError, OSError) as e:
                raise NetPlaneError(str(e)) from None
        M.request_total.inc(server="volume", op="write", code="ok")
        if md.get("x-sw-w-replicate") != "0":
            req = pb.WriteNeedleRequest(
                volume_id=vid,
                needle_id=nid,
                cookie=cookie,
                data=data,
                flags=flags,
                name=name.decode(errors="replace") if name else "",
                mime=mime.decode(errors="replace") if mime else "",
            )
            err = self.replicate_write(req)
            if err:
                raise NetPlaneError(f"replication: {err}")
        return size, n.checksum

    def _blob_root(self) -> str:
        root = os.environ.get("SEAWEED_EC_STREAM_BLOB_ROOT", "")
        if not root:
            root = os.path.join(
                self.store.locations[0].directory, "stream_shards"
            )
        return root

    def _net_plane_resolve_blob(self, path: str, op: str, md: dict):
        """Remote stream-shard blob landing for kind=blob writes — the
        transport behind ``net:`` durable-parity remote roots. Paths
        are confined to the blob root (env
        ``SEAWEED_EC_STREAM_BLOB_ROOT``, default
        ``<dir0>/stream_shards``); a path that escapes refuses. Returns
        an fd the plane pwrites+closes, or None when the op was handled
        here (unlink)."""
        from ..ec.net_plane import NetPlaneError

        if self.jwt_key:
            from ..utils.security import JwtError, verify_jwt

            try:
                verify_jwt(self.jwt_key, md.get("x-sw-w-jwt", ""), "blob")
            except JwtError:
                raise NetPlaneError("unauthorized") from None
        root = os.path.realpath(self._blob_root())
        full = os.path.realpath(os.path.join(root, path))
        if full != root and not full.startswith(root + os.sep):
            raise NetPlaneError("blob path escapes stream root")
        if op == "unlink":
            try:
                os.unlink(full)
            except FileNotFoundError:
                pass
            except OSError as e:
                raise NetPlaneError(str(e)) from None
            return None
        try:
            os.makedirs(os.path.dirname(full), exist_ok=True)
            return os.open(full, os.O_CREAT | os.O_RDWR, 0o644)
        except OSError as e:
            raise NetPlaneError(str(e)) from None

    # ----------------------------------------------------- remote shards

    def _master_client(self):
        """Lazy cached MasterClient (vid + EC lookup caches, persistent
        channel) — one per server, shared by all EC volumes."""
        with self._mc_lock:
            if self._mc is None:
                from ..client.master_client import MasterClient

                self._mc = MasterClient(self.master_addr)
            return self._mc

    def _net_plane_client(self):
        """Lazy shared NetPlaneClient for pull-side shard copies
        (VolumeEcShardsCopy / ec_migrate): pooled connections to peer
        sidecars, no-plane refusals memoized with TTL."""
        with self._mc_lock:
            if self._np_client is None:
                from ..ec.net_plane import NetPlaneClient

                self._np_client = NetPlaneClient()
            return self._np_client

    def _cluster_ec_telemetry(self) -> dict:
        """Heartbeat-learned per-node device telemetry from the
        master's /cluster/status (`EcTelemetry`: node_id -> chips/
        breakers/stage EWMAs) — the LIVE signal shard placement scores
        beside slots and disk headroom. Best-effort: any failure
        returns {} and planning degrades to the static scoring."""
        try:
            import requests as _requests

            mc = self._master_client()
            addr = getattr(mc, "_leader", "") or getattr(
                mc, "http_addr", ""
            )
            if not addr:
                return {}
            r = _requests.get(
                f"http://{addr}/cluster/status", timeout=2
            )
            r.raise_for_status()
            tele = r.json().get("EcTelemetry")
            return tele if isinstance(tele, dict) else {}
        except Exception:  # noqa: BLE001 — telemetry is advisory
            return {}

    def _peer_stub(self, peer: str):
        with self._mc_lock:
            ch = self._peer_channels.get(peer)
            if ch is None:
                ch = grpc.insecure_channel(peer)
                self._peer_channels[peer] = ch
            return rpc.volume_stub(ch)

    def _remote_reader_factory(self, vid: int, collection: str):
        def read(shard_id: int, offset: int, size: int, generation: int):
            try:
                locs = self._master_client().lookup_ec(vid).get(shard_id, [])
            except (LookupError, grpc.RpcError):
                return None
            my_url = f"{self.ip}:{self.grpc_port}"
            for loc in locs:
                peer = f"{loc.url.split(':')[0]}:{loc.grpc_port}"
                if peer == my_url:
                    continue
                try:
                    buf = b"".join(
                        c.data
                        for c in self._peer_stub(peer).VolumeEcShardRead(
                            pb.EcShardReadRequest(
                                volume_id=vid,
                                shard_id=shard_id,
                                offset=offset,
                                size=size,
                                generation=generation,
                            ),
                            timeout=30,
                            # request id + trace context ride to the
                            # peer: a degraded read's remote sibling
                            # fetches join the reader's trace
                            metadata=trace.grpc_metadata(),
                        )
                    )
                    if len(buf) == size:
                        return buf
                except grpc.RpcError:
                    continue
            return None

        return read

    # ---------------------------------------------- peer-fetch rebuild

    def peer_fetch_rebuild(
        self, vid: int, collection: str = "", backend_name: str = ""
    ) -> dict:
        """Cluster-level EC self-heal for one volume on THIS server:
        when fewer than k verified-good source shards are on local
        disk, stream siblings from peer holders (VolumeEcShardRead,
        generation-fenced, sidecar-verified with verify-and-exclude —
        ec/peer_rebuild.py), rebuild through the staged/scheduled
        device path, mount the regenerated shards this server owns,
        and distribute regenerated CLUSTER-LOST shards to planned
        holders (ec/placement.py) before handing them off. Idempotent:
        a re-run after any crash window (publish, distribute)
        converges without minting duplicate copies."""
        # One peer rebuild per volume at a time on this server: a
        # concurrent second call (operator shell racing the fleet
        # dispatcher — the worker-control one-live-task dedupe only
        # covers tasks) would wipe the first call's staging directory
        # mid-flight. Refuse, don't queue: the first run heals the
        # volume and a refused caller re-runs idempotently.
        busy = self._peer_rebuild_busy.setdefault(vid, threading.Lock())
        if not busy.acquire(blocking=False):
            raise ECError(
                f"peer-fetch rebuild for ec volume {vid} is already "
                f"running on this server; re-run after it finishes"
            )
        try:
            return self._peer_fetch_rebuild_locked(
                vid, collection, backend_name
            )
        finally:
            busy.release()

    def _peer_fetch_rebuild_locked(
        self, vid: int, collection: str, backend_name: str
    ) -> dict:
        loc_base = self.service._ec_base(vid, collection)
        if loc_base is None:
            raise ECError(f"ec volume {vid} not found on this server")
        from ..ec.peer_rebuild import PeerFetchTransient, rebuild_from_peers
        from ..ec.volume_info import VolumeInfo

        vi = VolumeInfo.maybe_load(loc_base + ".vif")
        ctx = (vi.ec_ctx if vi else None) or ec_context.ECContext()
        generation = vi.encode_ts_ns if vi else 0
        ev = self.store.find_ec_volume(vid)
        if ev is None:
            # an unmounted volume has no legitimate-set to scope targets
            # by — distribution would ship this server's own shards
            # away. Offline repair keeps the local rebuild path.
            raise ECError(
                f"ec volume {vid} is not mounted here; peer-fetch "
                f"rebuild needs the serving mount"
            )
        legit = set(ev.legitimate_shards())

        # Fresh holder map (a balance move since the cached lookup would
        # route fetches at a server that no longer has the shard); the
        # master is REQUIRED here — without topology there is no safe
        # notion of "lost" vs "lives on a peer".
        try:
            located = self._master_client().lookup_ec(vid, refresh=True)
        except (LookupError, grpc.RpcError) as e:
            raise ECError(f"peer-fetch rebuild needs the master: {e}") from e
        me = f"{self.ip}:{self.port}"
        holders: dict[int, list[str]] = {}
        for sid, locs in located.items():
            peers = [fleet.grpc_addr(l) for l in locs if l.url != me]
            if peers:
                holders[sid] = peers
        lost = {sid for sid in range(ctx.total) if not located.get(sid)}
        present = {
            i
            for i in range(ctx.total)
            if os.path.exists(loc_base + ctx.to_ext(i))
        }
        # Same no-duplicate-minting contract as the local rebuild RPC:
        # regenerate only this server's legitimate set plus shards the
        # master knows no location for. Present-but-corrupt locals are
        # replaced by rebuild_from_peers regardless.
        targets = sorted((legit | lost) - present)

        def fetch(peer: str, sid: int, off: int, size: int) -> bytes:
            try:
                buf = bytearray()
                for c in self._peer_stub(peer).VolumeEcShardRead(
                    pb.EcShardReadRequest(
                        volume_id=vid,
                        shard_id=sid,
                        offset=off,
                        size=size,
                        generation=generation,
                    ),
                    timeout=60,
                    # one trace id across the whole cluster heal: the
                    # rebuild's span context rides to every peer's
                    # shard-read stream
                    metadata=trace.grpc_metadata(),
                ):
                    buf += c.data
                    M.net_bytes_copied_total.inc(len(c.data), plane="python", direction="read")
            except grpc.RpcError as e:
                # mid-stream peer death / stale generation / unreachable:
                # all retry-then-replan material, never a crash
                raise PeerFetchTransient(
                    f"{peer}: {e.code().name}: {e.details()}"
                ) from e
            M.net_bytes_received_total.inc(len(buf), plane="python", direction="read")
            M.net_bytes_copied_total.inc(len(buf), plane="python", direction="read")
            return bytes(buf)

        # Native ingress (ec/net_plane.py): sibling streams land
        # directly in pooled aligned buffers on the peer's shard byte
        # plane (grpc addr + port offset); peers without the plane are
        # memoized and their streams ride the gRPC fetch above.
        np_client = None
        fetch_into = None
        try:
            from ..ec import net_plane as _netp

            np_client = _netp.NetPlaneClient()
            fetch_into = _netp.make_fetch_into(np_client, vid, generation)
        except Exception:  # pragma: no cover - defensive
            np_client = None

        from ..ec.backend import get_backend

        backend = get_backend(
            backend_name or self.store.ec_backend,
            ctx.data_shards,
            ctx.parity_shards,
        )
        try:
            with M.request_seconds.time(server="volume", op="ec_peer_rebuild"):
                report = rebuild_from_peers(
                    loc_base,
                    holders,
                    fetch,
                    ctx=ctx,
                    targets=targets,
                    backend=backend,
                    scheduler=self.store.ec_scheduler,
                    fetch_into=fetch_into,
                )
        finally:
            if np_client is not None:
                np_client.close()
        M.ec_ops_total.inc(
            op="peer_rebuild", backend=backend_name or self.store.ec_backend
        )
        # Locally-owned regenerated shards re-enter service: swap the
        # mounted fds onto the fresh inodes (quarantined shards come
        # back too) and advertise via heartbeat. legit already covers
        # every corrupt shard this server may mount — served rot is in
        # shard_fds, quarantined rot rides legitimate_shards(); a
        # corrupt NON-legit file is a rotten handoff leftover, and
        # mounting it here would advertise a holder that the distribute
        # step below then unlinks.
        owned = sorted(sid for sid in report.rebuilt if sid in legit)
        if owned:
            ev.reopen_shards(owned)
            self.notify_new_ec_shards(vid, collection)
        # Leaf-repaired shards were patched IN PLACE on the canonical
        # inode: the serving fd stays valid, but cached reconstructions
        # over the patched byte ranges are stale — drop exactly those.
        for sid, ranges in report.patched_ranges.items():
            ev.invalidate_shard_ranges(sid, ranges)
        distributed = self._distribute_lost_shards(
            vid, collection, loc_base, ctx, legit
        )
        return {
            "rebuilt": sorted(report.rebuilt),
            "fetched": sorted(report.fetched),
            "distributed": distributed,
            "repaired": sorted(report.leaf_repaired),
        }

    def _distribute_lost_shards(
        self, vid: int, collection: str, base: str, ctx, legit
    ) -> list[int]:
        """Ship regenerated cluster-lost shards this server does NOT own
        to planned holders (copy + mount on the destination, then delete
        the local handoff copy). The inventory is the DISK — every
        canonical shard file outside this server's legitimate set — not
        just this run's rebuild output, so a re-run after a
        crash-during-distribute finishes the handoff instead of leaving
        limbo files; and the holder map is re-fetched HERE, so a crashed
        prior run whose destination already mounted the shard resolves
        by deleting the local duplicate instead of copying it to a
        second holder. The local copies are never mounted here, so the
        master never sees a duplicate holder mid-flight."""
        inventory = [
            sid
            for sid in range(ctx.total)
            if sid not in legit and os.path.exists(base + ctx.to_ext(sid))
        ]
        if not inventory:
            return []
        from .. import faults
        from ..ec.placement import node_view_for, plan_shard_placement

        try:
            located = self._master_client().lookup_ec(vid, refresh=True)
        except (LookupError, grpc.RpcError) as e:
            # the rebuild + local mounts above are already durable; a
            # re-run finishes the handoff. Typed refusal, not an
            # unhandled RpcError escaping the servicer as UNKNOWN.
            raise ECError(
                f"rebuilt shards are mounted, but distributing "
                f"cluster-lost shards needs the master: {e}; re-run "
                f"ec.rebuild -fromPeers to finish the handoff"
            ) from e
        me = f"{self.ip}:{self.port}"
        done: list[int] = []
        pending: list[int] = []
        for sid in inventory:
            if any(l.url != me for l in located.get(sid, [])):
                # a holder already serves it (crash-after-mount, or a
                # concurrent balance copy): finish the handoff — the
                # ec.balance dedupe rule — by dropping the local copy
                os.unlink(base + ctx.to_ext(sid))
                done.append(sid)
            else:
                pending.append(sid)
        if not pending:
            return done
        try:
            topo = self._master_client().topology()
        except (LookupError, grpc.RpcError) as e:
            raise ECError(
                f"rebuilt shards are mounted, but placing cluster-lost "
                f"shards needs the master topology: {e}; re-run "
                f"ec.rebuild -fromPeers to finish the handoff"
            ) from e
        nodes = {n.id: n for n in topo.nodes}
        # Live compute signal beside the capacity signal: the master's
        # heartbeat-learned per-node chip loads (EcTelemetry) rank
        # otherwise-equal destinations by queue headroom, so a
        # regenerated shard lands where there is compute slack for its
        # future degraded reads — the routing loop closed cluster-wide.
        cluster_tele = self._cluster_ec_telemetry()
        sp = trace.current()
        if sp is not None:
            sp.event(
                "placement_signals",
                source=("live" if cluster_tele else "static"),
                node_loads={
                    nid: t.get("chips", {})
                    and sum(
                        c.get("load", 0)
                        for c in t.get("chips", {}).values()
                    )
                    for nid, t in cluster_tele.items()
                },
            )
        # Capacity-aware views: used bytes straight from the topology
        # (volume sizes + EC shard bytes); the denominator is the
        # master's own volume size limit, learned via heartbeat. Either
        # side unknown -> headroom unknown -> slot-only planning.
        views = [
            node_view_for(
                n.id,
                n.rack,
                n.data_center,
                n.max_volume_count,
                len(n.volumes),
                n.ec_shards,
                ec_telemetry=cluster_tele.get(n.id),
                used_bytes=(
                    sum(int(v.size) for v in n.volumes)
                    + sum(
                        int(e.shard_size) * bin(e.shard_bits).count("1")
                        for e in n.ec_shards
                    )
                ),
                capacity_bytes=(
                    int(n.max_volume_count or 8) * self.volume_size_limit
                    if self.volume_size_limit > 0
                    else -1
                ),
            )
            for n in topo.nodes
        ]
        try:
            shard_bytes = os.path.getsize(base + ctx.to_ext(pending[0]))
        except OSError:
            shard_bytes = 0
        shard_count = {
            n.id: {e.id: bin(e.shard_bits).count("1") for e in n.ec_shards}
            for n in topo.nodes
        }
        faults.fire("ec.peer_rebuild.before_distribute", volume=vid)
        adopted: list[int] = []
        # In-pass re-planning: a destination that dies (or refuses) is
        # EXCLUDED and the remaining shards are re-planned against the
        # surviving candidates inside this same run — a dead holder no
        # longer defers the handoff to the next rebuild pass. Each
        # failed round excludes at least one node, so the loop is
        # bounded by the topology size.
        remaining = list(pending)
        dead_nodes: set[str] = set()
        for _round in range(max(len(views), 1) + 1):
            if not remaining:
                break
            candidates = [v for v in views if v.id not in dead_nodes]
            plan = plan_shard_placement(
                candidates, vid, remaining, shard_bytes=shard_bytes
            )
            if _round and plan:
                log.warning(
                    "re-planned ec %d distribution for shards %s after "
                    "excluding dead destinations %s",
                    vid, remaining, sorted(dead_nodes),
                )
            next_round: list[int] = []
            for sid in remaining:
                node = nodes.get(plan.get(sid, ""))
                if node is not None and node.id in dead_nodes:
                    # planned in THIS round before the node died on an
                    # earlier shard: don't burn another copy timeout on
                    # it — straight to the next round's re-plan
                    next_round.append(sid)
                    continue
                if node is None or node.location.url == me:
                    if _round:
                        # re-plan round after a destination death: no
                        # SURVIVING alternate can take it. Keep the
                        # handoff copy on disk (unmounted, never
                        # advertised) for the next rebuild run instead
                        # of adopting — a dead peer must not silently
                        # re-home the shard onto the rebuilder.
                        log.warning(
                            "ec %d.%02d: no surviving alternate "
                            "destination; handoff deferred to the next "
                            "run", vid, sid,
                        )
                        continue
                    # first plan: no capacity anywhere (or the planner
                    # chose us) — adopt the shard locally rather than
                    # leave it in limbo
                    adopted.append(sid)
                    done.append(sid)
                    continue
                dest = fleet.grpc_addr(node.location)
                first_on_dst = shard_count.get(node.id, {}).get(vid, 0) == 0
                try:
                    stub = self._peer_stub(dest)
                    stub.VolumeEcShardsCopy(
                        pb.EcShardsCopyRequest(
                            volume_id=vid,
                            collection=collection,
                            shard_ids=[sid],
                            source_url=f"{self.ip}:{self.grpc_port}",
                            copy_ecx=first_on_dst,
                            copy_ecj=first_on_dst,
                            copy_vif=first_on_dst,
                            copy_ecsum=first_on_dst,
                        ),
                        timeout=600,
                        metadata=trace.grpc_metadata(),
                    )
                    stub.VolumeEcShardsMount(
                        pb.EcShardsMountRequest(
                            volume_id=vid, collection=collection
                        ),
                        timeout=60,
                        metadata=trace.grpc_metadata(),
                    )
                except grpc.RpcError as e:
                    # destination died mid-distribute: exclude it and
                    # re-plan THIS shard against the survivors in the
                    # next round; the handoff copy stays on disk
                    # (unmounted, never advertised) either way, so a
                    # crash mid-re-plan still converges on re-run.
                    # Best-effort delete of whatever the COPY landed at
                    # the failed destination first: a copy-succeeded/
                    # mount-failed node keeps the shard at its canonical
                    # path, and once the shard is re-homed elsewhere a
                    # later mount on that node would advertise a
                    # duplicate holder. A dead node ignores the delete;
                    # a merely-slow one is cleaned.
                    log.warning(
                        "distribute ec %d.%02d -> %s failed: %s; "
                        "excluding the destination and re-planning",
                        vid, sid, dest, e.code().name,
                    )
                    try:
                        self._peer_stub(dest).VolumeEcShardsDelete(
                            pb.EcShardsDeleteRequest(
                                volume_id=vid,
                                collection=collection,
                                shard_ids=[sid],
                            ),
                            timeout=15,
                            metadata=trace.grpc_metadata(),
                        )
                    except grpc.RpcError:
                        pass  # node truly unreachable: nothing landed,
                        # or its disk state is beyond reach either way
                    dead_nodes.add(node.id)
                    next_round.append(sid)
                    continue
                faults.fire(
                    "ec.peer_rebuild.after_distribute", volume=vid, shard=sid
                )
                os.unlink(base + ctx.to_ext(sid))
                shard_count.setdefault(node.id, {})[vid] = (
                    shard_count.get(node.id, {}).get(vid, 0) + 1
                )
                done.append(sid)
            remaining = next_round
        if adopted:
            # mount ONLY the adopted ids: a blanket refresh would also
            # mount handoff copies whose distribute failed above, and
            # those must stay unmounted/unadvertised so the next run
            # retries the handoff instead of this server keeping them
            ev = self.store.find_ec_volume(vid)
            if ev is not None:
                ev.reopen_shards(adopted)
            self.notify_new_ec_shards(vid, collection)
        return done

    # ------------------------------------------------------- replication

    def _replica_locations(self, vid: int) -> list[pb.Location]:
        try:
            locs = self._master_client().lookup(vid)
        except (LookupError, grpc.RpcError):
            return []
        me = f"{self.ip}:{self.port}"
        return [l for l in locs if l.url != me]

    def _peer_metadata(self, vid: int):
        """Peer-auth metadata for gRPC writes on a keyed cluster."""
        if not self.jwt_key:
            return None
        from ..utils.security import sign_jwt

        return (("authorization", f"Bearer {sign_jwt(self.jwt_key, str(vid))}"),)

    def _plane_replicate(self, host: str, grpc_port: int,
                         request: pb.WriteNeedleRequest) -> bool:
        """One replication leg over the native write plane: a pooled
        sidecar connection instead of a per-write gRPC round trip.
        Returns False (caller falls back to gRPC) when the plane is
        off, chaos other than write-path chaos is armed, the peer has
        no sidecar (memoized with TTL), or the write errs — the gRPC
        leg is the correctness path, the plane leg only the fast one."""
        try:
            from ..ec import net_plane as _netp
            from ..ec import native_io

            if not native_io.enabled():
                return False
            if not _netp.write_plane_admissible():
                return False
            jwt = ""
            if self.jwt_key:
                from ..utils.security import sign_jwt

                jwt = sign_jwt(self.jwt_key, str(request.volume_id))
            self._net_plane_client().write_needle(
                (host, _netp.derive_port(grpc_port)),
                request.volume_id,
                request.needle_id,
                request.cookie,
                bytes(request.data),
                flags=request.flags,
                name=request.name.encode() if request.name else b"",
                mime=request.mime.encode() if request.mime else b"",
                jwt=jwt,
                replicate=False,
            )
            return True
        except Exception:  # noqa: BLE001 — any plane failure => gRPC
            return False

    def replicate_write(self, request: pb.WriteNeedleRequest) -> str:
        """Synchronous fan-out to replica holders (reference
        store_replicate.go:32 DistributedOperation). Each leg tries
        the native write plane first (pooled connection, fused-CRC
        landing), falling back to the per-write gRPC ``WriteNeedle``
        when the peer has no sidecar — both legs produce bit-identical
        needle records on the replica."""
        errors = []
        md = self._peer_metadata(request.volume_id)
        for loc in self._replica_locations(request.volume_id):
            host = loc.url.split(":")[0]
            if self._plane_replicate(host, loc.grpc_port, request):
                continue
            rep = pb.WriteNeedleRequest()
            rep.CopyFrom(request)
            rep.is_replicate = True
            try:
                r = self._peer_stub(
                    f"{host}:{loc.grpc_port}"
                ).WriteNeedle(rep, timeout=30, metadata=md)
                if r.error:
                    errors.append(f"{loc.url}: {r.error}")
            except grpc.RpcError as e:
                errors.append(f"{loc.url}: {e.code().name}")
        return "; ".join(errors)

    def replicate_ec_delete(self, vid: int, collection: str, needle_id: int) -> str:
        """Journal the EC tombstone on every other shard holder. Returns
        an error summary ('' = all holders reached) — a silently missed
        holder would resurrect the blob, so failures must surface."""
        try:
            # fresh holder list: a balance move since the cached lookup
            # would otherwise be missed entirely
            shard_locs = self._master_client().lookup_ec(vid, refresh=True)
        except (LookupError, grpc.RpcError) as e:
            return f"ec tombstone fan-out: holder lookup failed: {e}"
        me = f"{self.ip}:{self.port}"
        md = self._peer_metadata(vid)
        errors = []
        seen = set()
        for locs in shard_locs.values():
            for loc in locs:
                if loc.url == me or loc.url in seen:
                    continue
                seen.add(loc.url)
                try:
                    self._peer_stub(
                        f"{loc.url.split(':')[0]}:{loc.grpc_port}"
                    ).VolumeEcBlobDelete(
                        pb.EcBlobDeleteRequest(
                            volume_id=vid,
                            collection=collection,
                            needle_id=needle_id,
                        ),
                        timeout=30,
                        metadata=md,
                    )
                except grpc.RpcError as e:
                    errors.append(f"{loc.url}: {e.code().name}")
        return "; ".join(errors)

    def replicate_delete(self, request: pb.DeleteNeedleRequest) -> None:
        md = self._peer_metadata(request.volume_id)
        for loc in self._replica_locations(request.volume_id):
            rep = pb.DeleteNeedleRequest()
            rep.CopyFrom(request)
            rep.is_replicate = True
            try:
                self._peer_stub(
                    f"{loc.url.split(':')[0]}:{loc.grpc_port}"
                ).DeleteNeedle(rep, timeout=30, metadata=md)
            except grpc.RpcError:
                pass

    # -------------------------------------------------------- heartbeats

    def _ec_telemetry_json(self) -> str:
        """Device-telemetry blob riding every full heartbeat: per-chip
        queue load + breaker state (ec/chip_pool.chip_load_hint over
        this server's OWN scheduler scope), the flight recorder's
        per-op/stage EWMAs, and per-EC-volume HEAT counters (lifetime
        read/reconstruction bytes — the master's rebalance scanner
        diffs them per sweep to rank hot volumes, ec/rebalance.py).
        The master is the only consumer — it aggregates into
        /cluster/status, the sw_ec_queue_load fleet gauges, and the
        gravity/heat planners; placement readers age the blob out via
        `received_at`/`ts` (SEAWEED_EC_TELEMETRY_STALE_S)."""
        from ..ec.chip_pool import chip_load_hint

        try:
            chips = chip_load_hint(self.store.ec_scheduler)
        except Exception:  # telemetry must never break the heartbeat
            chips = {}
        breakers_open = sum(
            1 for c in chips.values() if c.get("breaker") == "open"
        )
        ec_volumes: dict[str, dict] = {}
        try:
            for dloc in self.store.locations:
                for vid, ev in dloc.ec_volumes.items():
                    ec_volumes[str(vid)] = {
                        "read_bytes": int(ev.bytes_read),
                        "reconstructed_bytes": int(ev.bytes_reconstructed),
                    }
        except Exception:  # heat is advisory; never break the heartbeat
            ec_volumes = {}
        try:
            from ..ec.device_queue import residency_snapshot

            residency = residency_snapshot()
        except Exception:  # advisory; never break the heartbeat
            residency = {}
        return json.dumps(
            {
                "chips": chips,
                "breakers_open": breakers_open,
                "degraded": breakers_open > 0,
                "residency": residency,
                "stage_ewma_s": {
                    k: round(v, 6) for k, v in trace.stage_ewmas().items()
                },
                "ec_volumes": ec_volumes,
                "ts": time.time(),
            }
        )

    def _full_heartbeat(self) -> pb.Heartbeat:
        st = self.store.status()
        # addr label keeps multi-server processes from clobbering each
        # other on the shared registry
        addr = self.store.public_url
        M.volume_count.set(len(st["volumes"]), kind="normal", addr=addr)
        M.volume_count.set(len(st["ec_volumes"]), kind="ec", addr=addr)
        M.volume_bytes.set(
            sum(v["size"] for v in st["volumes"]), kind="normal", addr=addr
        )
        M.volume_bytes.set(
            sum(e["shard_size"] * len(e["shards"]) for e in st["ec_volumes"]),
            kind="ec",
            addr=addr,
        )
        return pb.Heartbeat(
            ip=self.ip,
            port=self.port,
            public_url=self.store.public_url,
            grpc_port=self.grpc_port,
            max_volume_count=self.max_volume_count,
            data_center=self.data_center,
            rack=self.rack,
            volumes=[
                pb.VolumeInfoMsg(
                    id=v["id"],
                    collection=v["collection"],
                    size=v["size"],
                    file_count=v["file_count"],
                    deleted_count=v["deleted_count"],
                    deleted_bytes=v["deleted_bytes"],
                    read_only=v["read_only"],
                    replica_placement=v["replica_placement"],
                    version=v["version"],
                    ttl=v.get("ttl", ""),
                    disk_type=v.get("disk_type", "hdd"),
                )
                for v in st["volumes"]
            ],
            ec_shards=[
                pb.EcShardInfoMsg(
                    id=e["id"],
                    collection=e["collection"],
                    shard_bits=_shard_bits(e["shards"]),
                    shard_size=e["shard_size"],
                    data_shards=e["data_shards"],
                    parity_shards=e["parity_shards"],
                    generation=e["generation"],
                )
                for e in st["ec_volumes"]
            ],
            has_no_volumes=not st["volumes"],
            has_no_ec_shards=not st["ec_volumes"],
            ec_telemetry_json=self._ec_telemetry_json(),
        )

    def notify_new_volume(self, vid: int) -> None:
        self._hb_queue.put(self._full_heartbeat())

    def notify_deleted_volume(self, vid: int) -> None:
        self._hb_queue.put(self._full_heartbeat())

    def notify_new_ec_shards(self, vid: int, collection: str) -> None:
        self._hb_queue.put(self._full_heartbeat())

    def notify_deleted_ec_shards(self, vid: int, collection: str, sids) -> None:
        self._hb_queue.put(self._full_heartbeat())

    def _heartbeat_iter(self):
        yield self._full_heartbeat()
        last_full = time.time()
        while not self._hb_stop.is_set():
            try:
                hb = self._hb_queue.get(timeout=2.0)
                yield hb
            except queue.Empty:
                # periodic full refresh doubles as liveness pulse; also
                # the reaper tick for expired TTL volumes
                reaped = self.store.reap_expired_volumes()
                if reaped:
                    log.info("reaped expired TTL volumes: %s", reaped)
                yield self._full_heartbeat()
                last_full = time.time()

    def _heartbeat_loop(self):
        target = self.master_addrs[0]
        fail_idx = 0
        while not self._hb_stop.is_set():
            redirect = None
            try:
                with grpc.insecure_channel(self._master_grpc(target)) as ch:
                    stream = rpc.master_stub(ch).SendHeartbeat(self._heartbeat_iter())
                    for resp in stream:
                        if self._hb_stop.is_set():
                            return
                        if resp.volume_size_limit:
                            self.volume_size_limit = int(
                                resp.volume_size_limit
                            )
                        if resp.leader and resp.leader != target:
                            # a follower answered: re-home to the leader
                            redirect = resp.leader
                            break
            except grpc.RpcError:
                pass
            if self._hb_stop.is_set():
                return
            if redirect:
                target = redirect
                continue  # reconnect immediately, no backoff
            # stream broke or follower with no known leader: try the
            # next configured master after a short pause
            fail_idx += 1
            target = self.master_addrs[fail_idx % len(self.master_addrs)]
            if self._hb_stop.wait(1.0):
                return

    # -------------------------------------------------------------- http

    def _handler_class(self):
        server = self

        from ..utils.request_id import RequestTracingMixin

        class Handler(RequestTracingMixin, BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            trace_server_kind = "volume"

            def log_message(self, *a):
                pass

            def _error(self, code: int, msg: str) -> None:
                body = json.dumps({"error": msg}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _fid(self):
                path = urlparse(self.path).path.lstrip("/")
                # accept "<vid>,<fid>" and "<vid>/<fid>"
                return FileId.parse(path.replace("/", ","))

            def _jwt_rejected(self, fid) -> bool:
                """True (and 401 already sent) when the cluster has a
                signing key and this request lacks a valid token
                (reference maybeCheckJwtAuthorization)."""
                if not server.jwt_key:
                    return False
                from ..utils.security import JwtError, verify_jwt

                auth = self.headers.get("Authorization", "")
                token = auth[7:] if auth.startswith("Bearer ") else ""
                try:
                    verify_jwt(server.jwt_key, token, str(fid))
                    return False
                except JwtError as e:
                    self._error(401, f"unauthorized: {e}")
                    return True

            def do_GET(self):
                u = urlparse(self.path)
                from ..utils.pprof import handle_debug_endpoint

                if handle_debug_endpoint(self, u):
                    return
                if self.serve_slo_endpoint(u.path):
                    return
                if u.path == "/debug/traces":
                    # Flight-recorder ring as Chrome trace_event JSON
                    # (load in Perfetto / chrome://tracing); ?trace_id=
                    # narrows to one cross-server trace, ?op= to one
                    # root op class, ?min_ms= to slow ops only;
                    # ?format=spans returns the raw span-tree docs
                    # instead. Loopback-only, same operator gate as
                    # /debug/pprof.
                    from ..utils.pprof import require_loopback

                    if not require_loopback(self, "trace"):
                        return
                    q = parse_qs(u.query)
                    tid = q.get("trace_id", [""])[0]
                    try:
                        min_ms = float(q.get("min_ms", ["0"])[0] or 0.0)
                    except ValueError:
                        min_ms = 0.0
                    docs = trace.traces(
                        tid, op=q.get("op", [""])[0], min_ms=min_ms
                    )
                    if q.get("format", [""])[0] == "spans":
                        payload = docs
                    else:
                        payload = trace.chrome_trace(docs=docs)
                    body = json.dumps(payload).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if u.path == "/metrics":
                    from ..utils.metrics import REGISTRY

                    body = REGISTRY.render()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if u.path == "/status":
                    st = server.store.status()
                    # per-chip per-class scheduler counters (depth /
                    # wait / throughput) ride along with volume status,
                    # keyed by each queue's `chip` device id — THIS
                    # server's scope, so a second tenant's chips never
                    # alias into these gauges. Pod breaker health rides
                    # on top: N of the M live chip queues with an OPEN
                    # fallback breaker (those chips' streams are running
                    # on CPU) flips `degraded`, the at-a-glance "this
                    # pod is not serving at device speed" flag.
                    snap = server.store.ec_scheduler.stats_snapshot()
                    open_b = sum(
                        1 for e in snap if e.get("breaker") == "open"
                    )
                    st["ec_device_queue"] = {
                        "queues": snap,
                        "chips": len(snap),
                        "breakers_open": open_b,
                        "degraded": open_b > 0,
                    }
                    if server.net_plane is not None:
                        # native shard byte plane sidecar health:
                        # sendfile vs python egress byte split
                        st["ec_net_plane"] = server.net_plane.status()
                    try:
                        from ..ec.stream_encode import stream_summary

                        # streaming-EC (encode-on-write) health: open
                        # streams in this process + parity-lag/sealed
                        # counters (sw_ec_stream_*)
                        st["ec_streams"] = stream_summary()
                    except Exception:  # noqa: BLE001
                        pass
                    try:
                        from ..ec.device_queue import residency_snapshot

                        # process-wide per-chip residency ledger:
                        # budget/inflight/high-watermarks + per-tenant
                        # shed counters (multi-tenant overload safety)
                        st["ec_residency"] = residency_snapshot()
                    except Exception:  # noqa: BLE001
                        pass
                    body = json.dumps(st).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                try:
                    # Per-tenant shedding on the needle data plane:
                    # reads against an over-share tenant's scope back
                    # off before any parse/lookup work when the
                    # residency ledger is at full shed (level 3) —
                    # the HTTP analogue of the S3 gateway's SlowDown,
                    # so direct volume readers see backpressure too.
                    from ..ec.device_queue import shed_advice

                    ra = shed_advice(
                        getattr(server.store.ec_scheduler, "tenant", "default")
                    )
                except Exception:  # shed is advisory; never block reads
                    ra = None
                if ra is not None:
                    body = json.dumps(
                        {"error": "tenant over fair device share"}
                    ).encode()
                    self.send_response(503)
                    self.send_header("Retry-After", str(max(1, int(ra))))
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                try:
                    fid = self._fid()
                except FileIdError as e:
                    return self._error(400, str(e))
                if parse_qs(u.query).get("locate", [""])[0] == "true":
                    # control plane of the bulk-read fast path: where
                    # the payload bytes live + which sidecar socket
                    # serves them (utils/fastread.py)
                    vol = server.store.find_volume(fid.volume_id)
                    if vol is None:
                        return self._error(404, "volume not here (or EC)")
                    try:
                        path, off, size, crc = vol.locate_payload(
                            fid.needle_id, fid.cookie
                        )
                    except (NotFoundError, CookieMismatch) as e:
                        return self._error(404, str(e))
                    except VolumeError as e:
                        return self._error(409, str(e))
                    sock = ""
                    apath = os.path.abspath(path)
                    for d, s in server.fastread_sockets.items():
                        if apath.startswith(d + os.sep):
                            sock = s
                            break
                    body = json.dumps(
                        {
                            "path": apath,
                            "offset": off,
                            "size": size,
                            "crc32c": crc,
                            "socket": sock,
                        }
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self._sw_op = "read"
                try:
                    # gateway stage: needle read (an EC degraded read
                    # below this opens its own ec.degraded_read child
                    # span under the same HTTP root via the ambient
                    # span, down to the chip)
                    with trace.stage(trace.current(), "volume.read"):
                        n = server.store.read_needle(
                            fid.volume_id, fid.needle_id, fid.cookie
                        )
                except (NotFoundError, ECError) as e:
                    return self._error(404, str(e))
                except (CookieMismatch, CrcError) as e:
                    return self._error(404, str(e))
                except (VolumeError, ValueError, OSError) as e:
                    # volume closed/converted mid-read: an error RESPONSE,
                    # never a dropped connection
                    return self._error(503, str(e))
                ctype = n.mime.decode() if n.mime else "application/octet-stream"
                data = n.data
                # on-the-fly thumbnailing (reference weed/images,
                # volume_server_handlers_read.go:362-421)
                rq = parse_qs(u.query)
                etag = f"{n.checksum:08x}"
                if "width" in rq or "height" in rq:
                    from ..utils.images import detect_format, resized

                    try:
                        rw = int(rq.get("width", ["0"])[0] or 0)
                        rh = int(rq.get("height", ["0"])[0] or 0)
                    except ValueError:
                        rw = rh = 0  # malformed dims: serve the original
                    rmode = rq.get("mode", [""])[0]
                    if rmode not in ("", "fit", "fill"):
                        # whitelist: the value is echoed into the ETag
                        # header, so arbitrary bytes would be header
                        # injection (response splitting)
                        rmode = ""
                    out, _, _ = resized(data, rw, rh, rmode)
                    if out is not data:
                        data = out
                        # re-encode may change the container (GIF→PNG)
                        # and each variant needs its own cache key
                        fmt = detect_format(data)
                        if fmt:
                            ctype = f"image/{fmt.lower()}"
                        etag = f"{n.checksum:08x}-{rw}x{rh}{rmode}"
                total = len(data)
                status = 200
                content_range = None
                rng = self.headers.get("Range", "")
                if rng.startswith("bytes=") and self.command != "HEAD":
                    try:
                        lo_s, _, hi_s = rng[6:].split(",")[0].partition("-")
                        lo = int(lo_s) if lo_s else max(total - int(hi_s), 0)
                        hi = int(hi_s) if hi_s and lo_s else total - 1
                        if lo > hi or lo >= total:  # incl. any range on empty body
                            self.send_response(416)
                            self.send_header("Content-Range", f"bytes */{total}")
                            self.send_header("Content-Length", "0")
                            self.end_headers()
                            return
                        hi = min(hi, total - 1)
                        data = data[lo : hi + 1]
                        status = 206
                        content_range = f"bytes {lo}-{hi}/{total}"
                    except ValueError:
                        pass  # malformed Range: serve the full body
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.send_header("Accept-Ranges", "bytes")
                if content_range:
                    self.send_header("Content-Range", content_range)
                self.send_header("ETag", f'"{etag}"')
                self.end_headers()
                if self.command != "HEAD":
                    # needle payloads leave through the native
                    # scatter-gather sender on the pooled front end
                    from ..utils.http_pool import send_body

                    send_body(self, data)

            do_HEAD = do_GET

            def do_POST(self):
                u = urlparse(self.path)
                q = parse_qs(u.query)
                try:
                    fid = self._fid()
                except FileIdError as e:
                    return self._error(400, str(e))
                if self._jwt_rejected(fid):
                    return
                length = int(self.headers.get("Content-Length", "0"))
                body = self.rfile.read(length)
                name, mime, data = _parse_upload(self.headers, body)
                req = pb.WriteNeedleRequest(
                    volume_id=fid.volume_id,
                    needle_id=fid.needle_id,
                    cookie=fid.cookie,
                    data=data,
                    name=name,
                    mime=mime,
                    is_replicate=q.get("type", [""])[0] == "replicate",
                )
                resp = server.service.WriteNeedle(req, None)
                if resp.error:
                    return self._error(500, resp.error)
                body = json.dumps({"name": name, "size": resp.size}).encode()
                self.send_response(201)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_DELETE(self):
                u = urlparse(self.path)
                q = parse_qs(u.query)
                try:
                    fid = self._fid()
                except FileIdError as e:
                    return self._error(400, str(e))
                if self._jwt_rejected(fid):
                    return
                resp = server.service.DeleteNeedle(
                    pb.DeleteNeedleRequest(
                        volume_id=fid.volume_id,
                        needle_id=fid.needle_id,
                        is_replicate=q.get("type", [""])[0] == "replicate",
                    ),
                    None,
                )
                if resp.error:
                    if resp.freed_bytes:
                        # freed locally but fan-out incomplete
                        code = 500
                    elif "not found" in resp.error:
                        code = 404
                    else:
                        # transient (volume mid-conversion, IO): 503 so
                        # clients retry instead of treating it as gone
                        code = 503
                    return self._error(code, resp.error)
                body = json.dumps({"size": resp.freed_bytes}).encode()
                self.send_response(202)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        return Handler

    # -------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._grpc.start()
        self._http_thread.start()
        self._hb_thread.start()
        if self.net_plane is not None:
            self.net_plane.start()
        if self.scrub_daemon is not None:
            self.scrub_daemon.start()

    def stop(self) -> None:
        self._hb_stop.set()
        if self.net_plane is not None:
            self.net_plane.stop()
        if self.scrub_daemon is not None:
            self.scrub_daemon.stop()
        if self.fastread_sockets:
            from ..utils.fastread import stop_server as _fr_stop

            for sock in self.fastread_sockets.values():
                _fr_stop(sock)
        self._grpc.stop(grace=0.5)
        self._http.shutdown()
        self._http.server_close()
        with self._mc_lock:
            if self._mc is not None:
                self._mc.close()
            if self._np_client is not None:
                self._np_client.close()
            for ch in self._peer_channels.values():
                ch.close()
            self._peer_channels.clear()
        self.store.close()


def _parse_upload(headers, body: bytes) -> tuple[str, str, bytes]:
    """multipart/form-data or raw body -> (name, mime, data)."""
    ctype = headers.get("Content-Type", "")
    if ctype.startswith("multipart/form-data"):
        import email.parser
        import email.policy

        msg = email.parser.BytesParser(policy=email.policy.HTTP).parsebytes(
            b"Content-Type: " + ctype.encode() + b"\r\n\r\n" + body
        )
        for part in msg.iter_parts():
            data = part.get_payload(decode=True)
            if data is None:
                continue
            return (
                part.get_filename() or "",
                part.get_content_type(),
                data,
            )
        return "", "", b""
    return "", ctype if ctype != "application/octet-stream" else "", body
