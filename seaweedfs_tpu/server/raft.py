"""Raft consensus for master HA.

Reference: weed/server/raft_hashicorp.go + raft_server.go — the
reference replicates the max-volume-id/sequencer allocation state across
masters and derives leadership for the topology (`Topo.IsLeader`,
topology.go:245). This is an original, compact Raft (leader election,
log replication, majority commit, durable term/vote/log) specialised to
that small state machine; topology itself is NOT replicated — it is
rebuilt from volume-server heartbeats on whichever master leads, exactly
like the reference.

State machine commands:
  alloc_volume_id(value=hint) -> applied result max(state, hint) + 1
  noop                        -> leader barrier entry on election

Persistence: one JSON-lines file per node (term/vote records and log
entries), fsynced on every durable mutation before any RPC response
that promises it — the same discipline the storage engine uses.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time

import grpc

from ..pb import cluster_pb2 as pb
from ..pb import rpc
from ..utils.glog import logger

log = logger("raft")

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class TransportError(Exception):
    """A peer RPC failed (network, timeout, dead peer). The raft code
    treats it exactly like the gRPC error it wraps; the injectable
    fault transport in tests raises it directly."""


class GrpcTransport:
    """Default peer transport: gRPC to host:port+10000 (the service
    port convention every component uses)."""

    def __init__(self, node: "RaftNode"):
        self._node = node

    def call(self, peer: str, method: str, request, timeout: float):
        try:
            return getattr(self._node._peer_stub(peer), method)(
                request, timeout=timeout
            )
        except grpc.RpcError as e:
            raise TransportError(str(e)) from None


class RaftNode:
    """One master's raft participant.

    `node_id` / `peers` are the masters' HTTP host:port addresses (the
    cluster-wide names); RPCs go to port+10000 like every other service.
    `apply_fn(kind, value) -> result` runs under the node lock in log
    order exactly once per committed entry.
    """

    def __init__(
        self,
        node_id: str,
        peers: list[str],
        state_dir: str | None = None,
        apply_fn=None,
        election_timeout: tuple[float, float] = (0.4, 0.8),
        heartbeat_interval: float = 0.1,
        snapshot_fn=None,
        restore_fn=None,
        compact_threshold: int = 1024,
        transport_factory=None,
    ):
        """transport_factory(node) -> object with
        call(peer, method, request, timeout); None = gRPC. The seam the
        deterministic fault harness injects drops/delays/partitions
        through (tests/raft_sim.py)."""
        self.node_id = node_id
        self.peers = [p for p in peers if p != node_id]
        self.apply_fn = apply_fn or (lambda kind, value: 0)
        # snapshot_fn() -> JSON-able dict of the state machine;
        # restore_fn(dict) reloads it. Both run under the node lock.
        self.snapshot_fn = snapshot_fn or (lambda: {})
        self.restore_fn = restore_fn or (lambda state: None)
        self.compact_threshold = max(compact_threshold, 8)
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval

        self._lock = threading.RLock()
        self._applied_cv = threading.Condition(self._lock)
        self.role = FOLLOWER
        self.current_term = 0
        self.voted_for: str | None = None
        # log entries with ABSOLUTE index > snap_index (compaction drops
        # the applied prefix into the snapshot)
        self.log: list[pb.RaftEntry] = []
        self.snap_index = 0
        self.snap_term = 0
        self._snap_state: dict = {}
        self.commit_index = 0
        self.last_applied = 0
        self.leader_id: str | None = None
        self.removed = False  # True once a config change drops this node
        self._membership_lock = threading.Lock()  # one change at a time
        # index -> (term, result): the term pins ownership so a deposed
        # leader can never return a foreign entry's result
        self._apply_results: dict[int, tuple[int, int]] = {}
        # indices a propose() call is still waiting on — eviction of
        # _apply_results must never cross the smallest of these, or a
        # slow proposer's committed (term, result) can vanish before it
        # wakes (spurious NotLeader for a committed write => retry
        # double-apply).
        self._propose_waiting: set[int] = set()
        # leader volatile state
        self._next_index: dict[str, int] = {}
        self._match_index: dict[str, int] = {}

        self._state_path = (
            os.path.join(state_dir, "raft.jsonl") if state_dir else None
        )
        self._state_file = None
        self._load_state()

        self._stop = threading.Event()
        self._last_heard = time.monotonic()
        self._last_broadcast = 0.0
        self._repl_inflight: set[str] = set()
        self._channels: dict[str, grpc.Channel] = {}
        self.transport = (
            transport_factory(self) if transport_factory else GrpcTransport(self)
        )
        self._threads: list[threading.Thread] = []
        # hook(leader_addr) fired whenever the known leader changes
        # (election won, or a valid leader's first append) — the master
        # uses it to notify KeepConnected sessions
        self.on_leader_change = None

    # ------------------------------------------------- index arithmetic

    def _last_index(self) -> int:
        return self.snap_index + len(self.log)

    def _entry_at(self, idx: int) -> pb.RaftEntry:
        """Entry at ABSOLUTE index idx (> snap_index)."""
        return self.log[idx - self.snap_index - 1]

    def _term_at(self, idx: int) -> int:
        if idx == 0:
            return 0
        if idx == self.snap_index:
            return self.snap_term
        if idx < self.snap_index:
            return -1  # compacted away: only InstallSnapshot can help
        return self.log[idx - self.snap_index - 1].term

    def _truncate_from(self, idx: int) -> None:
        """Drop entries at absolute index >= idx."""
        del self.log[max(idx - self.snap_index - 1, 0) :]

    # ------------------------------------------------------- persistence

    def _load_state(self) -> None:
        if not self._state_path or not os.path.exists(self._state_path):
            return
        with open(self._state_path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    break  # torn tail from a crash: ignore the partial record
                if rec["t"] == "term":
                    self.current_term = rec["term"]
                    self.voted_for = rec.get("voted_for")
                elif rec["t"] == "snapshot":
                    self.snap_index = rec["index"]
                    self.snap_term = rec["term"]
                    self._snap_state = rec.get("state", {})
                    members = rec.get("members")
                    if members:
                        self.peers = [m for m in members if m != self.node_id]
                    self.log = []
                    self.commit_index = self.snap_index
                    self.last_applied = self.snap_index
                    self.restore_fn(self._snap_state)
                elif rec["t"] == "entry":
                    e = pb.RaftEntry(
                        term=rec["term"],
                        index=rec["index"],
                        kind=rec["kind"],
                        value=rec.get("value", 0),
                        data=rec.get("data", ""),
                    )
                    if e.index <= self.snap_index:
                        continue  # already folded into the snapshot
                    # replace any conflicting suffix, then append
                    self._truncate_from(e.index)
                    self.log.append(e)
                elif rec["t"] == "truncate":
                    self._truncate_from(rec["index"])

    def _persist(self, rec: dict) -> None:
        if not self._state_path:
            return
        if self._state_file is None:
            self._state_file = open(self._state_path, "a", encoding="utf-8")
        self._state_file.write(json.dumps(rec) + "\n")
        self._state_file.flush()
        os.fsync(self._state_file.fileno())

    def _persist_term(self) -> None:
        self._persist(
            {"t": "term", "term": self.current_term, "voted_for": self.voted_for}
        )

    def _persist_entry(self, e: pb.RaftEntry) -> None:
        rec = {
            "t": "entry",
            "term": e.term,
            "index": e.index,
            "kind": e.kind,
            "value": e.value,
        }
        if e.data:
            rec["data"] = e.data
        self._persist(rec)

    def _rewrite_state_file_locked(self) -> None:
        """Atomic rewrite: snapshot + current term + surviving entries.
        This is what BOUNDS the on-disk log — the old JSONL grew
        forever (r3 verdict Weak #9)."""
        if not self._state_path:
            return
        tmp = self._state_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            members = sorted({self.node_id, *self.peers})
            f.write(
                json.dumps(
                    {
                        "t": "snapshot",
                        "index": self.snap_index,
                        "term": self.snap_term,
                        "state": self._snap_state,
                        "members": members,
                    }
                )
                + "\n"
            )
            f.write(
                json.dumps(
                    {
                        "t": "term",
                        "term": self.current_term,
                        "voted_for": self.voted_for,
                    }
                )
                + "\n"
            )
            for e in self.log:
                rec = {
                    "t": "entry",
                    "term": e.term,
                    "index": e.index,
                    "kind": e.kind,
                    "value": e.value,
                }
                if e.data:
                    rec["data"] = e.data
                f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        if self._state_file:
            self._state_file.close()
        os.replace(tmp, self._state_path)
        self._state_file = open(self._state_path, "a", encoding="utf-8")

    def _maybe_compact_locked(self) -> None:
        """Fold the applied prefix into a snapshot once the log exceeds
        the threshold. The snapshot is taken EXACTLY at last_applied
        (snapshot_fn reflects every applied entry and nothing more);
        followers behind it are caught up via InstallSnapshot."""
        if (
            len(self.log) <= self.compact_threshold
            or self.last_applied <= self.snap_index
        ):
            return
        new_snap = self.last_applied
        self.snap_term = self._term_at(new_snap)
        self._snap_state = dict(self.snapshot_fn())
        del self.log[: new_snap - self.snap_index]
        self.snap_index = new_snap
        self._rewrite_state_file_locked()
        log.v(
            1,
            f"{self.node_id}: compacted log through {new_snap} "
            f"({len(self.log)} entries kept)",
        )

    # ------------------------------------------------------------ timers

    def start(self) -> None:
        if not self.peers:
            # single-master deployment: degenerate raft, instant leader
            with self._lock:
                self.current_term += 1
                self._become_leader_locked()
        t = threading.Thread(target=self._ticker, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for ch in self._channels.values():
            ch.close()
        if self._state_file:
            self._state_file.close()
            self._state_file = None

    def _election_deadline(self) -> float:
        lo, hi = self.election_timeout
        return random.uniform(lo, hi)

    def _ticker(self) -> None:
        deadline = self._election_deadline()
        while not self._stop.wait(0.02):
            with self._lock:
                role = self.role
            if role == LEADER:
                if (
                    time.monotonic() - self._last_broadcast
                    >= self.heartbeat_interval
                ):
                    self._broadcast_append()
            else:
                if (
                    not self.removed
                    and time.monotonic() - self._last_heard > deadline
                ):
                    deadline = self._election_deadline()
                    self._run_election()

    # ---------------------------------------------------------- election

    def _run_election(self) -> None:
        with self._lock:
            if not self.peers:
                return
            self.role = CANDIDATE
            self.current_term += 1
            self.voted_for = self.node_id
            self._set_leader_locked(None)  # the old leader timed out
            self._persist_term()
            term = self.current_term
            last_idx = self._last_index()
            last_term = self._term_at(last_idx)
        self._last_heard = time.monotonic()
        log.v(1, f"{self.node_id}: starting election term {term}")
        votes = 1
        req = pb.RaftVoteRequest(
            term=term,
            candidate_id=self.node_id,
            last_log_index=last_idx,
            last_log_term=last_term,
        )
        lock = threading.Lock()
        done = threading.Event()
        answered = 0

        def ask(peer: str):
            nonlocal votes, answered
            granted = False
            resp = None
            try:
                resp = self.transport.call(peer, "RaftRequestVote", req, 2)
            except TransportError:
                pass
            if resp is not None:
                with self._lock:
                    if resp.term > self.current_term:
                        self._step_down_locked(resp.term)
                        done.set()
                        return
                    granted = bool(
                        resp.granted
                        and self.role == CANDIDATE
                        and self.current_term == term
                    )
            with lock:
                answered += 1
                if granted:
                    votes += 1
                all_in = answered == len(self.peers)
                won = votes > (len(self.peers) + 1) // 2
            if won:
                with self._lock:
                    if self.role == CANDIDATE and self.current_term == term:
                        self._become_leader_locked()
                done.set()
            elif all_in:
                # Every reply (or failure) is in and there is no
                # majority: conclude NOW. Blocking the full RPC timeout
                # here re-synchronizes split-vote candidates — with
                # fast-failing peers both retry on the same 2s beat and
                # can split forever; an instant exit lets the
                # randomized election timeout actually desynchronize.
                done.set()

        threads = [
            threading.Thread(target=ask, args=(p,), daemon=True)
            for p in self.peers
        ]
        for t in threads:
            t.start()
        done.wait(timeout=2)

    def _set_leader_locked(self, leader: str | None) -> None:
        if leader == self.leader_id:
            return
        self.leader_id = leader
        if self.on_leader_change and leader:
            try:
                self.on_leader_change(leader)
            except Exception:  # noqa: BLE001 — a hook must not kill raft
                pass

    def _become_leader_locked(self) -> None:
        if self.role == LEADER:
            return
        self.role = LEADER
        self._set_leader_locked(self.node_id)
        next_idx = self._last_index() + 1
        for p in self.peers:
            self._next_index[p] = next_idx
            self._match_index[p] = 0
        log.info(f"{self.node_id}: leader for term {self.current_term}")
        # commit barrier: an entry from the current term must commit
        # before earlier-term entries count as committed (Raft §5.4.2)
        self._append_locked("noop", 0)
        if not self.peers:
            self._advance_commit_locked(self._last_index())

    def _step_down_locked(self, term: int) -> None:
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist_term()
        if self.role != FOLLOWER:
            log.info(f"{self.node_id}: stepping down (term {term})")
        self.role = FOLLOWER
        # whoever led before is no longer known-good; advertising a
        # stale leader would bounce clients at a dead address
        self._set_leader_locked(None)
        self._last_heard = time.monotonic()

    # --------------------------------------------------------------- log

    def _append_locked(self, kind: str, value: int, data: str = "") -> int:
        e = pb.RaftEntry(
            term=self.current_term,
            index=self._last_index() + 1,
            kind=kind,
            value=value,
            data=data,
        )
        self.log.append(e)
        self._persist_entry(e)
        if kind == "config":
            # membership takes effect when APPENDED (hashicorp/raft
            # semantics): a 2-node group can remove its dead member —
            # the quorum for the config entry is counted against the
            # NEW set, not the unreachable old one
            self._apply_config_locked(e, at_append=True)
        return e.index

    def propose(
        self, kind: str, value: int = 0, timeout: float = 10.0, data: str = ""
    ) -> int:
        """Leader-only: append, replicate, wait for apply; returns the
        state machine's result for the entry."""
        with self._lock:
            if self.role != LEADER:
                raise NotLeader(self.leader_id)
            term = self.current_term
            idx = self._append_locked(kind, value, data)
            # register the waiter BEFORE dropping the lock for the
            # broadcast: the eviction floor must already see idx, or a
            # descheduled proposer's committed result can be evicted
            # during the unlocked window
            self._propose_waiting.add(idx)
        try:
            self._broadcast_append()
            deadline = time.monotonic() + timeout
            with self._applied_cv:
                while self.last_applied < idx:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"raft commit timeout at index {idx}"
                        )
                    self._applied_cv.wait(remaining)
                # the entry at idx must still be OURS (a competing
                # leader may have overwritten the uncommitted suffix,
                # or an installed snapshot may have advanced
                # last_applied past an index we never applied). The
                # recorded (term, result) pins ownership even after
                # compaction.
                got = self._apply_results.get(idx)
                if got is None or got[0] != term:
                    raise NotLeader(self.leader_id)
                return got[1]
        finally:
            # covers the broadcast too: a leaked waiter would pin the
            # eviction floor for the life of the process
            with self._lock:
                self._propose_waiting.discard(idx)

    def _apply_config_locked(self, e: pb.RaftEntry, at_append: bool = False) -> None:
        try:
            members = json.loads(e.data)
        except json.JSONDecodeError:
            return
        old = sorted({self.node_id, *self.peers})
        self.peers = [m for m in members if m != self.node_id]
        for p in self.peers:
            self._next_index.setdefault(p, self._last_index() + 1)
            self._match_index.setdefault(p, 0)
        if self.node_id in members:
            self.removed = False  # a re-add must restore campaigning
        elif not at_append:
            # committed removal: stop campaigning/serving. A leader
            # removing ITSELF keeps leading until this commits (it must
            # replicate the entry first), then steps down.
            self.removed = True
            if self.role == LEADER:
                self._step_down_locked(self.current_term)
        if sorted(members) != old:
            log.info(
                f"{self.node_id}: membership {old} -> {sorted(members)}"
            )

    def _advance_commit_locked(self, new_commit: int) -> None:
        new_commit = min(new_commit, self._last_index())
        if new_commit <= self.commit_index:
            return
        self.commit_index = new_commit
        while self.last_applied < self.commit_index:
            e = self._entry_at(self.last_applied + 1)
            self.last_applied += 1
            if e.kind == "config":
                self._apply_config_locked(e)
                result = 0
            else:
                result = self.apply_fn(e.kind, e.value)
            self._apply_results[e.index] = (e.term, int(result or 0))
            if len(self._apply_results) > 4096:
                # never evict an index a propose() call still waits on
                floor = min(self._propose_waiting, default=e.index + 1)
                for k in sorted(self._apply_results)[:2048]:
                    if k >= floor:
                        break
                    del self._apply_results[k]
        self._applied_cv.notify_all()
        self._maybe_compact_locked()

    # ------------------------------------------------------- replication

    def _peer_stub(self, peer: str):
        ch = self._channels.get(peer)
        if ch is None:
            host, _, port = peer.partition(":")
            ch = grpc.insecure_channel(f"{host}:{int(port) + 10000}")
            self._channels[peer] = ch
        return rpc.Stub(ch, rpc.RAFT_SERVICE)

    def _broadcast_append(self) -> None:
        self._last_broadcast = time.monotonic()
        if not self.peers:
            # single-node group: a majority of one is the leader itself
            with self._lock:
                if self.role == LEADER:
                    self._advance_commit_locked(self._last_index())
            return
        # one replication in flight per peer: a slow/dead peer must not
        # accumulate a new blocked thread per tick. Snapshot the peer
        # list under the lock — config changes mutate it live.
        with self._lock:
            targets = [p for p in self.peers if p not in self._repl_inflight]
            self._repl_inflight.update(targets)
        for p in targets:
            threading.Thread(
                target=self._replicate_guarded, args=(p,), daemon=True
            ).start()

    def _replicate_guarded(self, peer: str) -> None:
        try:
            self._replicate_to(peer)
        finally:
            with self._lock:
                self._repl_inflight.discard(peer)

    def _replicate_to(self, peer: str) -> None:
        with self._lock:
            if self.role != LEADER:
                return
            term = self.current_term
            next_idx = self._next_index.get(peer, self._last_index() + 1)
            if next_idx <= self.snap_index:
                # the entries this follower needs were compacted away:
                # ship the snapshot instead
                snap_req = pb.RaftInstallSnapshotRequest(
                    term=term,
                    leader_id=self.node_id,
                    last_included_index=self.snap_index,
                    last_included_term=self.snap_term,
                    state=json.dumps(self._snap_state).encode(),
                    members=sorted({self.node_id, *self.peers}),
                )
            else:
                snap_req = None
                prev_idx = next_idx - 1
                prev_term = self._term_at(prev_idx)
                entries = self.log[next_idx - self.snap_index - 1 :]
                req = pb.RaftAppendRequest(
                    term=term,
                    leader_id=self.node_id,
                    prev_log_index=prev_idx,
                    prev_log_term=prev_term,
                    entries=entries,
                    leader_commit=self.commit_index,
                )
        if snap_req is not None:
            try:
                sresp = self.transport.call(
                    peer, "RaftInstallSnapshot", snap_req, 5
                )
            except TransportError:
                return
            with self._lock:
                if sresp.term > self.current_term:
                    self._step_down_locked(sresp.term)
                elif sresp.success:
                    self._match_index[peer] = snap_req.last_included_index
                    self._next_index[peer] = snap_req.last_included_index + 1
            return
        try:
            resp = self.transport.call(peer, "RaftAppendEntries", req, 2)
        except TransportError:
            return
        with self._lock:
            if resp.term > self.current_term:
                self._step_down_locked(resp.term)
                return
            if self.role != LEADER or self.current_term != term:
                return
            if resp.success:
                self._match_index[peer] = max(
                    self._match_index.get(peer, 0), resp.match_index
                )
                self._next_index[peer] = self._match_index[peer] + 1
                # majority commit (count self)
                for n in range(self._last_index(), self.commit_index, -1):
                    if self._term_at(n) != self.current_term:
                        break  # only current-term entries commit by counting
                    acks = 1 + sum(
                        1 for p in self.peers if self._match_index.get(p, 0) >= n
                    )
                    if acks > (len(self.peers) + 1) // 2:
                        self._advance_commit_locked(n)
                        break
            else:
                # fast back-up using the follower's conflict hint
                self._next_index[peer] = max(
                    1,
                    min(
                        resp.conflict_index or (next_idx - 1),
                        self._last_index() + 1,
                    ),
                )

    # ------------------------------------------------------ RPC handlers

    def RaftRequestVote(self, request: pb.RaftVoteRequest, context) -> pb.RaftVoteResponse:
        with self._lock:
            # Disruption guard (Raft thesis §4.2.3): a server REMOVED
            # from the cluster never learns it (the leader stops
            # replicating to it at the config append) and will campaign
            # with ever-higher terms forever. Deny votes — WITHOUT
            # adopting the term — while we believe a leader is alive:
            # a live leader denies always (a genuinely new leader will
            # depose it via AppendEntries), a follower denies within the
            # minimum election timeout of last leader contact.
            if request.term > self.current_term:
                if self.role == LEADER or (
                    self.leader_id is not None
                    and time.monotonic() - self._last_heard
                    < self.election_timeout[0]
                ):
                    return pb.RaftVoteResponse(
                        term=self.current_term, granted=False
                    )
                self._step_down_locked(request.term)
            granted = False
            if request.term == self.current_term and self.voted_for in (
                None,
                request.candidate_id,
            ):
                last_idx = self._last_index()
                last_term = self._term_at(last_idx)
                up_to_date = request.last_log_term > last_term or (
                    request.last_log_term == last_term
                    and request.last_log_index >= last_idx
                )
                if up_to_date:
                    granted = True
                    self.voted_for = request.candidate_id
                    self._persist_term()
                    self._last_heard = time.monotonic()
            return pb.RaftVoteResponse(term=self.current_term, granted=granted)

    def RaftAppendEntries(self, request: pb.RaftAppendRequest, context) -> pb.RaftAppendResponse:
        with self._lock:
            if request.term > self.current_term:
                self._step_down_locked(request.term)
            if request.term < self.current_term:
                return pb.RaftAppendResponse(
                    term=self.current_term, success=False
                )
            # valid leader for our term
            self.role = FOLLOWER
            self._set_leader_locked(request.leader_id)
            self._last_heard = time.monotonic()
            # log consistency check (indexes are absolute; anything at
            # or below our snapshot is already committed here)
            if request.prev_log_index > self._last_index():
                return pb.RaftAppendResponse(
                    term=self.current_term,
                    success=False,
                    conflict_index=self._last_index() + 1,
                )
            if (
                request.prev_log_index > self.snap_index
                and self._term_at(request.prev_log_index)
                != request.prev_log_term
            ):
                bad_term = self._term_at(request.prev_log_index)
                ci = request.prev_log_index
                while (
                    ci > self.snap_index + 1
                    and self._term_at(ci - 1) == bad_term
                ):
                    ci -= 1
                return pb.RaftAppendResponse(
                    term=self.current_term, success=False, conflict_index=ci
                )
            # append / overwrite conflicts
            for e in request.entries:
                if e.index <= self.snap_index:
                    continue  # folded into our snapshot already
                if e.index <= self._last_index():
                    if self._term_at(e.index) == e.term:
                        continue  # already have it
                    self._truncate_from(e.index)
                    self._persist({"t": "truncate", "index": e.index})
                self.log.append(e)
                self._persist_entry(e)
                if e.kind == "config":
                    # follower adopts the membership at append, like
                    # the leader (at_append: no step-down until commit)
                    self._apply_config_locked(e, at_append=True)
            if request.leader_commit > self.commit_index:
                self._advance_commit_locked(request.leader_commit)
            return pb.RaftAppendResponse(
                term=self.current_term,
                success=True,
                match_index=request.prev_log_index + len(request.entries),
            )

    def RaftInstallSnapshot(
        self, request: pb.RaftInstallSnapshotRequest, context
    ) -> pb.RaftInstallSnapshotResponse:
        with self._lock:
            if request.term > self.current_term:
                self._step_down_locked(request.term)
            if request.term < self.current_term:
                return pb.RaftInstallSnapshotResponse(
                    term=self.current_term, success=False
                )
            self.role = FOLLOWER
            self._set_leader_locked(request.leader_id)
            self._last_heard = time.monotonic()
            if request.last_included_index <= max(
                self.snap_index, self.last_applied
            ):
                # Stale snapshot: the state machine has already applied
                # past last_included_index (a leader conflict-hint walk
                # can back next_index below a follower's applied point).
                # Restoring would roll the state machine back while
                # last_applied stays ahead, silently losing the entries
                # in (lii, last_applied] — acknowledge without acting.
                return pb.RaftInstallSnapshotResponse(
                    term=self.current_term, success=True
                )
            try:
                state = json.loads(request.state or b"{}")
            except json.JSONDecodeError:
                state = {}
            # keep any log suffix newer than the snapshot; drop the rest
            if (
                self._last_index() > request.last_included_index
                and self._term_at(request.last_included_index)
                == request.last_included_term
            ):
                del self.log[
                    : request.last_included_index - self.snap_index
                ]
            else:
                self.log = []
            self.snap_index = request.last_included_index
            self.snap_term = request.last_included_term
            self._snap_state = state
            self.restore_fn(state)
            if request.members:
                self.peers = [
                    m for m in request.members if m != self.node_id
                ]
            self.commit_index = max(self.commit_index, self.snap_index)
            self.last_applied = max(self.last_applied, self.snap_index)
            self._rewrite_state_file_locked()
            self._applied_cv.notify_all()
            return pb.RaftInstallSnapshotResponse(
                term=self.current_term, success=True
            )

    # -------------------------------------------------------- membership

    def add_server(self, server: str) -> list[str]:
        return self._change_membership("add", server)

    def remove_server(self, server: str) -> list[str]:
        return self._change_membership("remove", server)

    def _change_membership(self, op: str, server: str) -> list[str]:
        """Sequential single-server change (Raft §6 one-at-a-time rule:
        any two consecutive memberships differing by one server always
        share a majority, so joint consensus is unnecessary). The
        membership lock serializes concurrent admin calls end-to-end —
        without it two changes could both base off the same set and the
        second would silently undo the first."""
        with self._membership_lock:
            with self._lock:
                if self.role != LEADER:
                    raise NotLeader(self.leader_id)
                for e in self.log[self.commit_index - self.snap_index :]:
                    if e.kind == "config":
                        raise RuntimeError(
                            "a membership change is already in flight"
                        )
                members = sorted({self.node_id, *self.peers})
                if op == "add":
                    if server in members:
                        return members
                    members = sorted({*members, server})
                else:
                    if server not in members:
                        return members
                    members = sorted(m for m in members if m != server)
                    if not members:
                        raise RuntimeError("cannot remove the last member")
            self.propose("config", data=json.dumps(members), timeout=10.0)
            return members

    def RaftChangeMembership(
        self, request: pb.RaftChangeRequest, context
    ) -> pb.RaftChangeResponse:
        try:
            if request.op == "add":
                members = self.add_server(request.server)
            elif request.op == "remove":
                members = self.remove_server(request.server)
            else:
                return pb.RaftChangeResponse(error=f"bad op {request.op!r}")
        except NotLeader as e:
            return pb.RaftChangeResponse(
                error="not the leader", leader=e.leader or ""
            )
        except (RuntimeError, TimeoutError) as e:
            return pb.RaftChangeResponse(error=str(e))
        return pb.RaftChangeResponse(
            members=members, leader=self.leader_id or ""
        )

    def RaftStatus(self, request, context) -> pb.RaftStatusResponse:
        with self._lock:
            return pb.RaftStatusResponse(
                node_id=self.node_id,
                leader=self.leader_id or "",
                term=self.current_term,
                role=self.role,
                peers=list(self.peers),
                commit_index=self.commit_index,
                applied_index=self.last_applied,
            )

    # ----------------------------------------------------------- queries

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self.role == LEADER

    @property
    def leader(self) -> str | None:
        with self._lock:
            return self.leader_id


class NotLeader(Exception):
    def __init__(self, leader: str | None):
        super().__init__(f"not the leader (try {leader or 'unknown'})")
        self.leader = leader
