"""Raft consensus for master HA.

Reference: weed/server/raft_hashicorp.go + raft_server.go — the
reference replicates the max-volume-id/sequencer allocation state across
masters and derives leadership for the topology (`Topo.IsLeader`,
topology.go:245). This is an original, compact Raft (leader election,
log replication, majority commit, durable term/vote/log) specialised to
that small state machine; topology itself is NOT replicated — it is
rebuilt from volume-server heartbeats on whichever master leads, exactly
like the reference.

State machine commands:
  alloc_volume_id(value=hint) -> applied result max(state, hint) + 1
  noop                        -> leader barrier entry on election

Persistence: one JSON-lines file per node (term/vote records and log
entries), fsynced on every durable mutation before any RPC response
that promises it — the same discipline the storage engine uses.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time

import grpc

from ..pb import cluster_pb2 as pb
from ..pb import rpc
from ..utils.glog import logger

log = logger("raft")

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class RaftNode:
    """One master's raft participant.

    `node_id` / `peers` are the masters' HTTP host:port addresses (the
    cluster-wide names); RPCs go to port+10000 like every other service.
    `apply_fn(kind, value) -> result` runs under the node lock in log
    order exactly once per committed entry.
    """

    def __init__(
        self,
        node_id: str,
        peers: list[str],
        state_dir: str | None = None,
        apply_fn=None,
        election_timeout: tuple[float, float] = (0.4, 0.8),
        heartbeat_interval: float = 0.1,
    ):
        self.node_id = node_id
        self.peers = [p for p in peers if p != node_id]
        self.apply_fn = apply_fn or (lambda kind, value: 0)
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval

        self._lock = threading.RLock()
        self._applied_cv = threading.Condition(self._lock)
        self.role = FOLLOWER
        self.current_term = 0
        self.voted_for: str | None = None
        self.log: list[pb.RaftEntry] = []  # index 1-based: log[i-1]
        self.commit_index = 0
        self.last_applied = 0
        self.leader_id: str | None = None
        self._apply_results: dict[int, int] = {}
        # leader volatile state
        self._next_index: dict[str, int] = {}
        self._match_index: dict[str, int] = {}

        self._state_path = (
            os.path.join(state_dir, "raft.jsonl") if state_dir else None
        )
        self._state_file = None
        self._load_state()

        self._stop = threading.Event()
        self._last_heard = time.monotonic()
        self._last_broadcast = 0.0
        self._repl_inflight: set[str] = set()
        self._channels: dict[str, grpc.Channel] = {}
        self._threads: list[threading.Thread] = []
        # hook(leader_addr) fired whenever the known leader changes
        # (election won, or a valid leader's first append) — the master
        # uses it to notify KeepConnected sessions
        self.on_leader_change = None

    # ------------------------------------------------------- persistence

    def _load_state(self) -> None:
        if not self._state_path or not os.path.exists(self._state_path):
            return
        with open(self._state_path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    break  # torn tail from a crash: ignore the partial record
                if rec["t"] == "term":
                    self.current_term = rec["term"]
                    self.voted_for = rec.get("voted_for")
                elif rec["t"] == "entry":
                    e = pb.RaftEntry(
                        term=rec["term"],
                        index=rec["index"],
                        kind=rec["kind"],
                        value=rec.get("value", 0),
                    )
                    # replace any conflicting suffix, then append
                    del self.log[e.index - 1 :]
                    self.log.append(e)
                elif rec["t"] == "truncate":
                    del self.log[rec["index"] - 1 :]

    def _persist(self, rec: dict) -> None:
        if not self._state_path:
            return
        if self._state_file is None:
            self._state_file = open(self._state_path, "a", encoding="utf-8")
        self._state_file.write(json.dumps(rec) + "\n")
        self._state_file.flush()
        os.fsync(self._state_file.fileno())

    def _persist_term(self) -> None:
        self._persist(
            {"t": "term", "term": self.current_term, "voted_for": self.voted_for}
        )

    def _persist_entry(self, e: pb.RaftEntry) -> None:
        self._persist(
            {
                "t": "entry",
                "term": e.term,
                "index": e.index,
                "kind": e.kind,
                "value": e.value,
            }
        )

    # ------------------------------------------------------------ timers

    def start(self) -> None:
        if not self.peers:
            # single-master deployment: degenerate raft, instant leader
            with self._lock:
                self.current_term += 1
                self._become_leader_locked()
        t = threading.Thread(target=self._ticker, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for ch in self._channels.values():
            ch.close()
        if self._state_file:
            self._state_file.close()
            self._state_file = None

    def _election_deadline(self) -> float:
        lo, hi = self.election_timeout
        return random.uniform(lo, hi)

    def _ticker(self) -> None:
        deadline = self._election_deadline()
        while not self._stop.wait(0.02):
            with self._lock:
                role = self.role
            if role == LEADER:
                if (
                    time.monotonic() - self._last_broadcast
                    >= self.heartbeat_interval
                ):
                    self._broadcast_append()
            else:
                if time.monotonic() - self._last_heard > deadline:
                    deadline = self._election_deadline()
                    self._run_election()

    # ---------------------------------------------------------- election

    def _run_election(self) -> None:
        with self._lock:
            if not self.peers:
                return
            self.role = CANDIDATE
            self.current_term += 1
            self.voted_for = self.node_id
            self._set_leader_locked(None)  # the old leader timed out
            self._persist_term()
            term = self.current_term
            last_idx = len(self.log)
            last_term = self.log[-1].term if self.log else 0
        self._last_heard = time.monotonic()
        log.v(1, f"{self.node_id}: starting election term {term}")
        votes = 1
        req = pb.RaftVoteRequest(
            term=term,
            candidate_id=self.node_id,
            last_log_index=last_idx,
            last_log_term=last_term,
        )
        lock = threading.Lock()
        done = threading.Event()

        def ask(peer: str):
            nonlocal votes
            try:
                resp = self._peer_stub(peer).RaftRequestVote(req, timeout=2)
            except grpc.RpcError:
                return
            with self._lock:
                if resp.term > self.current_term:
                    self._step_down_locked(resp.term)
                    done.set()
                    return
                if (
                    resp.granted
                    and self.role == CANDIDATE
                    and self.current_term == term
                ):
                    with lock:
                        votes += 1
                        if votes > (len(self.peers) + 1) // 2:
                            self._become_leader_locked()
                            done.set()

        threads = [
            threading.Thread(target=ask, args=(p,), daemon=True)
            for p in self.peers
        ]
        for t in threads:
            t.start()
        done.wait(timeout=2)

    def _set_leader_locked(self, leader: str | None) -> None:
        if leader == self.leader_id:
            return
        self.leader_id = leader
        if self.on_leader_change and leader:
            try:
                self.on_leader_change(leader)
            except Exception:  # noqa: BLE001 — a hook must not kill raft
                pass

    def _become_leader_locked(self) -> None:
        if self.role == LEADER:
            return
        self.role = LEADER
        self._set_leader_locked(self.node_id)
        next_idx = len(self.log) + 1
        for p in self.peers:
            self._next_index[p] = next_idx
            self._match_index[p] = 0
        log.info(f"{self.node_id}: leader for term {self.current_term}")
        # commit barrier: an entry from the current term must commit
        # before earlier-term entries count as committed (Raft §5.4.2)
        self._append_locked("noop", 0)
        if not self.peers:
            self._advance_commit_locked(len(self.log))

    def _step_down_locked(self, term: int) -> None:
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist_term()
        if self.role != FOLLOWER:
            log.info(f"{self.node_id}: stepping down (term {term})")
        self.role = FOLLOWER
        # whoever led before is no longer known-good; advertising a
        # stale leader would bounce clients at a dead address
        self._set_leader_locked(None)
        self._last_heard = time.monotonic()

    # --------------------------------------------------------------- log

    def _append_locked(self, kind: str, value: int) -> int:
        e = pb.RaftEntry(
            term=self.current_term, index=len(self.log) + 1, kind=kind, value=value
        )
        self.log.append(e)
        self._persist_entry(e)
        return e.index

    def propose(self, kind: str, value: int = 0, timeout: float = 10.0) -> int:
        """Leader-only: append, replicate, wait for apply; returns the
        state machine's result for the entry."""
        with self._lock:
            if self.role != LEADER:
                raise NotLeader(self.leader_id)
            term = self.current_term
            idx = self._append_locked(kind, value)
        self._broadcast_append()
        deadline = time.monotonic() + timeout
        with self._applied_cv:
            while self.last_applied < idx:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"raft commit timeout at index {idx}")
                self._applied_cv.wait(remaining)
            # the entry at idx must still be OURS (a competing leader
            # may have overwritten the uncommitted suffix)
            if idx > len(self.log) or self.log[idx - 1].term != term:
                raise NotLeader(self.leader_id)
            return self._apply_results.get(idx, 0)

    def _advance_commit_locked(self, new_commit: int) -> None:
        new_commit = min(new_commit, len(self.log))
        if new_commit <= self.commit_index:
            return
        self.commit_index = new_commit
        while self.last_applied < self.commit_index:
            e = self.log[self.last_applied]
            self.last_applied += 1
            result = self.apply_fn(e.kind, e.value)
            self._apply_results[e.index] = int(result or 0)
            if len(self._apply_results) > 4096:
                for k in sorted(self._apply_results)[:2048]:
                    del self._apply_results[k]
        self._applied_cv.notify_all()

    # ------------------------------------------------------- replication

    def _peer_stub(self, peer: str):
        ch = self._channels.get(peer)
        if ch is None:
            host, _, port = peer.partition(":")
            ch = grpc.insecure_channel(f"{host}:{int(port) + 10000}")
            self._channels[peer] = ch
        return rpc.Stub(ch, rpc.RAFT_SERVICE)

    def _broadcast_append(self) -> None:
        self._last_broadcast = time.monotonic()
        if not self.peers:
            # single-node group: a majority of one is the leader itself
            with self._lock:
                if self.role == LEADER:
                    self._advance_commit_locked(len(self.log))
            return
        # one replication in flight per peer: a slow/dead peer must not
        # accumulate a new blocked thread per tick
        with self._lock:
            targets = [p for p in self.peers if p not in self._repl_inflight]
            self._repl_inflight.update(targets)
        for p in targets:
            threading.Thread(
                target=self._replicate_guarded, args=(p,), daemon=True
            ).start()

    def _replicate_guarded(self, peer: str) -> None:
        try:
            self._replicate_to(peer)
        finally:
            with self._lock:
                self._repl_inflight.discard(peer)

    def _replicate_to(self, peer: str) -> None:
        with self._lock:
            if self.role != LEADER:
                return
            term = self.current_term
            next_idx = self._next_index.get(peer, len(self.log) + 1)
            prev_idx = next_idx - 1
            prev_term = self.log[prev_idx - 1].term if prev_idx >= 1 and prev_idx <= len(self.log) else 0
            entries = self.log[next_idx - 1 :]
            req = pb.RaftAppendRequest(
                term=term,
                leader_id=self.node_id,
                prev_log_index=prev_idx,
                prev_log_term=prev_term,
                entries=entries,
                leader_commit=self.commit_index,
            )
        try:
            resp = self._peer_stub(peer).RaftAppendEntries(req, timeout=2)
        except grpc.RpcError:
            return
        with self._lock:
            if resp.term > self.current_term:
                self._step_down_locked(resp.term)
                return
            if self.role != LEADER or self.current_term != term:
                return
            if resp.success:
                self._match_index[peer] = max(
                    self._match_index.get(peer, 0), resp.match_index
                )
                self._next_index[peer] = self._match_index[peer] + 1
                # majority commit (count self)
                for n in range(len(self.log), self.commit_index, -1):
                    if self.log[n - 1].term != self.current_term:
                        break  # only current-term entries commit by counting
                    acks = 1 + sum(
                        1 for p in self.peers if self._match_index.get(p, 0) >= n
                    )
                    if acks > (len(self.peers) + 1) // 2:
                        self._advance_commit_locked(n)
                        break
            else:
                # fast back-up using the follower's conflict hint
                self._next_index[peer] = max(
                    1, min(resp.conflict_index or (next_idx - 1), len(self.log) + 1)
                )

    # ------------------------------------------------------ RPC handlers

    def RaftRequestVote(self, request: pb.RaftVoteRequest, context) -> pb.RaftVoteResponse:
        with self._lock:
            if request.term > self.current_term:
                self._step_down_locked(request.term)
            granted = False
            if request.term == self.current_term and self.voted_for in (
                None,
                request.candidate_id,
            ):
                last_idx = len(self.log)
                last_term = self.log[-1].term if self.log else 0
                up_to_date = request.last_log_term > last_term or (
                    request.last_log_term == last_term
                    and request.last_log_index >= last_idx
                )
                if up_to_date:
                    granted = True
                    self.voted_for = request.candidate_id
                    self._persist_term()
                    self._last_heard = time.monotonic()
            return pb.RaftVoteResponse(term=self.current_term, granted=granted)

    def RaftAppendEntries(self, request: pb.RaftAppendRequest, context) -> pb.RaftAppendResponse:
        with self._lock:
            if request.term > self.current_term:
                self._step_down_locked(request.term)
            if request.term < self.current_term:
                return pb.RaftAppendResponse(
                    term=self.current_term, success=False
                )
            # valid leader for our term
            self.role = FOLLOWER
            self._set_leader_locked(request.leader_id)
            self._last_heard = time.monotonic()
            # log consistency check
            if request.prev_log_index > len(self.log):
                return pb.RaftAppendResponse(
                    term=self.current_term,
                    success=False,
                    conflict_index=len(self.log) + 1,
                )
            if (
                request.prev_log_index >= 1
                and self.log[request.prev_log_index - 1].term
                != request.prev_log_term
            ):
                bad_term = self.log[request.prev_log_index - 1].term
                ci = request.prev_log_index
                while ci > 1 and self.log[ci - 2].term == bad_term:
                    ci -= 1
                return pb.RaftAppendResponse(
                    term=self.current_term, success=False, conflict_index=ci
                )
            # append / overwrite conflicts
            for e in request.entries:
                if e.index <= len(self.log):
                    if self.log[e.index - 1].term == e.term:
                        continue  # already have it
                    del self.log[e.index - 1 :]
                    self._persist({"t": "truncate", "index": e.index})
                self.log.append(e)
                self._persist_entry(e)
            if request.leader_commit > self.commit_index:
                self._advance_commit_locked(request.leader_commit)
            return pb.RaftAppendResponse(
                term=self.current_term,
                success=True,
                match_index=request.prev_log_index + len(request.entries),
            )

    def RaftStatus(self, request, context) -> pb.RaftStatusResponse:
        with self._lock:
            return pb.RaftStatusResponse(
                node_id=self.node_id,
                leader=self.leader_id or "",
                term=self.current_term,
                role=self.role,
                peers=list(self.peers),
                commit_index=self.commit_index,
                applied_index=self.last_applied,
            )

    # ----------------------------------------------------------- queries

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self.role == LEADER

    @property
    def leader(self) -> str | None:
        with self._lock:
            return self.leader_id


class NotLeader(Exception):
    def __init__(self, leader: str | None):
        super().__init__(f"not the leader (try {leader or 'unknown'})")
        self.leader = leader
