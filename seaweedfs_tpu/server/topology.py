"""Master-side cluster topology: DC → rack → DataNode tree, volume
layouts, EC shard map.

Reference: weed/topology (Topology topology.go:38, VolumeLayout
volume_layout.go, growth volume_growth.go:98). Registration comes from
heartbeats (SyncDataNodeRegistration topology.go:579, incremental
:632); nodes live in a nested DataCenter/Rack tree with a flat id
index alongside. Rack-aware EC placement planning lives in
ec/placement.py.
"""

from __future__ import annotations

import json
import queue
import random
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Optional

from ..pb import cluster_pb2 as pb
from ..utils import metrics as _M


@dataclass
class DataNode:
    node_id: str  # "ip:port"
    ip: str
    port: int
    public_url: str
    grpc_port: int
    data_center: str = ""
    rack: str = ""
    max_volume_count: int = 8
    volumes: dict[int, pb.VolumeInfoMsg] = field(default_factory=dict)
    ec_shards: dict[int, pb.EcShardInfoMsg] = field(default_factory=dict)
    last_seen: float = field(default_factory=time.time)
    # identity of the heartbeat stream currently feeding this node; a
    # stale stream's cleanup must not unregister a node a newer stream owns
    owner_token: object = None
    # device-telemetry blob learned ONLY from heartbeats
    # (Heartbeat.ec_telemetry_json): per-chip queue load + breaker
    # state + stage EWMAs. Surfaced in /cluster/status and the
    # sw_ec_queue_load fleet gauges; {} until the node reports.
    ec_telemetry: dict = field(default_factory=dict)

    def location(self) -> pb.Location:
        return pb.Location(
            url=f"{self.ip}:{self.port}",
            public_url=self.public_url,
            grpc_port=self.grpc_port,
            data_center=self.data_center,
        )

    def free_slots(self) -> int:
        used = len(self.volumes) + (len(self.ec_shards) + 9) // 10
        return max(self.max_volume_count - used, 0)


@dataclass
class Rack:
    """DC → rack → DataNode tree level (reference weed/topology Rack)."""

    name: str
    nodes: dict[str, DataNode] = field(default_factory=dict)

    def free_slots(self) -> int:
        return sum(n.free_slots() for n in self.nodes.values())


@dataclass
class DataCenter:
    name: str
    racks: dict[str, Rack] = field(default_factory=dict)

    def free_slots(self) -> int:
        return sum(r.free_slots() for r in self.racks.values())

    def all_nodes(self):
        for r in self.racks.values():
            yield from r.nodes.values()


class Topology:
    def __init__(
        self,
        volume_size_limit: int = 30 * 1024**3,
        dead_after: float = 30.0,
        sequencer=None,
    ):
        self.volume_size_limit = volume_size_limit
        self.dead_after = dead_after
        self._lock = threading.RLock()
        self.nodes: dict[str, DataNode] = {}
        # per-volume auto-vacuum opt-out (volume.vacuum.disable)
        self.vacuum_disabled: set[int] = set()
        # nested tree view (reference Topology: DC -> rack -> node);
        # self.nodes stays the flat id index into the same DataNode
        # objects
        self.data_centers: dict[str, DataCenter] = {}
        self.max_volume_id = 0
        if sequencer is None:
            # snowflake: needle ids must survive master restarts — a
            # reused id would overwrite an existing blob in its volume
            from ..utils.sequence import SnowflakeSequencer

            sequencer = SnowflakeSequencer()
        self._sequencer = sequencer
        # KeepConnected subscribers: queues fed a VolumeLocationUpdate
        # per topology change (reference master KeepConnected streaming)
        self._subscribers: list[queue.Queue] = []
        # fleet telemetry gauges sample every live topology at scrape
        # time (weak: a test's dead master must not pin stale series)
        _topologies.add(self)

    # ----------------------------------------------------- keepconnected

    def subscribe(self) -> tuple[queue.Queue, list[pb.VolumeLocationUpdate]]:
        """Register a KeepConnected session: returns (delta queue, full
        snapshot — one update per node listing everything it holds)."""
        with self._lock:
            q: queue.Queue = queue.Queue(maxsize=4096)
            q.overflowed = False
            self._subscribers.append(q)
            snapshot = [
                pb.VolumeLocationUpdate(
                    url=f"{n.ip}:{n.port}",
                    public_url=n.public_url,
                    grpc_port=n.grpc_port,
                    new_vids=sorted(n.volumes),
                    new_ec_vids=sorted(n.ec_shards),
                )
                for n in self.nodes.values()
            ]
            return q, snapshot

    def unsubscribe(self, q: queue.Queue) -> None:
        with self._lock:
            if q in self._subscribers:
                self._subscribers.remove(q)

    def _publish_locked(self, update: pb.VolumeLocationUpdate) -> None:
        for q in list(self._subscribers):
            try:
                q.put_nowait(update)
            except queue.Full:
                # a wedged client must NOT keep serving its now-stale
                # map as authoritative: poison the session so the
                # KeepConnected loop ends the stream and the client
                # reconnects with a fresh snapshot
                q.overflowed = True
                self._subscribers.remove(q)

    def publish_leader(self, leader: str) -> None:
        """Push a leader-change notice to every session (clients
        reconnect to the new leader)."""
        with self._lock:
            self._publish_locked(pb.VolumeLocationUpdate(leader=leader))

    def _node_delta_locked(
        self,
        node: DataNode,
        new_vids=(),
        deleted_vids=(),
        new_ec=(),
        deleted_ec=(),
        gone: bool = False,
    ) -> None:
        if not (new_vids or deleted_vids or new_ec or deleted_ec or gone):
            return
        self._publish_locked(
            pb.VolumeLocationUpdate(
                url=f"{node.ip}:{node.port}",
                public_url=node.public_url,
                grpc_port=node.grpc_port,
                new_vids=sorted(new_vids),
                deleted_vids=sorted(deleted_vids),
                new_ec_vids=sorted(new_ec),
                deleted_ec_vids=sorted(deleted_ec),
                server_gone=gone,
            )
        )

    # -------------------------------------------------------- heartbeats

    @staticmethod
    def _absorb_telemetry(node: DataNode, hb: pb.Heartbeat) -> None:
        """Adopt the heartbeat's device-telemetry blob (best-effort: a
        malformed blob from a skewed-version server must never poison
        registration)."""
        if not hb.ec_telemetry_json:
            return
        try:
            tele = json.loads(hb.ec_telemetry_json)
        except ValueError:
            return
        if isinstance(tele, dict):
            tele["received_at"] = time.time()
            node.ec_telemetry = tele

    def sync_registration(self, node: DataNode, hb: pb.Heartbeat) -> None:
        """Full-list registration (first heartbeat / periodic refresh)."""
        with self._lock:
            # re-insert if a stale stream's cleanup raced us out
            self.nodes.setdefault(node.node_id, node)
            old_vids = set(node.volumes)
            old_ec = set(node.ec_shards)
            if hb.volumes or hb.has_no_volumes:
                node.volumes = {v.id: v for v in hb.volumes}
            if hb.ec_shards or hb.has_no_ec_shards:
                node.ec_shards = {e.id: e for e in hb.ec_shards}
            for v in node.volumes.values():
                self.max_volume_id = max(self.max_volume_id, v.id)
            node.last_seen = time.time()
            self._absorb_telemetry(node, hb)
            self._node_delta_locked(
                node,
                new_vids=set(node.volumes) - old_vids,
                deleted_vids=old_vids - set(node.volumes),
                new_ec=set(node.ec_shards) - old_ec,
                deleted_ec=old_ec - set(node.ec_shards),
            )

    def incremental_update(self, node: DataNode, hb: pb.Heartbeat) -> None:
        with self._lock:
            added_vids, removed_vids = set(), set()
            added_ec, removed_ec = set(), set()
            for v in hb.new_volumes:
                if v.id not in node.volumes:
                    added_vids.add(v.id)
                node.volumes[v.id] = v
                self.max_volume_id = max(self.max_volume_id, v.id)
            for vid in hb.deleted_volumes:
                if node.volumes.pop(vid, None) is not None:
                    removed_vids.add(vid)
            for e in hb.new_ec_shards:
                cur = node.ec_shards.get(e.id)
                if cur is not None:
                    if e.generation < cur.generation:
                        continue  # stale report loses to the newer generation
                    if e.generation == cur.generation:
                        e.shard_bits |= cur.shard_bits
                else:
                    added_ec.add(e.id)
                node.ec_shards[e.id] = e
            for e in hb.deleted_ec_shards:
                cur = node.ec_shards.get(e.id)
                if cur is None:
                    continue
                cur.shard_bits &= ~e.shard_bits
                if cur.shard_bits == 0:
                    node.ec_shards.pop(e.id, None)
                    removed_ec.add(e.id)
            node.last_seen = time.time()
            self._absorb_telemetry(node, hb)
            self._node_delta_locked(
                node,
                new_vids=added_vids,
                deleted_vids=removed_vids,
                new_ec=added_ec,
                deleted_ec=removed_ec,
            )

    def register_node(self, hb: pb.Heartbeat) -> DataNode:
        with self._lock:
            node_id = f"{hb.ip}:{hb.port}"
            node = self.nodes.get(node_id)
            if node is None:
                node = DataNode(
                    node_id=node_id,
                    ip=hb.ip,
                    port=hb.port,
                    public_url=hb.public_url or node_id,
                    grpc_port=hb.grpc_port,
                    data_center=hb.data_center,
                    rack=hb.rack,
                    max_volume_count=int(hb.max_volume_count) or 8,
                )
                self.nodes[node_id] = node
                self._tree_add_locked(node)
            if hb.max_volume_count:
                node.max_volume_count = int(hb.max_volume_count)
            return node

    def _tree_add_locked(self, node: DataNode) -> None:
        dc = self.data_centers.setdefault(
            node.data_center, DataCenter(node.data_center)
        )
        rack = dc.racks.setdefault(node.rack, Rack(node.rack))
        rack.nodes[node.node_id] = node

    def _tree_remove_locked(self, node: DataNode) -> None:
        dc = self.data_centers.get(node.data_center)
        if dc is None:
            return
        rack = dc.racks.get(node.rack)
        if rack is None:
            return
        rack.nodes.pop(node.node_id, None)
        if not rack.nodes:
            dc.racks.pop(node.rack, None)
        if not dc.racks:
            self.data_centers.pop(node.data_center, None)

    def unregister_node(self, node_id: str, owner_token: object = None) -> None:
        """With `owner_token`, remove only if that stream still owns the
        node (reconnect-race guard)."""
        with self._lock:
            node = self.nodes.get(node_id)
            if node is None:
                return
            if owner_token is not None and node.owner_token is not owner_token:
                return
            self.nodes.pop(node_id, None)
            self._tree_remove_locked(node)
            self._node_delta_locked(node, gone=True)

    def collections(self) -> list[str]:
        with self._lock:
            cols = set()
            for n in self.nodes.values():
                for v in n.volumes.values():
                    cols.add(v.collection)
                for e in n.ec_shards.values():
                    cols.add(e.collection)
            return sorted(cols)

    def prune_dead(self) -> list[str]:
        cutoff = time.time() - self.dead_after
        with self._lock:
            dead = [nid for nid, n in self.nodes.items() if n.last_seen < cutoff]
            for nid in dead:
                node = self.nodes.pop(nid)
                self._tree_remove_locked(node)
                self._node_delta_locked(node, gone=True)
            return dead

    # ------------------------------------------------------------ lookup

    def lookup(self, vid: int) -> list[pb.Location]:
        with self._lock:
            return [
                n.location() for n in self.nodes.values() if vid in n.volumes
            ]

    def lookup_ec(self, vid: int) -> dict[int, list[pb.Location]]:
        """shard_id -> locations."""
        with self._lock:
            out: dict[int, list[pb.Location]] = {}
            for n in self.nodes.values():
                e = n.ec_shards.get(vid)
                if e is None:
                    continue
                for sid in range(32):
                    if e.shard_bits & (1 << sid):
                        out.setdefault(sid, []).append(n.location())
            return out

    # ---------------------------------------------------- write planning

    def next_needle_id(self) -> int:
        return self._sequencer.next_id()

    def next_volume_id(self) -> int:
        with self._lock:
            self.max_volume_id += 1
            return self.max_volume_id

    def optimistic_add_volume(self, node: DataNode, vol: pb.VolumeInfoMsg) -> None:
        """Register a just-allocated volume before its heartbeat
        confirms it — and PUBLISH the delta, so KeepConnected sessions
        learn new volumes without waiting a heartbeat period."""
        with self._lock:
            fresh = vol.id not in node.volumes
            node.volumes[vol.id] = vol
            self.max_volume_id = max(self.max_volume_id, vol.id)
            if fresh:
                self._node_delta_locked(node, new_vids=(vol.id,))

    def apply_allocated_volume_id(self, hint: int) -> int:
        """Raft state-machine apply: allocate past both the replicated
        max and the heartbeat-observed max (`hint` is the proposer's
        view; followers converge on the same value in log order)."""
        with self._lock:
            self.max_volume_id = max(self.max_volume_id, hint) + 1
            return self.max_volume_id

    # a volume at this fraction of the size limit is "crowded": still
    # writable, but the layout steers new writes elsewhere and asks for
    # growth before the bucket fills (reference volume_layout.go
    # crowded-state transitions)
    CROWDED_FRACTION = 0.9

    def writable_volumes(
        self,
        collection: str,
        replication: str,
        ttl: str = "",
        disk_type: str = "",
    ) -> list[tuple[int, list[DataNode]]]:
        """(vid, holders) for volumes writable under the given policy.
        The (collection, replication, ttl, diskType) tuple buckets
        volumes the way the reference's VolumeLayout does."""
        copies = _replica_copies(replication)
        with self._lock:
            by_vid: dict[int, list[DataNode]] = {}
            for n in self.nodes.values():
                for v in n.volumes.values():
                    if (
                        v.collection == collection
                        and not v.read_only
                        and v.size < self.volume_size_limit
                        and (not replication or v.replica_placement == replication)
                        and v.ttl == (ttl or "")
                        and (
                            not disk_type
                            or (v.disk_type or "hdd") == disk_type
                        )
                    ):
                        by_vid.setdefault(v.id, []).append(n)
            return [
                (vid, holders)
                for vid, holders in sorted(by_vid.items())
                if len(holders) >= copies
            ]

    def _is_crowded(self, vid: int, holders: list[DataNode]) -> bool:
        limit = self.volume_size_limit * self.CROWDED_FRACTION
        return any(
            n.volumes[vid].size >= limit for n in holders if vid in n.volumes
        )

    def pick_for_write(
        self,
        collection: str,
        replication: str,
        ttl: str = "",
        disk_type: str = "",
    ) -> Optional[tuple[int, list[DataNode]]]:
        candidates = self.writable_volumes(
            collection, replication, ttl, disk_type
        )
        if not candidates:
            return None
        roomy = [
            c for c in candidates if not self._is_crowded(c[0], c[1])
        ]
        # crowded volumes are a last resort, not an equal choice
        return random.choice(roomy or candidates)

    def all_crowded(
        self,
        collection: str,
        replication: str,
        ttl: str = "",
        disk_type: str = "",
    ) -> bool:
        """True when the bucket is writable only through crowded
        volumes — the master's cue to grow BEFORE writes start
        failing (reference crowded → grow transition)."""
        candidates = self.writable_volumes(
            collection, replication, ttl, disk_type
        )
        return bool(candidates) and all(
            self._is_crowded(vid, holders) for vid, holders in candidates
        )

    def plan_growth(self, replication: str) -> list[DataNode]:
        """Pick target nodes for one new volume honoring the replica
        placement code XYZ: X copies on other data centers, Y on other
        racks (same DC), Z on other servers of the same rack (reference
        findEmptySlotsForOneVolume, volume_growth.go:192)."""
        from ..storage.super_block import ReplicaPlacement

        try:
            rp = ReplicaPlacement.parse(replication or "000")
        except ValueError:
            return []
        x, y, z = rp.diff_data_centers, rp.diff_racks, rp.same_rack

        def pick_per_group(groups, count, exclude_key):
            """One available node per distinct group — each diff-DC /
            diff-rack copy must land on a DIFFERENT DC/rack. None =
            unsatisfiable. Groups ordered by aggregate free slots."""
            if count == 0:
                return []
            picked = []
            for key, members in sorted(
                groups.items(),
                key=lambda kv: -sum(n.free_slots() for n in kv[1]),
            ):
                if key == exclude_key:
                    continue
                avail = [n for n in members if n.free_slots() > 0]
                if not avail:
                    continue
                picked.append(max(avail, key=lambda n: n.free_slots()))
                if len(picked) == count:
                    return picked
            return None

        with self._lock:
            avail = sorted(
                (n for n in self.nodes.values() if n.free_slots() > 0),
                key=lambda n: -n.free_slots(),
            )
            if len(avail) < 1 + x + y + z:
                return []
            for primary in avail:
                dc = self.data_centers.get(primary.data_center)
                if dc is None:
                    continue
                rack = dc.racks.get(primary.rack)
                same_rack = [
                    n
                    for n in (rack.nodes.values() if rack else ())
                    if n is not primary and n.free_slots() > 0
                ]
                other_rack = pick_per_group(
                    {
                        rk: list(r.nodes.values())
                        for rk, r in dc.racks.items()
                    },
                    count=y,
                    exclude_key=primary.rack,
                )
                other_dc = pick_per_group(
                    {
                        dk: list(d.all_nodes())
                        for dk, d in self.data_centers.items()
                    },
                    count=x,
                    exclude_key=primary.data_center,
                )
                if (
                    len(same_rack) >= z
                    and other_rack is not None
                    and other_dc is not None
                ):
                    return [primary] + same_rack[:z] + other_rack + other_dc
            return []

    def collection_volumes(self, name: str) -> list[tuple[int, str, int]]:
        """(vid, ip, grpc_port) of every normal volume in a collection."""
        with self._lock:
            return [
                (v.id, n.ip, n.grpc_port)
                for n in self.nodes.values()
                for v in n.volumes.values()
                if v.collection == name
            ]

    def collection_ec_shards(self, name: str) -> list[tuple[int, str, int, list[int]]]:
        """(vid, ip, grpc_port, shard_ids) per holder for EC volumes of
        a collection."""
        with self._lock:
            return [
                (
                    e.id,
                    n.ip,
                    n.grpc_port,
                    [i for i in range(32) if e.shard_bits & (1 << i)],
                )
                for n in self.nodes.values()
                for e in n.ec_shards.values()
                if e.collection == name
            ]

    def garbage_candidates(self, threshold: float) -> list[tuple[int, str, int]]:
        """(vid, ip, grpc_port) of garbage-heavy writable volumes.
        Volumes an operator disabled via volume.vacuum.disable are
        skipped (reference topology Volume.SkipVacuum)."""
        with self._lock:
            return [
                (v.id, n.ip, n.grpc_port)
                for n in self.nodes.values()
                for v in n.volumes.values()
                if v.size > 0
                and not v.read_only
                and v.id not in self.vacuum_disabled
                and v.deleted_bytes / max(v.size, 1) > threshold
            ]

    # ------------------------------------------------------------- stats

    def statistics(self) -> pb.StatisticsResponse:
        with self._lock:
            vols = {v.id for n in self.nodes.values() for v in n.volumes.values()}
            ecs = {e.id for n in self.nodes.values() for e in n.ec_shards.values()}
            return pb.StatisticsResponse(
                used_size=sum(
                    v.size for n in self.nodes.values() for v in n.volumes.values()
                ),
                file_count=sum(
                    v.file_count
                    for n in self.nodes.values()
                    for v in n.volumes.values()
                ),
                volume_count=len(vols),
                ec_volume_count=len(ecs),
                node_count=len(self.nodes),
            )

    def to_proto(self) -> pb.TopologyResponse:
        with self._lock:
            return pb.TopologyResponse(
                max_volume_id=self.max_volume_id,
                nodes=[
                    pb.DataNodeInfo(
                        id=n.node_id,
                        location=n.location(),
                        volumes=list(n.volumes.values()),
                        ec_shards=list(n.ec_shards.values()),
                        max_volume_count=n.max_volume_count,
                        rack=n.rack,
                        data_center=n.data_center,
                    )
                    for n in sorted(self.nodes.values(), key=lambda n: n.node_id)
                ],
            )


# --------------------------------------------------------------------------
# Fleet telemetry gauges: heartbeat-learned per-chip queue load and pod
# breaker health across every live Topology (scrape-time callbacks over
# a weak registry — the PR 6 sw_ec_chip_breaker_open pattern). These are
# the MASTER-side series; the per-server sw_ec_queue_* counters come
# from each volume server's own scheduler.
# --------------------------------------------------------------------------

_topologies: "weakref.WeakSet[Topology]" = weakref.WeakSet()


def _iter_chip_loads():
    seen = set()
    for topo in list(_topologies):
        for node in list(topo.nodes.values()):
            chips = node.ec_telemetry.get("chips")
            if not isinstance(chips, dict):
                continue
            for chip, c in chips.items():
                key = (node.node_id, chip)
                if key in seen:  # two topologies tracking one node
                    continue
                seen.add(key)
                try:
                    load = float(c.get("load", 0))
                except (TypeError, AttributeError, ValueError):
                    continue
                yield {"node": node.node_id, "chip": chip}, load


def _iter_breakers_open():
    seen = set()
    for topo in list(_topologies):
        for node in list(topo.nodes.values()):
            tele = node.ec_telemetry
            if not tele or node.node_id in seen:
                continue
            seen.add(node.node_id)
            try:
                n_open = float(tele.get("breakers_open", 0))
            except (TypeError, ValueError):
                continue
            yield {"node": node.node_id}, n_open


_M.REGISTRY.gauge(
    "sw_ec_queue_load",
    "per-chip device-queue load (cost units queued + in flight), "
    "heartbeat-learned per node",
    ("node", "chip"),
    fn=_iter_chip_loads,
)
_M.REGISTRY.gauge(
    "sw_ec_fleet_breakers_open",
    "open per-chip fallback breakers per node (heartbeat-learned): "
    ">0 = that host's chips are failing over to CPU",
    ("node",),
    fn=_iter_breakers_open,
)


def _replica_copies(replication: str) -> int:
    """Replica placement 'XYZ' => 1 + sum of digits (copies across DC/
    rack/server; reference super_block/replica_placement.go)."""
    if not replication:
        return 1
    digits = [int(c) for c in replication if c.isdigit()]
    return 1 + sum(digits[:3])
