"""WebDAV gateway over the filer.

Reference: weed/server/webdav_server.go (x/net/webdav over the filer).
Class-2-less subset (no LOCK/UNLOCK): OPTIONS, PROPFIND depth 0/1,
GET/HEAD/PUT/DELETE, MKCOL, MOVE, COPY — enough for davfs/cadaver/
Finder-style clients.
"""

from __future__ import annotations

import threading
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import unquote, urlparse

from ..filer.entry import Entry, new_entry, normalize_path
from ..filer.filer import Filer, FilerError
from ..filer.filer_store import NotFound

DAV = "DAV:"
ET.register_namespace("D", DAV)


def _rfc1123(ts: int) -> str:
    import time as _t

    return _t.strftime("%a, %d %b %Y %H:%M:%S GMT", _t.gmtime(ts or 0))


class WebDavServer:
    def __init__(
        self, filer: Filer, ip: str = "localhost", port: int = 7333, tls=None
    ):
        self.filer = filer
        self.ip = ip
        self.port = port
        self._http = ThreadingHTTPServer((ip, port), self._handler_class())
        self.tls = tls
        if tls is not None:
            tls.wrap_server(self._http)
        self._thread = threading.Thread(target=self._http.serve_forever, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()

    def _handler_class(self):
        filer = self.filer

        from ..utils.request_id import RequestTracingMixin

        class Handler(RequestTracingMixin, BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _path(self) -> str:
                return normalize_path(unquote(urlparse(self.path).path))

            def _send(self, code: int, body: bytes = b"", ctype="application/xml; charset=utf-8", extra=None):
                self.send_response(code)
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                if code in (204, 201) and not body:
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body and self.command != "HEAD":
                    self.wfile.write(body)

            def _drain(self):
                if "chunked" in (self.headers.get("Transfer-Encoding", "")).lower():
                    # chunked bodies (Finder/davfs PUTs): read frames so
                    # the keep-alive connection stays in sync
                    parts = []
                    while True:
                        line = self.rfile.readline(1024).strip()
                        try:
                            size = int(line.split(b";")[0], 16)
                        except ValueError:
                            break
                        if size == 0:
                            self.rfile.readline(1024)  # trailing CRLF
                            break
                        parts.append(self.rfile.read(size))
                        self.rfile.read(2)  # chunk CRLF
                    return b"".join(parts)
                n = int(self.headers.get("Content-Length", "0") or "0")
                return self.rfile.read(n) if n else b""

            # ----------------------------------------------------- verbs

            def do_OPTIONS(self):
                self._send(
                    200,
                    extra={
                        "DAV": "1",
                        "Allow": "OPTIONS, PROPFIND, GET, HEAD, PUT, DELETE, MKCOL, MOVE, COPY",
                        "MS-Author-Via": "DAV",
                    },
                )

            def do_PROPFIND(self):
                self._drain()
                path = self._path()
                depth = self.headers.get("Depth", "1")
                try:
                    entry = filer.find_entry(path)
                except NotFound:
                    return self._send(404)
                ms = ET.Element(f"{{{DAV}}}multistatus")
                self._prop_response(ms, path, entry)
                if entry.is_directory and depth != "0":
                    for child in filer.list_entries(path, limit=10_000):
                        self._prop_response(ms, child.full_path, child)
                body = b'<?xml version="1.0" encoding="utf-8"?>' + ET.tostring(ms)
                self._send(207, body)

            def _prop_response(self, ms, path: str, entry: Entry):
                from urllib.parse import quote

                resp = ET.SubElement(ms, f"{{{DAV}}}response")
                href = ET.SubElement(resp, f"{{{DAV}}}href")
                href.text = quote(path) + (
                    "/" if entry.is_directory and path != "/" else ""
                )
                stat = ET.SubElement(resp, f"{{{DAV}}}propstat")
                prop = ET.SubElement(stat, f"{{{DAV}}}prop")
                rt = ET.SubElement(prop, f"{{{DAV}}}resourcetype")
                if entry.is_directory:
                    ET.SubElement(rt, f"{{{DAV}}}collection")
                else:
                    ET.SubElement(prop, f"{{{DAV}}}getcontentlength").text = str(
                        entry.file_size
                    )
                    ET.SubElement(prop, f"{{{DAV}}}getcontenttype").text = (
                        entry.attr.mime or "application/octet-stream"
                    )
                ET.SubElement(prop, f"{{{DAV}}}getlastmodified").text = _rfc1123(
                    entry.attr.mtime
                )
                ET.SubElement(prop, f"{{{DAV}}}displayname").text = entry.name
                ET.SubElement(stat, f"{{{DAV}}}status").text = "HTTP/1.1 200 OK"

            def do_GET(self):
                path = self._path()
                try:
                    entry = filer.find_entry(path)
                except NotFound:
                    return self._send(404)
                if entry.is_directory:
                    return self._send(403)
                data = b"" if self.command == "HEAD" else filer.read_entry(entry)
                self.send_response(200)
                self.send_header(
                    "Content-Type", entry.attr.mime or "application/octet-stream"
                )
                self.send_header(
                    "Content-Length",
                    str(entry.file_size if self.command == "HEAD" else len(data)),
                )
                self.send_header("Last-Modified", _rfc1123(entry.attr.mtime))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(data)

            do_HEAD = do_GET

            def do_PUT(self):
                data = self._drain()
                try:
                    filer.write_file(
                        self._path(),
                        data,
                        mime=self.headers.get("Content-Type", ""),
                    )
                except FilerError:
                    return self._send(409)
                self._send(201)

            def do_MKCOL(self):
                self._drain()
                path = self._path()
                if filer.exists(path):
                    return self._send(405)
                try:
                    filer.create_entry(new_entry(path, is_directory=True, mode=0o755))
                except FilerError:
                    return self._send(409)
                self._send(201)

            def do_DELETE(self):
                path = self._path()
                if not filer.exists(path):
                    return self._send(404)
                filer.delete_entry(path, recursive=True)
                self._send(204)

            def _dest(self) -> str | None:
                dest = self.headers.get("Destination", "")
                if not dest:
                    return None
                return normalize_path(unquote(urlparse(dest).path))

            def _overwrite_blocked(self, dst: str) -> bool:
                """RFC 4918: 'Overwrite: F' on an existing destination
                must 412, never clobber."""
                if self.headers.get("Overwrite", "T").upper() != "F":
                    return False
                if filer.exists(dst):
                    self._send(412)
                    return True
                return False

            def do_MOVE(self):
                self._drain()
                dst = self._dest()
                if dst is None:
                    return self._send(400)
                src = self._path()
                if src == dst:
                    return self._send(403)  # RFC 4918: same resource
                if self._overwrite_blocked(dst):
                    return
                try:
                    filer.rename(src, dst)
                except NotFound:
                    return self._send(404)
                except FilerError:
                    return self._send(409)
                self._send(201)

            def do_COPY(self):
                self._drain()
                dst = self._dest()
                if dst is None:
                    return self._send(400)
                if self._path() == dst:
                    return self._send(403)
                if self._overwrite_blocked(dst):
                    return
                try:
                    entry = filer.find_entry(self._path())
                    if entry.is_directory:
                        return self._send(403)  # file copies only, for now
                    filer.write_file(
                        dst, filer.read_entry(entry), mime=entry.attr.mime
                    )
                except NotFound:
                    return self._send(404)
                except FilerError:
                    return self._send(409)
                self._send(201)

        return Handler
