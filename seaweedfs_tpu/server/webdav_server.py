"""WebDAV gateway over the filer.

Reference: weed/server/webdav_server.go (x/net/webdav over the filer).
Class 1 + 2: OPTIONS, PROPFIND depth 0/1, GET/HEAD/PUT/DELETE, MKCOL,
MOVE, COPY, and LOCK/UNLOCK (exclusive write locks with timeouts,
refresh, If-token enforcement on every mutating verb, depth-infinity
collection locks) — what Windows/macOS mapped drives and Office-style
clients require before they will save.
"""

from __future__ import annotations

import re
import threading
import time
import uuid
import xml.etree.ElementTree as ET
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import unquote, urlparse

from ..filer.entry import Entry, new_entry, normalize_path
from ..filer.filer import Filer, FilerError
from ..filer.filer_store import NotFound

DAV = "DAV:"
ET.register_namespace("D", DAV)

_DEFAULT_LOCK_TIMEOUT = 600.0
_MAX_LOCK_TIMEOUT = 3600.0


class _DavLocks:
    """In-memory WebDAV lock table (the reference rides x/net/webdav's
    memLS — same per-gateway scope). Exclusive write locks only; a
    `shared` request is granted as exclusive (documented divergence:
    clients in the wild lock exclusively)."""

    def __init__(self):
        self._locks: dict[str, dict] = {}  # path -> lock
        self._mu = threading.Lock()

    def _expire_locked(self) -> None:
        now = time.monotonic()
        for p in [p for p, l in self._locks.items() if l["expires"] <= now]:
            del self._locks[p]

    @staticmethod
    def _conflicts(
        lock_path: str, lock: dict, path: str, member_change: bool = False
    ) -> bool:
        """One predicate for both enforcement and acquisition: the lock
        covers `path` when it IS the path, is an ancestor with Depth
        infinity, or sits underneath it (collection delete/move).
        member_change additionally applies a DEPTH-0 lock on the DIRECT
        parent (RFC 4918 §7.4: a depth-0 collection lock protects the
        collection's membership, not members' content)."""
        anc = (
            lock_path == "/"
            or path == lock_path
            or path.startswith(lock_path.rstrip("/") + "/")
        )
        if (
            lock_path == path
            or (anc and lock["depth"] == "infinity")
            or lock_path.startswith(path.rstrip("/") + "/")
        ):
            return True
        if member_change:
            parent = path.rstrip("/").rsplit("/", 1)[0] or "/"
            return lock_path == parent
        return False

    def covering(
        self, path: str, member_change: bool = False
    ) -> list[tuple[str, dict]]:
        with self._mu:
            self._expire_locked()
            return [
                (p, l)
                for p, l in self._locks.items()
                if self._conflicts(p, l, path, member_change)
            ]

    def lock(
        self, path: str, owner: str, depth: str, timeout: float
    ) -> dict | None:
        with self._mu:
            self._expire_locked()
            for p, l in self._locks.items():
                if self._conflicts(p, l, path):
                    return None  # conflicting lock
            lock = {
                "token": f"opaquelocktoken:{uuid.uuid4()}",
                "owner": owner,
                "depth": depth,
                "timeout": timeout,
                "expires": time.monotonic() + timeout,
                "path": path,
            }
            self._locks[path] = lock
            return lock

    def refresh(self, token: str, timeout: float, path: str) -> dict | None:
        """RFC 4918 §9.10.2: the request URI must fall within the
        lock's scope — a token for an unrelated resource must not be
        refreshable against this path."""
        with self._mu:
            self._expire_locked()
            for p, l in self._locks.items():
                if l["token"] == token and self._conflicts(p, l, path):
                    l["timeout"] = timeout
                    l["expires"] = time.monotonic() + timeout
                    return l
            return None

    def unlock(self, token: str) -> bool:
        with self._mu:
            self._expire_locked()
            for p, l in list(self._locks.items()):
                if l["token"] == token:
                    del self._locks[p]
                    return True
            return False


def _rfc1123(ts: int) -> str:
    import time as _t

    return _t.strftime("%a, %d %b %Y %H:%M:%S GMT", _t.gmtime(ts or 0))


class WebDavServer:
    def __init__(
        self, filer: Filer, ip: str = "localhost", port: int = 7333, tls=None
    ):
        self.filer = filer
        self.ip = ip
        self.port = port
        self.locks = _DavLocks()
        self._http = ThreadingHTTPServer((ip, port), self._handler_class())
        self.tls = tls
        if tls is not None:
            tls.wrap_server(self._http)
        self._thread = threading.Thread(target=self._http.serve_forever, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()

    def _handler_class(self):
        filer = self.filer
        locks = self.locks

        from ..utils.request_id import RequestTracingMixin

        class Handler(RequestTracingMixin, BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            trace_server_kind = "webdav"

            def log_message(self, *a):
                pass

            def _path(self) -> str:
                return normalize_path(unquote(urlparse(self.path).path))

            def _send(self, code: int, body: bytes = b"", ctype="application/xml; charset=utf-8", extra=None):
                self.send_response(code)
                for k, v in (extra or {}).items():
                    self.send_header(k, v)
                if code in (204, 201) and not body:
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body and self.command != "HEAD":
                    self.wfile.write(body)

            def _drain(self):
                if "chunked" in (self.headers.get("Transfer-Encoding", "")).lower():
                    # chunked bodies (Finder/davfs PUTs): read frames so
                    # the keep-alive connection stays in sync
                    parts = []
                    while True:
                        line = self.rfile.readline(1024).strip()
                        try:
                            size = int(line.split(b";")[0], 16)
                        except ValueError:
                            break
                        if size == 0:
                            self.rfile.readline(1024)  # trailing CRLF
                            break
                        parts.append(self.rfile.read(size))
                        self.rfile.read(2)  # chunk CRLF
                    return b"".join(parts)
                n = int(self.headers.get("Content-Length", "0") or "0")
                return self.rfile.read(n) if n else b""

            # ----------------------------------------------------- verbs

            def do_OPTIONS(self):
                self._send(
                    200,
                    extra={
                        "DAV": "1, 2",
                        "Allow": (
                            "OPTIONS, PROPFIND, GET, HEAD, PUT, DELETE, "
                            "MKCOL, MOVE, COPY, LOCK, UNLOCK"
                        ),
                        "MS-Author-Via": "DAV",
                    },
                )

            # ------------------------------------------------ class 2

            def _if_tokens(self) -> set[str]:
                return set(
                    re.findall(
                        r"<(opaquelocktoken:[^>]+)>",
                        self.headers.get("If", ""),
                    )
                )

            def _locked(self, *paths: str, member_change: bool = False) -> bool:
                """423 unless every covering lock's token is presented
                in the If header. Returns True when the request was
                rejected. member_change: the op adds/removes a
                collection member, so a depth-0 parent lock applies."""
                have = self._if_tokens()
                for path in paths:
                    if path is None:
                        continue
                    for _p, l in locks.covering(path, member_change):
                        if l["token"] not in have:
                            self._send(423)
                            return True
                return False

            @staticmethod
            def _parse_timeout(header: str | None) -> float:
                for part in (header or "").split(","):
                    part = part.strip()
                    if part.lower().startswith("second-"):
                        try:
                            return min(
                                float(part[7:]), _MAX_LOCK_TIMEOUT
                            )
                        except ValueError:
                            pass
                return _DEFAULT_LOCK_TIMEOUT

            def _lock_xml(self, lock: dict) -> bytes:
                prop = ET.Element(f"{{{DAV}}}prop")
                disc = ET.SubElement(prop, f"{{{DAV}}}lockdiscovery")
                al = ET.SubElement(disc, f"{{{DAV}}}activelock")
                lt = ET.SubElement(al, f"{{{DAV}}}locktype")
                ET.SubElement(lt, f"{{{DAV}}}write")
                ls = ET.SubElement(al, f"{{{DAV}}}lockscope")
                ET.SubElement(ls, f"{{{DAV}}}exclusive")
                ET.SubElement(al, f"{{{DAV}}}depth").text = lock["depth"]
                ET.SubElement(al, f"{{{DAV}}}owner").text = lock["owner"]
                ET.SubElement(al, f"{{{DAV}}}timeout").text = (
                    f"Second-{int(lock['timeout'])}"
                )
                tok = ET.SubElement(al, f"{{{DAV}}}locktoken")
                ET.SubElement(tok, f"{{{DAV}}}href").text = lock["token"]
                root = ET.SubElement(al, f"{{{DAV}}}lockroot")
                # .text assignment: ET escapes XML metacharacters in
                # paths ("Tom & Jerry.docx") on serialization
                ET.SubElement(root, f"{{{DAV}}}href").text = lock["path"]
                return (
                    b'<?xml version="1.0" encoding="utf-8"?>'
                    + ET.tostring(prop)
                )

            def do_LOCK(self):
                body = self._drain()
                path = self._path()
                timeout = self._parse_timeout(self.headers.get("Timeout"))
                if not body:
                    # refresh: token arrives in the If header
                    have = self._if_tokens()
                    lock = None
                    for t in have:
                        lock = locks.refresh(t, timeout, path)
                        if lock:
                            break
                    if lock is None:
                        return self._send(412)
                    return self._send(
                        200,
                        self._lock_xml(lock),
                        extra={"Lock-Token": f"<{lock['token']}>"},
                    )
                owner = ""
                try:
                    doc = ET.fromstring(body)
                    o = doc.find(f"{{{DAV}}}owner")
                    if o is not None:
                        owner = "".join(o.itertext()).strip() or (
                            o[0].text or "" if len(o) else ""
                        )
                except ET.ParseError:
                    return self._send(400)
                depth = (
                    "0"
                    if self.headers.get("Depth", "infinity") == "0"
                    else "infinity"
                )
                lock = locks.lock(path, owner, depth, timeout)
                if lock is None:
                    return self._send(423)
                created = False
                if not filer.exists(path):
                    # RFC 4918 §7.3: LOCK on an unmapped URL creates an
                    # empty lockable resource
                    try:
                        filer.write_file(path, b"")
                        created = True
                    except FilerError:
                        locks.unlock(lock["token"])
                        return self._send(409)
                self._send(
                    201 if created else 200,
                    self._lock_xml(lock),
                    extra={"Lock-Token": f"<{lock['token']}>"},
                )

            def do_UNLOCK(self):
                self._drain()
                m = re.search(
                    r"<([^>]+)>", self.headers.get("Lock-Token", "")
                )
                if not m:
                    return self._send(400)
                if not locks.unlock(m.group(1)):
                    return self._send(409)
                self._send(204)

            def do_PROPFIND(self):
                self._drain()
                path = self._path()
                depth = self.headers.get("Depth", "1")
                try:
                    entry = filer.find_entry(path)
                except NotFound:
                    return self._send(404)
                ms = ET.Element(f"{{{DAV}}}multistatus")
                self._prop_response(ms, path, entry)
                if entry.is_directory and depth != "0":
                    for child in filer.list_entries(path, limit=10_000):
                        self._prop_response(ms, child.full_path, child)
                body = b'<?xml version="1.0" encoding="utf-8"?>' + ET.tostring(ms)
                self._send(207, body)

            def _prop_response(self, ms, path: str, entry: Entry):
                from urllib.parse import quote

                resp = ET.SubElement(ms, f"{{{DAV}}}response")
                href = ET.SubElement(resp, f"{{{DAV}}}href")
                href.text = quote(path) + (
                    "/" if entry.is_directory and path != "/" else ""
                )
                stat = ET.SubElement(resp, f"{{{DAV}}}propstat")
                prop = ET.SubElement(stat, f"{{{DAV}}}prop")
                rt = ET.SubElement(prop, f"{{{DAV}}}resourcetype")
                if entry.is_directory:
                    ET.SubElement(rt, f"{{{DAV}}}collection")
                else:
                    ET.SubElement(prop, f"{{{DAV}}}getcontentlength").text = str(
                        entry.file_size
                    )
                    ET.SubElement(prop, f"{{{DAV}}}getcontenttype").text = (
                        entry.attr.mime or "application/octet-stream"
                    )
                ET.SubElement(prop, f"{{{DAV}}}getlastmodified").text = _rfc1123(
                    entry.attr.mtime
                )
                ET.SubElement(prop, f"{{{DAV}}}displayname").text = entry.name
                sl = ET.SubElement(prop, f"{{{DAV}}}supportedlock")
                le = ET.SubElement(sl, f"{{{DAV}}}lockentry")
                sc = ET.SubElement(le, f"{{{DAV}}}lockscope")
                ET.SubElement(sc, f"{{{DAV}}}exclusive")
                lt = ET.SubElement(le, f"{{{DAV}}}locktype")
                ET.SubElement(lt, f"{{{DAV}}}write")
                held = [l for p, l in locks.covering(path) if p == path]
                if held:
                    disc = ET.SubElement(prop, f"{{{DAV}}}lockdiscovery")
                    al = ET.SubElement(disc, f"{{{DAV}}}activelock")
                    alt = ET.SubElement(al, f"{{{DAV}}}locktype")
                    ET.SubElement(alt, f"{{{DAV}}}write")
                    als = ET.SubElement(al, f"{{{DAV}}}lockscope")
                    ET.SubElement(als, f"{{{DAV}}}exclusive")
                    ET.SubElement(al, f"{{{DAV}}}depth").text = held[0]["depth"]
                    tok = ET.SubElement(al, f"{{{DAV}}}locktoken")
                    ET.SubElement(tok, f"{{{DAV}}}href").text = held[0]["token"]
                ET.SubElement(stat, f"{{{DAV}}}status").text = "HTTP/1.1 200 OK"

            def do_GET(self):
                path = self._path()
                try:
                    entry = filer.find_entry(path)
                except NotFound:
                    return self._send(404)
                if entry.is_directory:
                    return self._send(403)
                data = b"" if self.command == "HEAD" else filer.read_entry(entry)
                self.send_response(200)
                self.send_header(
                    "Content-Type", entry.attr.mime or "application/octet-stream"
                )
                self.send_header(
                    "Content-Length",
                    str(entry.file_size if self.command == "HEAD" else len(data)),
                )
                self.send_header("Last-Modified", _rfc1123(entry.attr.mtime))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(data)

            do_HEAD = do_GET

            def do_PUT(self):
                data = self._drain()
                path = self._path()
                # creating a file changes the parent's membership
                if self._locked(path, member_change=not filer.exists(path)):
                    return
                try:
                    filer.write_file(
                        self._path(),
                        data,
                        mime=self.headers.get("Content-Type", ""),
                    )
                except FilerError:
                    return self._send(409)
                self._send(201)

            def do_MKCOL(self):
                self._drain()
                path = self._path()
                if self._locked(path, member_change=True):
                    return
                if filer.exists(path):
                    return self._send(405)
                try:
                    filer.create_entry(new_entry(path, is_directory=True, mode=0o755))
                except FilerError:
                    return self._send(409)
                self._send(201)

            def do_DELETE(self):
                path = self._path()
                if self._locked(path, member_change=True):
                    return
                if not filer.exists(path):
                    return self._send(404)
                filer.delete_entry(path, recursive=True)
                self._send(204)

            def _dest(self) -> str | None:
                dest = self.headers.get("Destination", "")
                if not dest:
                    return None
                return normalize_path(unquote(urlparse(dest).path))

            def _overwrite_blocked(self, dst: str) -> bool:
                """RFC 4918: 'Overwrite: F' on an existing destination
                must 412, never clobber."""
                if self.headers.get("Overwrite", "T").upper() != "F":
                    return False
                if filer.exists(dst):
                    self._send(412)
                    return True
                return False

            def do_MOVE(self):
                self._drain()
                dst = self._dest()
                if dst is None:
                    return self._send(400)
                src = self._path()
                if src == dst:
                    return self._send(403)  # RFC 4918: same resource
                if self._locked(src, dst, member_change=True):
                    return
                if self._overwrite_blocked(dst):
                    return
                try:
                    filer.rename(src, dst)
                except NotFound:
                    return self._send(404)
                except FilerError:
                    return self._send(409)
                self._send(201)

            def do_COPY(self):
                self._drain()
                dst = self._dest()
                if dst is None:
                    return self._send(400)
                if self._path() == dst:
                    return self._send(403)
                if self._locked(dst):
                    return
                if self._overwrite_blocked(dst):
                    return
                try:
                    entry = filer.find_entry(self._path())
                    if entry.is_directory:
                        return self._send(403)  # file copies only, for now
                    filer.write_file(
                        dst, filer.read_entry(entry), mime=entry.attr.mime
                    )
                except NotFound:
                    return self._send(404)
                except FilerError:
                    return self._send(409)
                self._send(201)

        return Handler
