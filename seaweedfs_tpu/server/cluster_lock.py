"""Cluster lock manager: exclusive named leases on the master leader.

Reference: weed/cluster/lock_manager/lock_manager.go — the reference
gates every mutating shell command on an exclusive cluster lock
(`confirmIsLocked`) and expires stale holders by lease. Locks live in
the leader's memory only: a failover drops them, which is safe because
holders renew within their TTL and discover the loss as a failed
renewal (same model as the reference's distributed lock ring falling
back to the new lock host).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass


@dataclass
class _Lease:
    owner: str
    token: str
    expires: float  # time.monotonic deadline


class LockManager:
    MAX_TTL = 3600.0

    def __init__(self):
        self._lock = threading.Lock()
        self._leases: dict[str, _Lease] = {}

    def acquire(
        self, name: str, owner: str, ttl: float, token: str = ""
    ) -> tuple[bool, str, str, float]:
        """Returns (ok, token, holder, remaining_ttl).

        Empty `token` = fresh acquire; matching token = renewal
        (re-entrant for the same session)."""
        ttl = min(max(ttl, 1.0), self.MAX_TTL)
        now = time.monotonic()
        with self._lock:
            lease = self._leases.get(name)
            if lease is not None and lease.expires <= now:
                lease = None  # expired: holder lost it
            if lease is None:
                tok = token or uuid.uuid4().hex
                self._leases[name] = _Lease(owner, tok, now + ttl)
                return True, tok, owner, ttl
            if token and lease.token == token:
                # renewal never SHORTENS a lease: a nested guard's
                # smaller ttl must not clobber a session `lock -ttl N`
                lease.expires = max(lease.expires, now + ttl)
                lease.owner = owner or lease.owner
                return True, lease.token, lease.owner, lease.expires - now
            return False, "", lease.owner, lease.expires - now

    def release(self, name: str, token: str) -> bool:
        with self._lock:
            lease = self._leases.get(name)
            if lease is None or lease.token != token:
                return False
            del self._leases[name]
            return True

    def status(self) -> list[tuple[str, str, float]]:
        """(name, owner, remaining_seconds) for live leases."""
        now = time.monotonic()
        with self._lock:
            out = []
            for name, lease in list(self._leases.items()):
                if lease.expires <= now:
                    del self._leases[name]
                    continue
                out.append((name, lease.owner, lease.expires - now))
            return sorted(out)
