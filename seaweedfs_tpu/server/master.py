"""Master server: heartbeat sink, fid assignment, volume/EC lookup,
volume growth orchestration.

Reference: weed/server/master_server.go (NewMasterServer :97),
master_grpc_server.go:66 (SendHeartbeat), master_grpc_server_assign.go:50
(Assign with growth), HTTP /dir/assign + /dir/lookup handlers. Raft HA
comes later; this is the single-master mode `weed master` itself defaults
to on one node.
"""

from __future__ import annotations

import json
import re
import threading
import time
from concurrent import futures
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import grpc

from ..pb import cluster_pb2 as pb
from ..pb import rpc
from ..storage.file_id import FileId, new_cookie
from .topology import DataNode, Topology


# collections become file-name prefixes on volume servers: path
# separators or control characters must never reach the storage layer
_COLLECTION_RE = re.compile(r"^[A-Za-z0-9_.\-]*$")


def _ec_stream_summary() -> dict:
    """Streaming-EC roll-up for /cluster/status (open encode-on-write
    streams + parity-lag/sealed counters). Import is lazy and failures
    degrade to {} — status must never depend on the EC stack."""
    try:
        from ..ec.stream_encode import stream_summary

        return stream_summary()
    except Exception:  # noqa: BLE001
        return {}


def _ec_residency_summary() -> dict:
    """Chip residency-ledger roll-up for /cluster/status (per-chip
    budget/inflight/watermarks + per-tenant shed counters). Lazy and
    failure-tolerant for the same reason as _ec_stream_summary."""
    try:
        from ..ec.device_queue import residency_snapshot

        return residency_snapshot()
    except Exception:  # noqa: BLE001
        return {}


class MasterService:
    """gRPC servicer (method-per-RPC, see pb/rpc.py)."""

    def __init__(self, topo: Topology, jwt_key: str = "", raft=None):
        from .cluster_lock import LockManager

        self.topo = topo
        self.jwt_key = jwt_key
        self.raft = raft  # None = pre-raft single master (tests construct this)
        self._grow_lock = threading.Lock()
        self.locks = LockManager()
        # set to a filer/lock_ring.DlmClient to ride the filer lock
        # ring instead of the local lease table (MasterServer wires it
        # from its dlm_filers parameter)
        self.dlm = None
        # volume-id allocation goes through raft when HA is on
        self.alloc_volume_id = topo.next_volume_id

    def _not_leader(self) -> str | None:
        """None when this master may serve; otherwise the leader hint."""
        if self.raft is None or self.raft.is_leader:
            return None
        return self.raft.leader or ""

    # ------------------------------------------------------- heartbeats

    def SendHeartbeat(self, request_iterator, context):
        leader = self._not_leader()
        if leader is not None:
            # redirect: volume servers must feed the leader's topology
            yield pb.HeartbeatResponse(leader=leader)
            return
        node: DataNode | None = None
        token = object()
        try:
            for hb in request_iterator:
                if self._not_leader() is not None:
                    yield pb.HeartbeatResponse(
                        leader=self.raft.leader or ""
                    )
                    return
                if node is None:
                    node = self.topo.register_node(hb)
                    node.owner_token = token
                    self.topo.sync_registration(node, hb)
                elif hb.volumes or hb.has_no_volumes or hb.ec_shards or hb.has_no_ec_shards:
                    self.topo.sync_registration(node, hb)
                else:
                    self.topo.incremental_update(node, hb)
                yield pb.HeartbeatResponse(
                    volume_size_limit=self.topo.volume_size_limit
                )
        finally:
            # stream closed = node gone (reference topology UnRegister on
            # missed pulse); owner_token keeps a stale stream's cleanup
            # from removing the node a replacement stream re-registered
            if node is not None:
                self.topo.unregister_node(node.node_id, owner_token=token)

    # ---------------------------------------------------- keepconnected

    def KeepConnected(self, request: pb.KeepConnectedRequest, context):
        """Streaming vid-location session (reference masterclient.go:483):
        full snapshot, then deltas; leader changes notify the client to
        reconnect elsewhere."""
        leader = self._not_leader()
        if leader is not None:
            yield pb.VolumeLocationUpdate(leader=leader)
            return
        import queue as _queue

        q, snapshot = self.topo.subscribe()
        try:
            for u in snapshot:
                yield u
            if self.raft is not None:
                # snapshot-complete marker: leader == the serving master
                # tells the client its vid map is now authoritative
                yield pb.VolumeLocationUpdate(leader=self.raft.node_id)
            while context is None or context.is_active():
                if q.overflowed:
                    return  # delta lost: end stream, client re-syncs
                try:
                    u = q.get(timeout=1.0)
                except _queue.Empty:
                    if self._not_leader() is not None:
                        yield pb.VolumeLocationUpdate(
                            leader=self.raft.leader or ""
                        )
                        return
                    continue
                yield u
                if u.leader:
                    return  # stepped down: client reconnects to the leader
        finally:
            self.topo.unsubscribe(q)

    # ------------------------------------------------------------ locks

    def AdminLock(self, request: pb.LockRequest, context) -> pb.LockResponse:
        leader = self._not_leader()
        if leader is not None:
            return pb.LockResponse(error=f"not leader; leader={leader}")
        if self.dlm is not None:
            # filer lock ring configured: the master's lease API is a
            # CLIENT of it (reference: shell/admin locks ride the
            # cluster lock_manager ring) — locks survive master AND
            # single-filer failures
            try:
                r = self.dlm.lock(
                    request.name,
                    request.owner,
                    request.ttl_seconds or 60.0,
                    request.token,
                )
            except ConnectionError as e:
                return pb.LockResponse(error=str(e))
            return pb.LockResponse(
                ok=r.ok,
                token=r.token,
                holder=r.holder,
                expires_ns=int(r.remaining * 1e9),
                error=r.error,
            )
        ok, token, holder, remaining = self.locks.acquire(
            request.name,
            request.owner,
            request.ttl_seconds or 60.0,
            request.token,
        )
        return pb.LockResponse(
            ok=ok,
            token=token,
            holder=holder,
            expires_ns=int(remaining * 1e9),
            error="" if ok else f"held by {holder}",
        )

    def AdminUnlock(self, request: pb.UnlockRequest, context) -> pb.UnlockResponse:
        leader = self._not_leader()
        if leader is not None:
            return pb.UnlockResponse(error=f"not leader; leader={leader}")
        if self.dlm is not None:
            try:
                r = self.dlm.unlock(request.name, request.token)
            except ConnectionError as e:
                return pb.UnlockResponse(error=str(e))
            return pb.UnlockResponse(ok=r.ok, error=r.error)
        ok = self.locks.release(request.name, request.token)
        return pb.UnlockResponse(
            ok=ok, error="" if ok else "not held by this token"
        )

    def VacuumControl(self, request, context) -> pb.VolumeCommandResponse:
        """volume.vacuum.enable/disable: per-volume opt-out from the
        periodic garbage sweep (reference Volume.SkipVacuum)."""
        with self.topo._lock:
            if request.disable:
                self.topo.vacuum_disabled.add(request.volume_id)
            else:
                self.topo.vacuum_disabled.discard(request.volume_id)
        return pb.VolumeCommandResponse()

    def AdminLockStatus(self, request, context) -> pb.LockStatusResponse:
        # leases live on the leader only: a deposed master's (stale,
        # typically empty) table must not masquerade as cluster state
        self._abort_if_follower(context)
        rows = self.dlm.status() if self.dlm is not None else self.locks.status()
        return pb.LockStatusResponse(
            locks=[
                pb.LockRow(name=n, owner=o, expires_ns=int(r * 1e9))
                for n, o, r in rows
            ]
        )

    # ----------------------------------------------------------- assign

    def Assign(self, request: pb.AssignRequest, context) -> pb.AssignResponse:
        leader = self._not_leader()
        if leader is not None:
            return pb.AssignResponse(error=f"not leader; leader={leader}")
        count = max(int(request.count), 1)
        # canonicalize ("90" -> "90m"): volume servers report canonical
        # TTLs in heartbeats, and the layout buckets compare strings
        from ..storage.ttl import TTL

        if not _COLLECTION_RE.match(request.collection):
            return pb.AssignResponse(
                error=f"invalid collection name {request.collection!r}"
            )
        try:
            ttl = str(TTL.parse(request.ttl))
        except ValueError as e:
            return pb.AssignResponse(error=f"bad ttl: {e}")
        dt = request.disk_type
        picked = self.topo.pick_for_write(
            request.collection, request.replication, ttl, disk_type=dt
        )
        if picked is None:
            grown = self._grow(
                request.collection, request.replication, ttl, disk_type=dt
            )
            if grown:
                picked = self.topo.pick_for_write(
                    request.collection, request.replication, ttl,
                    disk_type=dt,
                )
        elif self.topo.all_crowded(
            request.collection, request.replication, ttl, disk_type=dt
        ):
            # crowded-state proactive growth: serve THIS assign from
            # the crowded volume but add capacity in the background so
            # the bucket never runs dry (reference volume_layout.go)
            threading.Thread(
                target=self._grow,
                args=(request.collection, request.replication, ttl),
                kwargs={"disk_type": dt},
                daemon=True,
            ).start()
        if picked is None:
            return pb.AssignResponse(error="no writable volumes and growth failed")
        vid, holders = picked
        fid = FileId(vid, self.topo.next_needle_id(), new_cookie())
        token = ""
        if self.jwt_key:
            from ..utils.security import sign_jwt

            token = sign_jwt(self.jwt_key, str(fid))
        return pb.AssignResponse(
            fid=str(fid),
            count=count,
            location=holders[0].location(),
            replicas=[n.location() for n in holders[1:]],
            jwt=token,
        )

    def _grow(
        self,
        collection: str,
        replication: str,
        ttl: str = "",
        disk_type: str = "",
    ) -> list[int]:
        """Allocate one new volume on planned targets (reference
        VolumeGrowth.findEmptySlotsForOneVolume + AllocateVolume RPCs)."""
        with self._grow_lock:
            targets = self.topo.plan_growth(replication)
            if not targets:
                return []
            vid = self.alloc_volume_id()
            ok = []
            for node in targets:
                try:
                    with grpc.insecure_channel(f"{node.ip}:{node.grpc_port}") as ch:
                        rpc.volume_stub(ch).AllocateVolume(
                            pb.AllocateVolumeRequest(
                                volume_id=vid,
                                collection=collection,
                                replication=replication,
                                ttl=ttl,
                                disk_type=disk_type,
                            ),
                            timeout=10,
                        )
                    ok.append(node)
                except grpc.RpcError:
                    continue
            if not ok:
                return []
            # optimistic registration; the next heartbeat confirms
            for node in ok:
                self.topo.optimistic_add_volume(
                    node,
                    pb.VolumeInfoMsg(
                        id=vid,
                        collection=collection,
                        replica_placement=replication,
                        ttl=ttl,
                        # a typed grow must be typed in the layout too,
                        # or the re-pick that follows filters it out
                        disk_type=disk_type or "hdd",
                    ),
                )
            return [vid]

    def VolumeGrow(self, request: pb.VolumeGrowRequest, context) -> pb.VolumeGrowResponse:
        from ..storage.ttl import TTL

        if self._not_leader() is not None:
            return pb.VolumeGrowResponse()
        if not _COLLECTION_RE.match(request.collection):
            return pb.VolumeGrowResponse()
        try:
            ttl = str(TTL.parse(request.ttl))
        except ValueError:
            return pb.VolumeGrowResponse()
        vids = []
        for _ in range(max(int(request.count), 1)):
            vids.extend(self._grow(request.collection, request.replication, ttl))
        return pb.VolumeGrowResponse(volume_ids=vids)

    # ----------------------------------------------------------- lookup

    def LookupVolume(self, request, context) -> pb.LookupVolumeResponse:
        leader = self._not_leader()
        if leader is not None:
            # follower topology is not authoritative (leader-only reads,
            # reference topology.go:217)
            return pb.LookupVolumeResponse(
                volume_locations=[
                    pb.VolumeLocations(
                        volume_id=vid, error=f"not leader; leader={leader}"
                    )
                    for vid in request.volume_ids
                ]
            )
        out = []
        for vid in request.volume_ids:
            locs = self.topo.lookup(vid)
            if not locs:
                # EC volumes answer normal lookups too: any shard holder
                ec = self.topo.lookup_ec(vid)
                seen = {}
                for ls in ec.values():
                    for l in ls:
                        seen[l.url] = l
                locs = list(seen.values())
            out.append(
                pb.VolumeLocations(
                    volume_id=vid,
                    locations=locs,
                    error="" if locs else f"volume {vid} not found",
                )
            )
        return pb.LookupVolumeResponse(volume_locations=out)

    def LookupEcVolume(self, request, context) -> pb.LookupEcVolumeResponse:
        leader = self._not_leader()
        if leader is not None:
            return pb.LookupEcVolumeResponse(
                volume_id=request.volume_id,
                error=f"not leader; leader={leader}",
            )
        shard_locs = self.topo.lookup_ec(request.volume_id)
        return pb.LookupEcVolumeResponse(
            volume_id=request.volume_id,
            shard_locations=[
                pb.EcShardLocation(shard_id=sid, locations=locs)
                for sid, locs in sorted(shard_locs.items())
            ],
            error="" if shard_locs else f"ec volume {request.volume_id} not found",
        )

    def _abort_if_follower(self, context) -> None:
        """Topology reads are leader-only (reference topology.go:217):
        a follower's view is empty, not merely stale."""
        leader = self._not_leader()
        if leader is not None:
            if context is not None:
                context.abort(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"not leader; leader={leader}",
                )
            raise RuntimeError(f"not leader; leader={leader}")

    def Statistics(self, request, context) -> pb.StatisticsResponse:
        self._abort_if_follower(context)
        return self.topo.statistics()

    def Topology(self, request, context) -> pb.TopologyResponse:
        self._abort_if_follower(context)
        return self.topo.to_proto()

    def CollectionList(self, request, context) -> pb.CollectionListResponse:
        self._abort_if_follower(context)
        return pb.CollectionListResponse(collections=self.topo.collections())

    def CollectionDelete(self, request, context) -> pb.CollectionDeleteResponse:
        leader = self._not_leader()
        if leader is not None:
            return pb.CollectionDeleteResponse(
                error=f"not leader; leader={leader}"
            )
        """Drop every volume AND EC shard set of a collection
        cluster-wide — the fast bucket-delete path (reference
        CollectionDelete: reclaims space without per-object tombstones).
        Partial failures are reported, not swallowed: a skipped node's
        volumes would resurrect on its next heartbeat."""
        if not request.name:
            return pb.CollectionDeleteResponse(
                error="refusing to delete the default collection"
            )
        deleted = []
        failures = []
        for vid, ip, gport in self.topo.collection_volumes(request.name):
            try:
                with grpc.insecure_channel(f"{ip}:{gport}") as ch:
                    r = rpc.volume_stub(ch).VolumeDelete(
                        pb.VolumeCommandRequest(volume_id=vid), timeout=60
                    )
                if r.error:
                    failures.append(f"volume {vid}@{ip}: {r.error}")
                else:
                    deleted.append(vid)
            except grpc.RpcError as e:
                failures.append(f"volume {vid}@{ip}: {e.code().name}")
        for vid, ip, gport, sids in self.topo.collection_ec_shards(request.name):
            try:
                with grpc.insecure_channel(f"{ip}:{gport}") as ch:
                    stub = rpc.volume_stub(ch)
                    stub.VolumeEcShardsUnmount(
                        pb.EcShardsUnmountRequest(volume_id=vid, shard_ids=sids),
                        timeout=60,
                    )
                    stub.VolumeEcShardsDelete(
                        pb.EcShardsDeleteRequest(
                            volume_id=vid,
                            collection=request.name,
                            shard_ids=sids,
                        ),
                        timeout=60,
                    )
                deleted.append(vid)
            except grpc.RpcError as e:
                failures.append(f"ec {vid}@{ip}: {e.code().name}")
        return pb.CollectionDeleteResponse(
            deleted_volume_ids=sorted(set(deleted)),
            error="; ".join(failures),
        )


class MasterServer:
    """gRPC + HTTP front for one Topology."""

    def __init__(
        self,
        ip: str = "localhost",
        port: int = 9333,
        grpc_port: int = 0,
        volume_size_limit: int = 30 * 1024**3,
        jwt_key: str = "",
        garbage_threshold: float = 0.3,
        vacuum_interval: float = 60.0,
        ec_auto_fullness: float = 0.0,
        ec_quiet_seconds: float = 60.0,
        ec_scrub_interval: float = 0.0,
        ec_rebalance_interval: float = 0.0,
        peers: list[str] | str | None = None,
        meta_dir: str | None = None,
        election_timeout: tuple[float, float] = (0.4, 0.8),
        tls=None,
        telemetry_url: str = "",
        dlm_filers: list[str] | None = None,
    ):
        """ec_auto_fullness > 0 turns on the maintenance scanner: volumes
        at that fraction of the size limit (and write-quiet) get an
        ec_encode task submitted for the worker fleet (reference admin
        maintenance scanner).

        `peers`: every master in the HA group (including this one), as
        http host:port addresses — raft replicates the allocation state
        across them (reference raft_hashicorp.go). Empty/None = classic
        single master (instant self-leader)."""
        self.ip = ip
        self.port = port
        self.grpc_port = grpc_port or (port + 10000)
        self.topo = Topology(volume_size_limit=volume_size_limit)

        from .raft import NotLeader, RaftNode  # noqa: F401 (NotLeader re-export)

        if isinstance(peers, str):
            peers = [p.strip() for p in peers.split(",") if p.strip()]
        self.node_id = f"{ip}:{port}"
        self.raft = RaftNode(
            node_id=self.node_id,
            peers=list(peers or []),
            state_dir=meta_dir,
            apply_fn=self._raft_apply,
            election_timeout=election_timeout,
            snapshot_fn=lambda: {"max_volume_id": self.topo.max_volume_id},
            restore_fn=self._raft_restore,
        )
        self.raft.on_leader_change = self._on_leader_change
        self.service = MasterService(self.topo, jwt_key=jwt_key, raft=self.raft)
        if dlm_filers:
            # lease API rides the filer lock ring (dlm_filers: filer
            # gRPC addresses) instead of this master's local table
            from ..filer.lock_ring import DlmClient

            self.service.dlm = DlmClient(list(dlm_filers))
        self.service.alloc_volume_id = self._alloc_volume_id
        self.garbage_threshold = garbage_threshold
        self.vacuum_interval = vacuum_interval
        self.ec_auto_fullness = ec_auto_fullness
        self.ec_quiet_seconds = ec_quiet_seconds
        # Fleet scrub period (seconds, 0 = off): every EC volume's
        # shards get sidecar-verified once per period FLEET-WIDE via
        # ec_scrub worker tasks, staggered one volume per maintenance
        # tick; unrebuildable holders get peer-fetch rebuilds dispatched
        # from the aggregated reports (worker/control.py).
        self.ec_scrub_interval = ec_scrub_interval
        # Data-gravity period (seconds, 0 = off): every tick past the
        # period, the rebalance scanner ranks per-volume heat deltas
        # against holder chip-deficit and dispatches bounded ec_migrate
        # tasks (ec/rebalance.py; knobs SEAWEED_EC_REBALANCE_*).
        self.ec_rebalance_interval = ec_rebalance_interval
        self._ec_rebalance_last = 0.0
        self.balance_spread = 0.0  # 0 = auto-balance scanner off
        self.lifecycle_interval = 0.0  # 0 = lifecycle sweeps off
        self.lifecycle_filer = ""
        self._lifecycle_last = 0.0
        self.ec_balance_interval = 0.0  # 0 = auto ec_balance scanner off
        self._ec_balance_last = 0.0
        self._vacuum_stop = threading.Event()
        self._vacuum_thread = threading.Thread(
            target=self._vacuum_loop, daemon=True
        )

        from ..worker.control import WorkerControl

        self.worker_control = WorkerControl(
            topo=self.topo,
            config_get=self._maintenance_config,
            config_set=self._apply_maintenance_config,
        )
        self._grpc = grpc.server(futures.ThreadPoolExecutor(max_workers=32))
        rpc.add_service(self._grpc, rpc.MASTER_SERVICE, self.service)
        rpc.add_service(self._grpc, rpc.WORKER_SERVICE, self.worker_control)
        rpc.add_service(self._grpc, rpc.RAFT_SERVICE, self.raft)
        self._grpc.add_insecure_port(f"{ip}:{self.grpc_port}")

        self._http = ThreadingHTTPServer((ip, port), self._handler_class())
        self.tls = tls
        if tls is not None:
            tls.wrap_server(self._http)
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, daemon=True
        )

        # opt-in phone-home (reference weed/telemetry/collector.go:14):
        # leader-only aggregate counts, never names or data
        from ..utils.telemetry import TelemetryCollector

        def _tele_stats() -> dict:
            st = self.topo.statistics()
            return {
                "volume_count": st.volume_count,
                "ec_volume_count": st.ec_volume_count,
                "server_count": st.node_count,
                "used_size": st.used_size,
                "file_count": st.file_count,
            }

        self.telemetry = TelemetryCollector(
            telemetry_url, _tele_stats, is_leader_fn=lambda: self.raft.is_leader
        )

    # --------------------------------------------------------------- ha

    def _raft_apply(self, kind: str, value: int) -> int:
        if kind == "alloc_volume_id":
            return self.topo.apply_allocated_volume_id(value)
        return 0

    def _raft_restore(self, state: dict) -> None:
        """Reload the raft-snapshot state machine (log compaction /
        InstallSnapshot): the allocator must never go backwards."""
        self.topo.max_volume_id = max(
            self.topo.max_volume_id, int(state.get("max_volume_id", 0))
        )

    def _alloc_volume_id(self) -> int:
        """Volume ids are allocated through the replicated log so a
        failed-over leader can never reuse one (reference: raft-backed
        max volume id)."""
        return self.raft.propose("alloc_volume_id", self.topo.max_volume_id)

    def _on_leader_change(self, leader: str) -> None:
        self.topo.publish_leader(leader)

    @property
    def is_leader(self) -> bool:
        return self.raft.is_leader

    # ------------------------------------------------------------- http

    def _handler_class(self):
        master = self

        from ..utils.request_id import RequestTracingMixin

        class Handler(RequestTracingMixin, BaseHTTPRequestHandler):
            trace_server_kind = "master"

            def log_message(self, *a):
                pass

            def _json(self, code: int, obj: dict) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                u = urlparse(self.path)
                q = parse_qs(u.query)
                from ..utils.pprof import handle_debug_endpoint

                if handle_debug_endpoint(self, u):
                    return
                if self.serve_slo_endpoint(u.path):
                    return
                if u.path == "/dir/assign":
                    resp = master.service.Assign(
                        pb.AssignRequest(
                            count=int(q.get("count", ["1"])[0]),
                            collection=q.get("collection", [""])[0],
                            replication=q.get("replication", [""])[0],
                            ttl=q.get("ttl", [""])[0],
                            disk_type=q.get("disk", [""])[0],
                        ),
                        None,
                    )
                    if resp.error:
                        self._json(500, {"error": resp.error})
                    else:
                        out = {
                            "fid": resp.fid,
                            "count": resp.count,
                            "url": resp.location.url,
                            "publicUrl": resp.location.public_url,
                        }
                        if resp.jwt:
                            out["auth"] = resp.jwt
                        self._json(200, out)
                elif u.path == "/dir/lookup":
                    vid = int(q.get("volumeId", ["0"])[0].split(",")[0])
                    resp = master.service.LookupVolume(
                        pb.LookupVolumeRequest(volume_ids=[vid]), None
                    )
                    vl = resp.volume_locations[0]
                    if vl.error:
                        self._json(404, {"error": vl.error})
                    else:
                        self._json(
                            200,
                            {
                                "volumeId": str(vid),
                                "locations": [
                                    {"url": l.url, "publicUrl": l.public_url}
                                    for l in vl.locations
                                ],
                            },
                        )
                elif u.path == "/metrics":
                    from ..utils.metrics import REGISTRY

                    body = REGISTRY.render()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif u.path in ("/", "/ui"):
                    self._ui()
                elif u.path in ("/cluster/status", "/dir/status"):
                    topo = master.topo.to_proto()
                    # heartbeat-learned device telemetry per host: the
                    # master never probes volume servers for this —
                    # chips/breakers/stage-EWMAs arrive ONLY on the
                    # heartbeat stream (Heartbeat.ec_telemetry_json).
                    # Each entry carries its AGE (seconds since the
                    # master absorbed it) and whether the stale-aging
                    # gate (SEAWEED_EC_TELEMETRY_STALE_S) has stopped
                    # it from steering placement/gravity.
                    from ..ec.placement import telemetry_stale_after

                    stale_after = telemetry_stale_after()
                    now = time.time()
                    tele = {}
                    for node in list(master.topo.nodes.values()):
                        if not node.ec_telemetry:
                            continue
                        blob = dict(node.ec_telemetry)
                        stamped = blob.get("received_at") or blob.get("ts")
                        try:
                            age = max(now - float(stamped), 0.0)
                        except (TypeError, ValueError):
                            age = -1.0
                        blob["age_s"] = round(age, 1)
                        blob["stale"] = bool(age > stale_after >= 0)
                        tele[node.node_id] = blob
                    self._json(
                        200,
                        {
                            "IsLeader": True,
                            "MaxVolumeId": topo.max_volume_id,
                            "DataNodes": [
                                {
                                    "id": n.id,
                                    "volumes": len(n.volumes),
                                    "ecShards": len(n.ec_shards),
                                }
                                for n in topo.nodes
                            ],
                            "EcTelemetry": tele,
                            # fleet scrub health: per-holder bitrot /
                            # quarantine aggregated from ec_scrub task
                            # reports (worker/control.py)
                            "EcFleetScrub": (
                                master.worker_control.scrub_summary()
                            ),
                            # data-gravity evidence: the most recent
                            # ec_migrate dispatches (volume, src->dst,
                            # heat, gravity scores) from the scanner
                            "EcMigrations": (
                                master.worker_control.last_migrations
                            ),
                            # streaming-EC roll-up (sw_ec_stream_*):
                            # open encode-on-write streams in THIS
                            # process (combined deployments / tests)
                            # with live parity lag + lifetime counters
                            "EcStreams": _ec_stream_summary(),
                            # multi-tenant overload safety: the local
                            # chip residency ledger (combined deploys)
                            # plus each volume server's ledger snapshot
                            # as it rode in on the heartbeat telemetry
                            "EcResidency": {
                                "local": _ec_residency_summary(),
                                "nodes": {
                                    nid: blob.get("residency")
                                    for nid, blob in tele.items()
                                    if blob.get("residency")
                                },
                            },
                        },
                    )
                else:
                    self._json(404, {"error": "not found"})

            def _ui(self):
                """Minimal admin status page (reference weed/admin dash,
                server-rendered). Every interpolated string is escaped —
                collection/replication/ttl arrive from clients."""
                import html as _html

                esc = _html.escape
                topo = master.topo.to_proto()
                stats = master.topo.statistics()
                rows = []
                for n in topo.nodes:
                    vols = "".join(
                        f"<tr><td>{v.id}</td><td>{esc(v.collection) or '-'}</td>"
                        f"<td>{v.size:,}</td><td>{v.file_count}</td>"
                        f"<td>{v.deleted_count}</td>"
                        f"<td>{'RO' if v.read_only else 'RW'}</td>"
                        f"<td>{esc(v.replica_placement)}</td><td>{esc(v.ttl) or '-'}</td></tr>"
                        for v in sorted(n.volumes, key=lambda v: v.id)
                    )
                    ecs = "".join(
                        f"<tr><td>ec {e.id}</td><td>{esc(e.collection) or '-'}</td>"
                        f"<td colspan=2>shards {[i for i in range(32) if e.shard_bits & (1 << i)]}</td>"
                        f"<td colspan=4>{e.data_shards}+{e.parity_shards} gen {e.generation}</td></tr>"
                        for e in sorted(n.ec_shards, key=lambda e: e.id)
                    )
                    rows.append(
                        f"<h3>{esc(n.id)} <small>rack={esc(n.rack) or '-'} dc={esc(n.data_center) or '-'}"
                        f" slots={n.max_volume_count}</small></h3>"
                        f"<table border=1 cellpadding=4 cellspacing=0>"
                        f"<tr><th>vol</th><th>coll</th><th>size</th><th>files</th>"
                        f"<th>del</th><th>mode</th><th>rp</th><th>ttl</th></tr>"
                        f"{vols}{ecs}</table>"
                    )
                # maintenance fleet panel (public snapshot: the UI must
                # not depend on WorkerControl's locking internals)
                worker_rows, task_rows = master.worker_control.snapshot()
                workers = [
                    f"<tr><td>{esc(w['worker_id'])}</td>"
                    f"<td>{esc(','.join(w['capabilities']))}</td>"
                    f"<td>{esc(w['backend'])}</td>"
                    f"<td>{w['active']}/{w['max_concurrent']}</td></tr>"
                    for w in worker_rows
                ]
                tasks = [
                    f"<tr><td>{esc(t['task_id'])}</td><td>{esc(t['kind'])}</td>"
                    f"<td>{t['volume_id']}</td><td>{esc(t['state'])}</td>"
                    f"<td>{t['progress']:.0%}</td>"
                    f"<td>{esc(t['worker_id']) or '-'}</td>"
                    f"<td>{esc(t['error']) or '-'}</td></tr>"
                    for t in sorted(task_rows, key=lambda t: -t["created"])[:50]
                ]
                fleet = (
                    "<h2>maintenance fleet</h2>"
                    "<table border=1 cellpadding=4 cellspacing=0>"
                    "<tr><th>worker</th><th>capabilities</th><th>backend</th>"
                    "<th>active</th></tr>"
                    + ("".join(workers) or "<tr><td colspan=4>no workers</td></tr>")
                    + "</table><br>"
                    "<table border=1 cellpadding=4 cellspacing=0>"
                    "<tr><th>task</th><th>kind</th><th>vol</th><th>state</th>"
                    "<th>progress</th><th>worker</th><th>error</th></tr>"
                    + ("".join(tasks) or "<tr><td colspan=7>no tasks</td></tr>")
                    + "</table>"
                )
                body = (
                    "<html><head><title>seaweed-tpu master</title></head><body>"
                    f"<h1>seaweed-tpu cluster</h1>"
                    f"<p>nodes: {stats.node_count} &middot; volumes: "
                    f"{stats.volume_count} &middot; ec volumes: {stats.ec_volume_count}"
                    f" &middot; files: {stats.file_count} &middot; used: "
                    f"{stats.used_size:,} bytes &middot; max volume id: "
                    f"{topo.max_volume_id}</p>"
                    + "".join(rows)
                    + fleet
                    + "</body></html>"
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            do_POST = do_GET

        return Handler

    # ------------------------------------------------- maintenance config

    def _maintenance_config(self) -> dict:
        return {
            "ec_auto_fullness": self.ec_auto_fullness,
            "ec_quiet_seconds": self.ec_quiet_seconds,
            "garbage_threshold": self.garbage_threshold,
            "vacuum_interval_seconds": self.vacuum_interval,
            "balance_spread": self.balance_spread,
            "lifecycle_interval_seconds": self.lifecycle_interval,
            "lifecycle_filer": self.lifecycle_filer,
            "ec_balance_interval_seconds": self.ec_balance_interval,
            "ec_scrub_interval_seconds": self.ec_scrub_interval,
            "ec_rebalance_interval_seconds": self.ec_rebalance_interval,
        }

    def _apply_maintenance_config(self, cfg: dict) -> None:
        """Live-apply tuned policy: every knob is re-read each loop
        iteration, so no restart is needed. Validation here fails the
        whole update — a half-applied policy is worse than none."""
        import math

        # isfinite first: NaN slips through comparison-based range
        # checks ('quiet < 0' is False for NaN) and a NaN vacuum
        # interval turns _vacuum_loop into a hot busy-spin.
        for key in (
            "ec_auto_fullness",
            "ec_quiet_seconds",
            "garbage_threshold",
            "vacuum_interval_seconds",
            "balance_spread",
            "lifecycle_interval_seconds",
            "ec_balance_interval_seconds",
            "ec_scrub_interval_seconds",
            "ec_rebalance_interval_seconds",
        ):
            if not math.isfinite(cfg.get(key, 0.0)):
                raise ValueError(f"{key} must be finite, got {cfg.get(key)}")
        full = cfg.get("ec_auto_fullness", 0.0)
        if not (0.0 <= full <= 1.0):
            raise ValueError(f"ec_auto_fullness must be in [0,1], got {full}")
        thresh = cfg.get("garbage_threshold", 0.0)
        if not (0.0 < thresh <= 1.0):
            raise ValueError(
                f"garbage_threshold must be in (0,1], got {thresh}"
            )
        quiet = cfg.get("ec_quiet_seconds", 0.0)
        interval = cfg.get("vacuum_interval_seconds", 0.0)
        if quiet < 0 or interval <= 0:
            raise ValueError(
                "ec_quiet_seconds must be >=0 and "
                f"vacuum_interval_seconds >0 (got {quiet}, {interval})"
            )
        spread = cfg.get("balance_spread", 0.0)
        lc_interval = cfg.get("lifecycle_interval_seconds", 0.0)
        ecb_interval = cfg.get("ec_balance_interval_seconds", 0.0)
        scrub_interval = cfg.get("ec_scrub_interval_seconds", 0.0)
        rebal_interval = cfg.get("ec_rebalance_interval_seconds", 0.0)
        if (
            spread < 0 or lc_interval < 0 or ecb_interval < 0
            or scrub_interval < 0 or rebal_interval < 0
        ):
            raise ValueError(
                "balance_spread, lifecycle_interval_seconds, "
                "ec_balance_interval_seconds, ec_scrub_interval_seconds "
                "and ec_rebalance_interval_seconds "
                f"must be >=0 (got {spread}, {lc_interval}, "
                f"{ecb_interval}, {scrub_interval}, {rebal_interval})"
            )
        self.ec_auto_fullness = full
        self.ec_quiet_seconds = quiet
        self.garbage_threshold = thresh
        self.vacuum_interval = interval
        self.balance_spread = spread
        self.lifecycle_interval = lc_interval
        self.lifecycle_filer = str(cfg.get("lifecycle_filer", "") or "")
        self.ec_balance_interval = ecb_interval
        # the scrub scanner re-reads this every vacuum tick, so a live
        # update takes effect without restart (0 turns fleet scrub off)
        self.ec_scrub_interval = scrub_interval
        # gravity/heat rebalance cadence — same live-reload contract as
        # scrub above (0 disables the heat-driven migration scanner)
        self.ec_rebalance_interval = rebal_interval

    # ----------------------------------------------------------- vacuum

    def _vacuum_loop(self) -> None:
        """Periodic garbage sweep (reference topology_vacuum.go): ask
        every holder of a garbage-heavy volume to compact. Doubles as
        the dead-node sweeper for heartbeat streams that hung without
        breaking (prune_dead was otherwise never invoked)."""
        from ..utils.glog import logger

        log = logger("master")
        while not self._vacuum_stop.wait(self.vacuum_interval):
            # one bad tick must not kill the thread: this loop is ALSO
            # the garbage sweep and the dead-node pruner — a scanner
            # exception silently disabling vacuum cluster-wide is far
            # worse than a skipped scan
            try:
                self.topo.prune_dead()
                self.vacuum_once()
                if self.ec_auto_fullness > 0:
                    self.worker_control.scan_for_ec_candidates(
                        self.topo,
                        self.ec_auto_fullness,
                        self.topo.volume_size_limit,
                        quiet_seconds=self.ec_quiet_seconds,
                    )
                if self.balance_spread > 0:
                    self.worker_control.scan_for_balance_candidates(
                        self.topo, int(self.balance_spread)
                    )
                if self.lifecycle_interval > 0 and self.lifecycle_filer:
                    now = time.time()
                    if now - self._lifecycle_last >= self.lifecycle_interval:
                        self._lifecycle_last = now
                        self.worker_control.scan_for_lifecycle(
                            self.lifecycle_filer
                        )
                if self.ec_balance_interval > 0:
                    now = time.time()
                    if now - self._ec_balance_last >= self.ec_balance_interval:
                        self._ec_balance_last = now
                        self.worker_control.scan_for_ec_balance(self.topo)
                if self.ec_scrub_interval > 0:
                    # per-volume due-ness lives in the scanner; calling
                    # it every tick is what staggers volumes across the
                    # period instead of stampeding at each deadline
                    self.worker_control.scan_for_ec_scrub(
                        self.topo, self.ec_scrub_interval
                    )
                if self.ec_rebalance_interval > 0:
                    now = time.time()
                    if (
                        now - self._ec_rebalance_last
                        >= self.ec_rebalance_interval
                    ):
                        self._ec_rebalance_last = now
                        self.worker_control.scan_for_ec_rebalance(self.topo)
            except Exception as e:
                log.error(
                    "maintenance tick failed (%s: %s); loop continues",
                    type(e).__name__,
                    e,
                )

    def vacuum_once(self) -> list[int]:
        vacuumed = []
        for vid, ip, gport in self.topo.garbage_candidates(self.garbage_threshold):
            try:
                with grpc.insecure_channel(f"{ip}:{gport}") as ch:
                    rpc.volume_stub(ch).VacuumVolume(
                        pb.VacuumRequest(
                            volume_id=vid,
                            garbage_threshold=self.garbage_threshold,
                        ),
                        timeout=3600,
                    )
                vacuumed.append(vid)
            except grpc.RpcError:
                continue
        return vacuumed

    # -------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._grpc.start()
        self.raft.start()
        self._http_thread.start()
        self._vacuum_thread.start()
        self.telemetry.start()

    def stop(self) -> None:
        self.telemetry.stop()
        self.worker_control.stop()
        if self.service.dlm is not None:
            self.service.dlm.close()
        self.raft.stop()
        self._vacuum_stop.set()
        self._grpc.stop(grace=0.5)
        self._http.shutdown()
        self._http.server_close()

    def wait(self) -> None:
        self._grpc.wait_for_termination()
