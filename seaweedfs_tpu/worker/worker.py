"""Maintenance worker: connects to the master's WorkerControl stream,
registers capabilities, executes assigned tasks with progress reporting.

Reference: weed/worker (client.go bidi stream, tasks/registry.go task
types) and the plugin worker JobHandler model (plugin/worker/worker.go).
The ec_encode handler drives the same RPC pipeline the shell uses
(readonly -> generate(backend) -> mount -> delete source) — running it
with -backend tpu makes this process the TPU EC sidecar.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import uuid

import grpc

from ..client.master_client import MasterClient, volume_channel
from ..ec import fleet
from ..pb import cluster_pb2 as pb
from ..pb import rpc
from ..pb import worker_pb2 as wk
from ..utils import request_id as _rid
from ..utils import trace
from .control import VOLUME_INDEPENDENT_KINDS


class Worker:
    def __init__(
        self,
        master: str = "localhost:9333",
        capabilities: tuple = (
            "ec_encode", "vacuum", "balance", "s3_lifecycle", "ec_balance",
            "iceberg", "ec_scrub", "ec_rebuild", "ec_migrate",
        ),
        backend: str = "auto",
        max_concurrent: int = 2,
        worker_id: str = "",
    ):
        self.master_addr = master
        self.worker_id = worker_id or f"worker-{uuid.uuid4().hex[:8]}"
        self.capabilities = capabilities
        self.backend = backend
        self.max_concurrent = max_concurrent
        # declarative per-job config (reference weed/admin/plugin):
        # built-in kinds ship their tunables; plugin workers may extend
        self.descriptors: list[wk.TaskDescriptor] = [
            wk.TaskDescriptor(
                kind="ec_encode",
                display_name="Erasure encode",
                description="RS 10+4 encode a sealed volume into shards",
                fields=[
                    wk.ConfigField(
                        name="batch_mb",
                        type="int",
                        default="16",
                        help="device batch size per shard (MiB)",
                        min=1,
                        max=256,
                    )
                ],
            ),
            wk.TaskDescriptor(
                kind="vacuum",
                display_name="Vacuum",
                description="compact a volume, dropping deleted needles",
                fields=[
                    wk.ConfigField(
                        name="garbage_threshold",
                        type="float",
                        default="0",
                        help="minimum reclaimable fraction "
                        "(0 = always compact, the historical behavior)",
                        min=0.0,
                        max=1.0,
                    )
                ],
            ),
            wk.TaskDescriptor(
                kind="balance",
                display_name="Volume balance",
                description="move one volume replica between nodes "
                "(readonly -> copy -> delete at source)",
                fields=[
                    wk.ConfigField(
                        name="source",
                        type="string",
                        default="",
                        help="grpc host:port of the replica to move",
                    ),
                    wk.ConfigField(
                        name="target",
                        type="string",
                        default="",
                        help="grpc host:port of the receiving node",
                    ),
                ],
            ),
            wk.TaskDescriptor(
                kind="ec_balance",
                display_name="EC shard balance",
                description="dedupe + rack-aware spread of EC shards "
                "(runs the shell planner/executor)",
                fields=[
                    wk.ConfigField(
                        name="collection",
                        type="string",
                        default="",
                        help="restrict to one collection (empty = all)",
                    ),
                ],
            ),
            wk.TaskDescriptor(
                kind="ec_scrub",
                display_name="Fleet EC scrub",
                description="verify one EC volume's shards against the "
                ".ecsum sidecar on EVERY holder; repair locally where "
                "possible, report unrebuildable holders to the master",
                fields=[
                    wk.ConfigField(
                        name="repair",
                        type="bool",
                        default="true",
                        help="rebuild corrupt/missing shards on holders "
                        "that still have k verified-good local shards",
                    ),
                ],
            ),
            wk.TaskDescriptor(
                kind="ec_rebuild",
                display_name="EC rebuild",
                description="regenerate missing/corrupt EC shards on a "
                "holder; -fromPeers streams sibling shards from peer "
                "holders when the holder has fewer than k local shards",
                fields=[
                    wk.ConfigField(
                        name="fromPeers",
                        type="bool",
                        default="false",
                        help="peer-fetch rebuild (cluster self-healing)",
                    ),
                    wk.ConfigField(
                        name="holder",
                        type="string",
                        default="",
                        help="grpc host:port of the holder(s) to rebuild "
                        "on, comma-separated, driven sequentially "
                        "(empty = biggest holder, or smallest with "
                        "fromPeers)",
                    ),
                ],
            ),
            wk.TaskDescriptor(
                kind="ec_migrate",
                display_name="EC hot-volume migration",
                description="move one holder's whole EC shard set to a "
                "chip-rich low-load node (data gravity): copy over the "
                "native shard plane, verify vs .ecsum, unmount source, "
                "mount destination — never two mounted holders",
                fields=[
                    wk.ConfigField(
                        name="source",
                        type="string",
                        default="",
                        help="grpc host:port of the holder to drain",
                    ),
                    wk.ConfigField(
                        name="target",
                        type="string",
                        default="",
                        help="grpc host:port of the receiving node",
                    ),
                    wk.ConfigField(
                        name="shards",
                        type="string",
                        default="",
                        help="comma-separated shard ids to move (empty "
                        "= every shard the source currently holds)",
                    ),
                ],
            ),
            wk.TaskDescriptor(
                kind="iceberg",
                display_name="Iceberg snapshot expiry",
                description="expire old unreferenced table snapshots "
                "via the S3 gateway's catalog maintenance endpoint",
                fields=[
                    wk.ConfigField(
                        name="s3",
                        type="string",
                        default="",
                        help="host:port of the S3 gateway",
                    ),
                    wk.ConfigField(
                        name="access_key",
                        type="string",
                        default="",
                        help="Admin-capable access key",
                    ),
                    wk.ConfigField(
                        name="secret_key",
                        type="string",
                        default="",
                        help="secret for access_key",
                    ),
                    wk.ConfigField(
                        name="older_than_days",
                        type="float",
                        default="30",
                        help="expire snapshots older than this",
                        min=0.0,
                        max=36500.0,
                    ),
                    wk.ConfigField(
                        name="bucket",
                        type="string",
                        default="",
                        help="single table bucket (empty = whole catalog)",
                    ),
                ],
            ),
            wk.TaskDescriptor(
                kind="s3_lifecycle",
                display_name="S3 lifecycle sweep",
                description="apply bucket lifecycle rules (expiration, "
                "noncurrent cleanup, upload aborts) on a filer",
                fields=[
                    wk.ConfigField(
                        name="filer",
                        type="string",
                        default="",
                        help="grpc host:port of the filer to sweep",
                    ),
                    wk.ConfigField(
                        name="bucket",
                        type="string",
                        default="",
                        help="single bucket to sweep (empty = all)",
                    ),
                ],
            ),
        ]
        self._outbox: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._mc = MasterClient(master)
        self.completed: list[str] = []

    # ------------------------------------------------------------- stream

    def _messages(self):
        yield wk.WorkerMessage(
            register=wk.Register(
                worker_id=self.worker_id,
                capabilities=list(self.capabilities),
                max_concurrent=self.max_concurrent,
                backend=self.backend,
                descriptors=[
                    d for d in self.descriptors if d.kind in self.capabilities
                ],
            )
        )
        while not self._stop.is_set():
            try:
                msg = self._outbox.get(timeout=1.0)
                yield msg
            except queue.Empty:
                yield wk.WorkerMessage(heartbeat=wk.WorkerHeartbeat())

    def run(self) -> None:
        """Connect-and-serve loop; reconnects on stream loss."""
        while not self._stop.is_set():
            try:
                channel = grpc.insecure_channel(self._mc.grpc_addr)
                stub = rpc.Stub(channel, rpc.WORKER_SERVICE)
                for server_msg in stub.WorkerStream(self._messages()):
                    if self._stop.is_set():
                        break
                    if server_msg.WhichOneof("body") == "assign":
                        threading.Thread(
                            target=self._execute,
                            args=(server_msg.assign,),
                            daemon=True,
                        ).start()
                channel.close()
            except grpc.RpcError:
                if self._stop.wait(1.0):
                    return

    def stop(self) -> None:
        self._stop.set()

    # -------------------------------------------------------------- tasks

    def _report(
        self,
        task_id: str,
        state: str,
        progress: float = 0.0,
        error: str = "",
        detail: str = "",
    ) -> None:
        self._outbox.put(
            wk.WorkerMessage(
                update=wk.TaskUpdate(
                    task_id=task_id, state=state, progress=progress,
                    error=error, detail=detail,
                )
            )
        )

    def _execute(self, assign: wk.TaskAssign) -> None:
        # One request id per task, seeded from the task id: every
        # holder this task drives (scrub, rebuild, mount RPCs) logs the
        # SAME id, so grepping one fleet task across servers works.
        # When the flight recorder is armed the task is the trace root
        # — a dispatched peer-fetch rebuild and every peer shard-read
        # it triggers share this trace id.
        _rid.ensure(assign.task_id or None)
        sp = trace.start(
            f"task.{assign.kind}", name=assign.task_id,
            volume=assign.volume_id, worker=self.worker_id,
        )
        try:
            with trace.activate(sp):
                self._execute_task(assign)
        finally:
            trace.finish(sp)

    def _execute_task(self, assign: wk.TaskAssign) -> None:
        self._report(assign.task_id, "running", 0.0)
        if assign.kind in VOLUME_INDEPENDENT_KINDS:
            lock_name = f"task/{assign.kind}"
        else:
            lock_name = f"volume/{assign.volume_id}"
        token = ""
        try:
            # per-volume cluster lease: a shell ec.encode on the same
            # volume (which takes the same lease) cannot interleave with
            # this task's destructive steps
            token = self._mc.lock(
                lock_name, self.worker_id, ttl=3600.0, wait=5.0
            )
            detail = ""
            if assign.kind == "ec_encode":
                self._task_ec_encode(assign)
            elif assign.kind == "vacuum":
                self._task_vacuum(assign)
            elif assign.kind == "balance":
                self._task_balance(assign)
            elif assign.kind == "s3_lifecycle":
                self._task_s3_lifecycle(assign)
            elif assign.kind == "ec_balance":
                self._task_ec_balance(assign)
            elif assign.kind == "iceberg":
                self._task_iceberg(assign)
            elif assign.kind == "ec_scrub":
                detail = self._task_ec_scrub(assign)
            elif assign.kind == "ec_rebuild":
                detail = self._task_ec_rebuild(assign)
            elif assign.kind == "ec_migrate":
                detail = self._task_ec_migrate(assign)
            else:
                raise RuntimeError(f"unknown task kind {assign.kind}")
            self._report(assign.task_id, "done", 1.0, detail=detail)
            self.completed.append(assign.task_id)
        except Exception as e:
            self._report(assign.task_id, "failed", 0.0, error=str(e))
        finally:
            if token:
                self._mc.unlock(lock_name, token)

    def _holder_stubs(self, vid: int):
        locs = self._mc.lookup(vid, refresh=True)
        if not locs:
            raise RuntimeError(f"volume {vid} has no locations")
        out = []
        for loc in locs:
            ch = volume_channel(loc)
            out.append((loc, ch, rpc.volume_stub(ch)))
        return out

    def _task_ec_encode(self, assign: wk.TaskAssign) -> None:
        vid = assign.volume_id
        holders = self._holder_stubs(vid)
        try:
            for _, _, stub in holders:
                stub.VolumeMarkReadonly(
                    pb.VolumeCommandRequest(volume_id=vid), timeout=30
                )
            self._report(assign.task_id, "running", 0.2)
            _, _, gen_stub = holders[0]
            try:
                batch_mb = int(assign.params.get("batch_mb", "") or 0)
            except ValueError:
                batch_mb = 0
            gen_stub.VolumeEcShardsGenerate(
                pb.EcShardsGenerateRequest(
                    volume_id=vid,
                    collection=assign.collection,
                    backend=assign.backend or self.backend,
                    batch_mb=batch_mb,
                ),
                timeout=3600,
                metadata=trace.grpc_metadata(),
            )
            self._report(assign.task_id, "running", 0.8)
            gen_stub.VolumeEcShardsMount(
                pb.EcShardsMountRequest(
                    volume_id=vid, collection=assign.collection
                ),
                timeout=60,
                metadata=trace.grpc_metadata(),
            )
            for _, _, stub in holders:
                stub.VolumeDelete(
                    pb.VolumeCommandRequest(volume_id=vid), timeout=60
                )
        finally:
            for _, ch, _ in holders:
                ch.close()

    def _task_balance(self, assign: wk.TaskAssign) -> None:
        """Move one replica: readonly at source -> VolumeCopy into the
        target -> delete at source (reference worker balance task /
        shell volume.move). A failed copy restores the source
        writable so the move never strands the volume."""
        vid = assign.volume_id
        source = assign.params.get("source", "")
        target = assign.params.get("target", "")
        if not source or not target:
            raise RuntimeError("balance needs source and target params")
        with grpc.insecure_channel(source) as src_ch:
            src = rpc.volume_stub(src_ch)
            src.VolumeMarkReadonly(
                pb.VolumeCommandRequest(volume_id=vid), timeout=30
            )
            self._report(assign.task_id, "running", 0.2)
            try:
                with grpc.insecure_channel(target) as dst_ch:
                    r = rpc.volume_stub(dst_ch).VolumeCopy(
                        pb.EcShardsCopyRequest(
                            volume_id=vid,
                            collection=assign.collection,
                            source_url=source,
                        ),
                        timeout=3600,
                    )
                if r.error:
                    raise RuntimeError(f"copy failed: {r.error}")
            except Exception:
                src.VolumeMarkWritable(
                    pb.VolumeCommandRequest(volume_id=vid), timeout=30
                )
                raise
            self._report(assign.task_id, "running", 0.8)
            last_err: Exception | None = None
            for _attempt in range(3):
                try:
                    src.VolumeDelete(
                        pb.VolumeCommandRequest(volume_id=vid), timeout=60
                    )
                    return
                except grpc.RpcError as e:
                    last_err = e
                    time.sleep(1.0)
            # copy landed but the source copy survives (readonly, so no
            # divergence) — fail LOUDLY so an operator finishes the move
            raise RuntimeError(
                f"balance: volume {vid} copied to {target} but source "
                f"delete on {source} failed after retries ({last_err}); "
                "volume is duplicated and readonly at the source"
            )

    def _task_ec_balance(self, assign: wk.TaskAssign) -> None:
        """Rack-aware EC shard rebalancing: reuses the SHELL's planner
        and executor (ec/placement.py + ec.balance) so the worker and
        the operator path cannot drift — per-volume leases are taken
        inside the command itself."""
        import re
        import shlex

        from ..shell.commands import ShellEnv, run_command

        env = ShellEnv(self.master_addr)
        try:
            # the param is caller-supplied text headed for a shlex-split
            # argparse command line: quote it AND reject non-name shapes
            # (a leading "-" would read as a flag and argparse's
            # SystemExit is not an Exception — the task would hang in
            # 'running' instead of failing). -collection on task.submit
            # arrives in assign.collection, a plugin param override wins.
            col = assign.params.get("collection", "") or assign.collection
            if col and not re.fullmatch(r"[A-Za-z0-9_][A-Za-z0-9_.\-]*", col):
                raise RuntimeError(f"invalid collection name {col!r}")
            out = run_command(
                env,
                "ec.balance"
                + (f" -collection {shlex.quote(col)}" if col else ""),
            )
            if out.startswith("error"):
                raise RuntimeError(out)
        finally:
            env.close()

    def _task_ec_scrub(self, assign: wk.TaskAssign) -> str:
        """Fleet scrub of ONE EC volume: verify shards vs .ecsum on
        EVERY holder (the same walk the shell's ec.scrub does), repair
        in place on holders that still have k verified-good local
        shards, and report holders that do NOT — the master's control
        loop turns those into peer-fetch rebuild dispatches. Returns
        the JSON report the master aggregates (TaskUpdate.detail)."""
        vid = assign.volume_id
        shard_locs = self._mc.lookup_ec(vid, refresh=True)
        if not shard_locs:
            raise RuntimeError(f"ec volume {vid} has no holders")
        data_shards = 0
        try:
            for n in self._mc.topology().nodes:
                for e in n.ec_shards:
                    if e.id == vid and e.data_shards:
                        data_shards = e.data_shards
        except grpc.RpcError:
            pass
        if not data_shards:
            from ..ec.context import DATA_SHARDS

            data_shards = DATA_SHARDS
        repair = str(assign.params.get("repair", "true")).lower() in (
            "true", "1",
        )
        holder_sids, loc_by_url = fleet.holder_maps(shard_locs)
        holders: dict[str, dict] = {}
        for url, loc in sorted(loc_by_url.items()):
            dest = fleet.grpc_addr(loc)
            entry = {
                "grpc": dest, "checked": 0, "bad": [], "missing": [],
                "legacy_missing": 0, "quarantined": [], "rebuilt": [],
                "repaired": [], "journal_recovered": 0,
                "unrebuildable": False, "error": "",
            }
            holders[url] = entry
            with grpc.insecure_channel(dest) as ch:
                stub = rpc.volume_stub(ch)
                try:
                    r = stub.ScrubEcVolume(
                        pb.ScrubRequest(
                            volume_id=vid, collection=assign.collection
                        ),
                        timeout=3600,
                        metadata=trace.grpc_metadata(),
                    )
                except grpc.RpcError as e:
                    entry["error"] = e.code().name
                    continue
                if r.error:
                    entry["error"] = r.error
                    continue
                facts = fleet.holder_scrub_facts(
                    r, holder_sids.get(url, set()), data_shards
                )
                entry["checked"] = facts["checked"]
                # crash-recovery evidence: pending repair journals the
                # holder replayed/rolled back before this verify pass
                entry["journal_recovered"] = int(r.repair_journal_recovered)
                entry["bad"] = facts["bad"]
                entry["quarantined"] = facts["quarantined"]
                entry["missing"] = facts["missing"]
                # pre-checked_shards holders report losses only as a
                # count; carried separately so the fleet gauges still
                # see them (per-sid ids are unknowable)
                entry["legacy_missing"] = facts["legacy_gone"]
                if not facts["hurt"]:
                    continue
                if facts["unrebuildable"]:
                    # per-server repair can never fix this holder: the
                    # master dispatches a peer-fetch rebuild from the
                    # aggregated report
                    entry["unrebuildable"] = True
                    continue
                if not repair:
                    continue
                try:
                    rr = stub.VolumeEcShardsRebuild(
                        pb.EcShardsRebuildRequest(
                            volume_id=vid, collection=assign.collection
                        ),
                        timeout=3600,
                        metadata=trace.grpc_metadata(),
                    )
                    stub.VolumeEcShardsMount(
                        pb.EcShardsMountRequest(
                            volume_id=vid, collection=assign.collection
                        ),
                        timeout=60,
                        metadata=trace.grpc_metadata(),
                    )
                    entry["rebuilt"] = sorted(
                        int(x) for x in rr.rebuilt_shard_ids
                    )
                    entry["repaired"] = sorted(
                        int(x) for x in rr.repaired_shard_ids
                    )
                except grpc.RpcError as e:
                    entry["error"] = f"rebuild: {e.details()}"
        return json.dumps({"volume_id": vid, "holders": holders})

    def _task_ec_rebuild(self, assign: wk.TaskAssign) -> str:
        """Rebuild dispatcher: drive VolumeEcShardsRebuild on chosen
        holders — `fromPeers` selects the cluster-level peer-fetch path
        (the task the fleet scrub loop submits for unrebuildable
        holders); `holder` pins the server(s) (comma-separated, driven
        SEQUENTIALLY: concurrent peer rebuilds of one volume could both
        regenerate a cluster-lost shard and mint duplicates), default
        is the shell ec.rebuild heuristic (biggest holder, or the
        SMALLEST for fromPeers — the subset holder local rebuild
        refuses on)."""
        vid = assign.volume_id
        from_peers = str(assign.params.get("fromPeers", "")).lower() in (
            "true", "1",
        )
        holder = assign.params.get("holder", "")
        if not holder:
            shard_locs = self._mc.lookup_ec(vid, refresh=True)
            if not shard_locs:
                raise RuntimeError(f"ec volume {vid} has no holders")
            by_url, loc_by_url = fleet.holder_maps(shard_locs)
            url = fleet.pick_rebuild_holder(by_url, smallest=from_peers)
            loc = loc_by_url[url]
            holder = fleet.grpc_addr(loc)
        results = []
        errors = []
        for dest in [h for h in holder.split(",") if h]:
            try:
                with grpc.insecure_channel(dest) as ch:
                    stub = rpc.volume_stub(ch)
                    r = stub.VolumeEcShardsRebuild(
                        pb.EcShardsRebuildRequest(
                            volume_id=vid,
                            collection=assign.collection,
                            backend=assign.backend or self.backend,
                            from_peers=from_peers,
                        ),
                        timeout=3600,
                        metadata=trace.grpc_metadata(),
                    )
                    if not from_peers:
                        # the peer-fetch path mounts exactly the shards
                        # it owns/adopts itself; a blanket mount here
                        # would also pick up unmounted handoff copies
                        # kept after a failed distribute and advertise
                        # a duplicate holder
                        stub.VolumeEcShardsMount(
                            pb.EcShardsMountRequest(
                                volume_id=vid, collection=assign.collection
                            ),
                            timeout=60,
                            metadata=trace.grpc_metadata(),
                        )
            except grpc.RpcError as e:
                # keep driving the remaining holders: one refused/dead
                # holder must not strand the rest until the next scrub
                # period
                errors.append(f"{dest}: {e.code().name}: {e.details()}")
                continue
            results.append(
                {
                    "holder": dest,
                    "from_peers": from_peers,
                    "rebuilt": sorted(int(x) for x in r.rebuilt_shard_ids),
                    "fetched": sorted(int(x) for x in r.fetched_shard_ids),
                    "distributed": sorted(
                        int(x) for x in r.distributed_shard_ids
                    ),
                    # leaf-granular in-place repairs: healed without a
                    # whole-shard rebuild (~k·64 KiB wire per leaf)
                    "repaired": sorted(
                        int(x) for x in r.repaired_shard_ids
                    ),
                }
            )
        if errors and not results:
            raise RuntimeError("; ".join(errors))
        return json.dumps({"results": results, "errors": errors})

    def _task_ec_migrate(self, assign: wk.TaskAssign) -> str:
        """Hot-volume migration (data gravity, ec/rebalance.py): move
        the source holder's shard set of this volume to the target
        node. Runs under the volume lease the task framework already
        took, so it cannot interleave with an ec.balance of the same
        volume. Idempotent: a crash-rerun converges to exactly one
        mounted holder."""
        from ..ec.rebalance import drive_migration

        vid = assign.volume_id
        source = assign.params.get("source", "")
        target = assign.params.get("target", "")
        if not source or not target:
            raise RuntimeError("ec_migrate needs source and target params")
        shards = [
            int(s) for s in assign.params.get("shards", "").split(",") if s
        ]
        if not shards:
            # every shard the source currently advertises
            by_url, loc_by_url = fleet.holder_maps(
                self._mc.lookup_ec(vid, refresh=True)
            )
            for url, sids in by_url.items():
                if fleet.grpc_addr(loc_by_url[url]) == source:
                    shards = sorted(sids)
            if not shards:
                raise RuntimeError(
                    f"source {source} holds no shards of ec volume {vid}"
                )
        channels: dict[str, grpc.Channel] = {}

        def stub_for(addr: str):
            ch = channels.get(addr)
            if ch is None:
                ch = channels[addr] = grpc.insecure_channel(addr)
            return rpc.volume_stub(ch)

        def lookup_ec():
            located = self._mc.lookup_ec(vid, refresh=True)
            return {
                sid: [fleet.grpc_addr(l) for l in locs]
                for sid, locs in located.items()
            }

        try:
            out = drive_migration(
                vid, assign.collection, source, target, shards,
                stub_for=stub_for, lookup_ec=lookup_ec,
            )
        except grpc.RpcError as e:
            raise RuntimeError(
                f"migrate {source} -> {target}: {e.code().name}: "
                f"{e.details()}"
            ) from e
        finally:
            for ch in channels.values():
                ch.close()
        return json.dumps(out)

    def _task_iceberg(self, assign: wk.TaskAssign) -> None:
        """Iceberg snapshot expiry (reference worker tasks: the iceberg
        maintenance kind). The catalog lives inside the S3 gateway, so
        the task POSTs its Admin-gated /iceberg/v1/maintenance route
        with the sigv4 client the remote-storage SPI already ships."""
        import json as _json

        from ..remote.s3_client import RemoteS3Client

        s3 = assign.params.get("s3", "")
        if not s3:
            raise RuntimeError("iceberg needs an s3 (gateway host:port) param")
        try:
            days = float(assign.params.get("older_than_days", "") or 30)
        except ValueError:
            days = 30.0
        older = int(time.time() * 1000) - int(days * 86400_000)
        bucket = assign.params.get("bucket", "")
        body = {"older-than-ms": older}
        if not bucket:
            body["all-buckets"] = True
        client = RemoteS3Client(
            f"http://{s3}",
            assign.params.get("access_key", ""),
            assign.params.get("secret_key", ""),
        )
        path = (
            f"/iceberg/v1/{bucket}/maintenance"
            if bucket
            else "/iceberg/v1/maintenance"
        )
        r = client._request(
            "POST",
            path,
            payload=_json.dumps(body).encode(),
            extra_headers={"Content-Type": "application/json"},
        )
        out = r.json()
        if not isinstance(out, dict) or "tables_scanned" not in out:
            raise RuntimeError(f"unexpected maintenance response: {out!r}")

    def _task_s3_lifecycle(self, assign: wk.TaskAssign) -> None:
        """Delegate the sweep to the filer that owns the metadata."""
        from ..pb import filer_pb2 as fpb

        filer = assign.params.get("filer", "")
        if not filer:
            raise RuntimeError("s3_lifecycle needs a filer param")
        with grpc.insecure_channel(filer) as ch:
            r = rpc.filer_stub(ch).RunLifecycle(
                fpb.LifecycleRunRequest(
                    bucket=assign.params.get("bucket", "")
                ),
                timeout=3600,
            )
        if r.error:
            raise RuntimeError(r.error)

    def _task_vacuum(self, assign: wk.TaskAssign) -> None:
        # declarative per-job config: garbage_threshold from the
        # validated TaskAssign params. Absent = 0 = ALWAYS compact (the
        # pre-descriptor behavior; an explicitly submitted vacuum must
        # not silently become a no-op), matching the declared default.
        try:
            threshold = float(assign.params.get("garbage_threshold", "") or 0.0)
        except ValueError:
            threshold = 0.0
        for _, ch, stub in self._holder_stubs(assign.volume_id):
            try:
                stub.VacuumVolume(
                    pb.VacuumRequest(
                        volume_id=assign.volume_id,
                        garbage_threshold=threshold,
                    ),
                    timeout=3600,
                )
            finally:
                ch.close()
