"""`python -m seaweedfs_tpu.worker -master host:9333 -backend tpu`
(reference `weed worker`): register with the fleet control plane and
execute maintenance tasks. With -backend tpu this process IS the TPU
EC sidecar."""

from __future__ import annotations

import argparse
import signal
import sys

from .worker import Worker


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="seaweedfs_tpu.worker")
    p.add_argument("-master", default="localhost:9333")
    p.add_argument("-backend", default="auto", help="EC backend: cpu|tpu|auto")
    p.add_argument("-maxConcurrent", type=int, default=2)
    p.add_argument("-capabilities", default="ec_encode,vacuum")
    a = p.parse_args(argv)
    w = Worker(
        master=a.master,
        capabilities=tuple(a.capabilities.split(",")),
        backend=a.backend,
        max_concurrent=a.maxConcurrent,
    )
    signal.signal(signal.SIGTERM, lambda *x: w.stop())
    signal.signal(signal.SIGINT, lambda *x: w.stop())
    print(f"worker {w.worker_id} -> {a.master} (backend={a.backend})", flush=True)
    w.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
