"""Worker-fleet control plane, hosted by the master.

Reference: weed/admin/maintenance (scanner -> queue -> dispatcher) and
weed/admin/plugin (registry/scheduler/dispatcher over
PluginControlService.WorkerStream). One bidi stream per worker carries
registration, heartbeats, task assignment, and progress — the surface a
TPU EC sidecar plugs into (BASELINE.json).
"""

from __future__ import annotations

import json
import queue
import threading
import time
import uuid
from dataclasses import dataclass, field

from ..pb import worker_pb2 as wk
from ..utils import metrics as _M
from ..utils.glog import logger

_log = logger("worker.control")

# Fleet-wide scrub health, aggregated from ec_scrub task reports (the
# master's own view of bitrot across every holder — per-server scrub
# daemons only ever see their own disks).
_fleet_volumes = _M.REGISTRY.gauge(
    "sw_ec_fleet_scrubbed_volumes",
    "EC volumes with a completed fleet scrub report",
)
_fleet_corrupt = _M.REGISTRY.gauge(
    "sw_ec_fleet_corrupt_shards",
    "corrupt EC shards across the fleet (latest scrub reports)",
)
_fleet_missing = _M.REGISTRY.gauge(
    "sw_ec_fleet_missing_shards",
    "advertised-but-missing EC shards across the fleet",
)
_fleet_dispatch = _M.REGISTRY.counter(
    "sw_ec_fleet_peer_rebuild_dispatch_total",
    "peer-fetch rebuild tasks dispatched for unrebuildable holders",
)
_migrate_dispatch = _M.REGISTRY.counter(
    "sw_ec_fleet_migration_dispatch_total",
    "hot-volume ec_migrate tasks dispatched by the gravity scanner",
)


@dataclass
class _Worker:
    worker_id: str
    capabilities: set
    max_concurrent: int
    backend: str
    outbox: "queue.Queue" = field(default_factory=queue.Queue)
    active: int = 0
    last_seen: float = field(default_factory=time.time)
    # declarative per-job config (reference weed/admin/plugin):
    # kind -> TaskDescriptor proto
    descriptors: dict = field(default_factory=dict)


@dataclass
class _Task:
    task_id: str
    kind: str
    volume_id: int
    collection: str
    backend: str
    params: dict = field(default_factory=dict)
    state: str = "pending"  # pending|assigned|running|done|failed
    worker_id: str = ""
    progress: float = 0.0
    error: str = ""
    created: float = field(default_factory=time.time)
    attempts: int = 0


KNOWN_KINDS = (
    "ec_encode", "vacuum", "balance", "s3_lifecycle", "ec_balance", "iceberg",
    "ec_scrub", "ec_rebuild", "ec_migrate",
)
# cluster-wide kinds always submit with volume_id=0: the shell skips the
# -volumeId requirement for them and the worker scopes their cluster
# lease by KIND (task/<kind>) instead of the shared volume/0 name
VOLUME_INDEPENDENT_KINDS = ("ec_balance", "s3_lifecycle", "iceberg")
WORKER_STALE_SECONDS = 30.0
TASK_RETENTION = 1000  # terminal tasks kept for task.list history


class WorkerControl:
    """Registry + queue + dispatcher; also the gRPC servicer."""

    def __init__(self, topo=None, config_get=None, config_set=None):
        """topo: the master Topology, used to resolve volume collections
        and scan for maintenance candidates.

        config_get/config_set: callbacks the hosting master wires in so
        the admin plane can read/tune maintenance policy over gRPC
        (reference admin/maintenance config_schema.go). config_get() ->
        dict of MaintenanceConfig fields; config_set(dict) applies them
        live."""
        self.topo = topo
        self.config_get = config_get
        self.config_set = config_set
        self._lock = threading.Condition()
        self._workers: dict[str, _Worker] = {}
        self._tasks: dict[str, _Task] = {}
        self._pending: list[str] = []
        # (size, since_ts) per volume for the quiet-period check
        self._size_watch: dict[int, tuple[int, float]] = {}
        # vid -> last fleet-scrub submit ts (the stagger state)
        self._scrub_watch: dict[int, float] = {}
        # (node_id, vid) -> last-seen lifetime heat counter, so the
        # gravity scanner ranks per-sweep heat DELTAS, not totals
        self._heat_prev: dict[tuple[str, int], int] = {}
        # last sweep's planned migrations (status surfaces)
        self.last_migrations: list[dict] = []
        # vid -> latest aggregated ec_scrub report (fleet health view)
        self.scrub_reports: dict[int, dict] = {}
        self._stop = threading.Event()
        self._dispatcher = threading.Thread(target=self._dispatch_loop, daemon=True)
        self._dispatcher.start()

    # ----------------------------------------------------------- queueing

    def _resolve_collection(self, volume_id: int) -> str:
        if self.topo is None:
            return ""
        with self.topo._lock:
            for n in self.topo.nodes.values():
                v = n.volumes.get(volume_id)
                if v is not None:
                    return v.collection
        return ""

    def submit(
        self,
        kind: str,
        volume_id: int,
        collection: str = "",
        backend: str = "",
        params: dict | None = None,
    ) -> str:
        with self._lock:  # _workers mutates under this lock
            plugin_kinds = sorted(
                set().union(*(w.capabilities for w in self._workers.values()))
                if self._workers
                else set()
            )
        if kind not in KNOWN_KINDS and kind not in plugin_kinds:
            raise ValueError(
                f"unknown task kind {kind!r} (built-in: {KNOWN_KINDS}; "
                f"connected plugin kinds: {plugin_kinds or 'none'})"
            )
        # explicit = the CALLER stated params; periodic scanners submit
        # with none and must never conflict with an operator's task
        explicit = bool(params)
        params = self._validate_params(kind, dict(params or {}))
        if kind in VOLUME_INDEPENDENT_KINDS:
            # normalize at the ONE choke point: an explicit nonzero vid
            # for a cluster-wide kind would split its dedupe and run
            # the same sweep twice back-to-back
            volume_id = 0
        if not collection:
            # collection determines on-disk paths; a task executed with
            # the wrong one fails AFTER destructive steps
            collection = self._resolve_collection(volume_id)
        task_id = uuid.uuid4().hex[:12]
        with self._lock:
            self._prune_locked()
            # dedupe: one live task per (kind, volume). A duplicate
            # with DIFFERENT params must fail loudly, not silently
            # drop the caller's overrides.
            for t in self._tasks.values():
                if (
                    t.kind == kind
                    and t.volume_id == volume_id
                    # for cluster-wide kinds the collection is part of
                    # the identity (ec_balance of A vs B is different
                    # work); for per-volume kinds it must NOT be — a
                    # mistyped -collection would split the one-live-
                    # task-per-volume guarantee and run a destructive
                    # task under the wrong on-disk paths
                    and (
                        kind not in VOLUME_INDEPENDENT_KINDS
                        or t.collection == collection
                    )
                    and t.state in ("pending", "assigned", "running")
                ):
                    if explicit and params != t.params:
                        # name only the differing KEYS: values can be
                        # credentials (iceberg carries secret_key) and
                        # this string goes back to any submit caller
                        diff = sorted(
                            k
                            for k in set(params) | set(t.params)
                            if params.get(k) != t.params.get(k)
                        )
                        raise ValueError(
                            f"task {t.task_id} for {kind}/{volume_id} is "
                            f"already live with different params (keys: "
                            f"{diff}); cancel it before re-submitting"
                        )
                    return t.task_id
            self._tasks[task_id] = _Task(
                task_id, kind, volume_id, collection, backend, params
            )
            self._pending.append(task_id)
            self._lock.notify_all()
        return task_id

    def _prune_locked(self) -> None:
        terminal = [
            t for t in self._tasks.values() if t.state in ("done", "failed")
        ]
        if len(terminal) > TASK_RETENTION:
            terminal.sort(key=lambda t: t.created)
            for t in terminal[: len(terminal) - TASK_RETENTION]:
                self._tasks.pop(t.task_id, None)

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                self._lock.wait(timeout=0.5)
                # evict hung workers: an open-but-dead stream would pin
                # its tasks 'assigned' forever (heartbeats come every ~1s)
                now = time.time()
                for w in list(self._workers.values()):
                    if now - w.last_seen > WORKER_STALE_SECONDS:
                        w.outbox.put(None)  # closes its pump -> requeue
                still_pending = []
                for task_id in self._pending:
                    t = self._tasks.get(task_id)
                    if t is None or t.state != "pending":
                        continue
                    w = self._pick_worker(t.kind)
                    if w is None:
                        still_pending.append(task_id)
                        continue
                    t.state = "assigned"
                    t.worker_id = w.worker_id
                    w.active += 1
                    assign = wk.TaskAssign(
                        task_id=t.task_id,
                        kind=t.kind,
                        volume_id=t.volume_id,
                        collection=t.collection,
                        backend=t.backend or w.backend,
                    )
                    for pk, pv in t.params.items():
                        assign.params[pk] = pv
                    w.outbox.put(wk.ServerMessage(assign=assign))
                self._pending = still_pending

    def _validate_params(self, kind: str, params: dict) -> dict:
        """Validate submitted params against the kind's declarative
        descriptor (reference weed/admin/plugin DESIGN: per-job config
        schema declared by the worker at registration). Unknown keys
        and type/range violations are rejected; declared defaults fill
        absent fields."""
        desc = None
        with self._lock:
            for w in self._workers.values():
                if kind in w.descriptors:
                    desc = w.descriptors[kind]
                    break
        if desc is None:
            if params:
                raise ValueError(
                    f"task kind {kind!r} declares no config fields"
                )
            return {}
        fields = {f.name: f for f in desc.fields}
        for name in params:
            if name not in fields:
                raise ValueError(
                    f"unknown param {name!r} for {kind!r} "
                    f"(declared: {sorted(fields)})"
                )
        out: dict = {}
        for name, f in fields.items():
            raw = params.get(name, f.default)
            if raw == "" and name not in params:
                continue  # optional, no default
            if f.type == "int":
                try:
                    v = int(raw)
                except ValueError:
                    raise ValueError(f"param {name!r} must be an int") from None
            elif f.type == "float":
                try:
                    v = float(raw)
                except ValueError:
                    raise ValueError(f"param {name!r} must be a float") from None
            elif f.type == "bool":
                if str(raw).lower() not in ("true", "false", "0", "1"):
                    raise ValueError(f"param {name!r} must be a bool")
                v = str(raw).lower() in ("true", "1")
            else:
                v = str(raw)
            if f.type in ("int", "float") and not (f.min == f.max == 0):
                if not (f.min <= float(v) <= f.max):
                    raise ValueError(
                        f"param {name!r}={v} outside [{f.min}, {f.max}]"
                    )
            # NORMALIZED storage ('0.30' == '0.3', 'True' == 'true'):
            # the duplicate-conflict check compares these strings
            out[name] = str(v).lower() if f.type == "bool" else str(v)
        return out

    def _pick_worker(self, kind: str):
        best = None
        for w in self._workers.values():
            if kind not in w.capabilities or w.active >= w.max_concurrent:
                continue
            if best is None or w.active < best.active:
                best = w
        return best

    # ------------------------------------------------------------ servicer

    def WorkerStream(self, request_iterator, context):
        worker: _Worker | None = None
        recv_done = threading.Event()

        def receiver():
            nonlocal worker
            try:
                for msg in request_iterator:
                    kind = msg.WhichOneof("body")
                    if kind == "register":
                        r = msg.register
                        with self._lock:
                            worker = _Worker(
                                worker_id=r.worker_id or uuid.uuid4().hex[:8],
                                capabilities=set(r.capabilities),
                                max_concurrent=r.max_concurrent or 1,
                                backend=r.backend or "auto",
                                descriptors={
                                    d.kind: d for d in r.descriptors
                                },
                            )
                            self._workers[worker.worker_id] = worker
                            self._lock.notify_all()
                        worker.outbox.put(wk.ServerMessage(ack=wk.ServerAck()))
                    elif kind == "heartbeat" and worker is not None:
                        worker.last_seen = time.time()
                    elif kind == "update" and worker is not None:
                        self._apply_update(worker, msg.update)
            except Exception:
                pass  # stream torn down mid-read (worker vanished)
            finally:
                recv_done.set()
                if worker is not None:
                    worker.outbox.put(None)

        t = threading.Thread(target=receiver, daemon=True)
        t.start()
        # wait for registration, then pump the outbox; no deadline —
        # bailing early while the receiver may still register would
        # leak a ghost worker whose outbox nobody drains
        while worker is None and not recv_done.is_set():
            time.sleep(0.05)
        if worker is None:
            return
        try:
            while context.is_active():
                try:
                    item = worker.outbox.get(timeout=0.5)
                except queue.Empty:
                    continue
                if item is None:
                    return
                yield item
        finally:
            with self._lock:
                # a reconnected stream may have re-registered this id
                # with a NEW worker object: only remove our own
                if self._workers.get(worker.worker_id) is worker:
                    self._workers.pop(worker.worker_id, None)
                # requeue tasks the dead worker was running
                for task in self._tasks.values():
                    if task.worker_id == worker.worker_id and task.state in (
                        "assigned",
                        "running",
                    ):
                        task.state = "pending"
                        task.worker_id = ""
                        self._pending.append(task.task_id)
                self._lock.notify_all()

    def _apply_update(self, worker: _Worker, u: wk.TaskUpdate) -> None:
        scrub_done: _Task | None = None
        with self._lock:
            t = self._tasks.get(u.task_id)
            if t is None:
                return
            t.progress = u.progress
            if u.state == "running":
                t.state = "running"
            elif u.state in ("done", "failed"):
                if (
                    u.state == "failed"
                    and "cluster lock" in u.error
                    and t.attempts < 5
                ):
                    # transient contention (a shell holds the volume
                    # lease): requeue instead of terminal failure
                    t.attempts += 1
                    t.state = "pending"
                    t.error = u.error
                    t.worker_id = ""
                    self._pending.append(t.task_id)
                else:
                    t.state = u.state
                    t.error = u.error
                    if t.kind == "ec_scrub" and u.detail:
                        scrub_done = t
                worker.active = max(worker.active - 1, 0)
                self._lock.notify_all()
        if scrub_done is not None:
            # outside the registry lock: aggregation re-enters submit()
            # when it dispatches a peer-fetch rebuild
            self._record_scrub_report(scrub_done, u.detail)

    def SubmitTask(self, request, context):
        try:
            task_id = self.submit(
                request.kind,
                request.volume_id,
                request.collection,
                request.backend,
                params=dict(request.params),
            )
        except ValueError as e:
            return wk.SubmitTaskResponse(error=str(e))
        return wk.SubmitTaskResponse(task_id=task_id)

    def ListTasks(self, request, context):
        with self._lock:
            return wk.ListTasksResponse(
                tasks=[
                    wk.TaskInfo(
                        task_id=t.task_id,
                        kind=t.kind,
                        volume_id=t.volume_id,
                        state=t.state,
                        worker_id=t.worker_id,
                        progress=t.progress,
                        error=t.error,
                    )
                    for t in sorted(
                        self._tasks.values(), key=lambda t: t.created
                    )
                ]
            )

    def ListWorkers(self, request, context):
        workers, _ = self.snapshot()
        with self._lock:
            descs = {
                wid: list(w.descriptors.values())
                for wid, w in self._workers.items()
            }
        return wk.ListWorkersResponse(
            workers=[
                wk.WorkerInfo(
                    worker_id=w["worker_id"],
                    capabilities=w["capabilities"],
                    backend=w["backend"],
                    active=w["active"],
                    max_concurrent=w["max_concurrent"],
                    descriptors=descs.get(w["worker_id"], []),
                )
                for w in workers
            ]
        )

    def GetMaintenanceConfig(self, request, context):
        cfg = self.config_get() if self.config_get else {}
        return wk.MaintenanceConfig(**cfg)

    def SetMaintenanceConfig(self, request, context):
        if self.config_set is None or self.config_get is None:
            return wk.SetMaintenanceConfigResponse(
                error="maintenance config not wired on this master"
            )
        # Read-modify-write: fields absent from the request keep their
        # current value (proto3 optional presence) — a client tuning one
        # knob must not silently zero the others. Held under the lock so
        # two concurrent partial updates cannot interleave and drop one
        # client's knob.
        with self._lock:
            cfg = dict(self.config_get())
            for key in (
                "ec_auto_fullness",
                "ec_quiet_seconds",
                "garbage_threshold",
                "vacuum_interval_seconds",
                "balance_spread",
                "lifecycle_interval_seconds",
                "lifecycle_filer",
                "ec_balance_interval_seconds",
                "ec_scrub_interval_seconds",
                "ec_rebalance_interval_seconds",
            ):
                if request.HasField(key):
                    cfg[key] = getattr(request, key)
            try:
                self.config_set(cfg)
            except ValueError as e:
                return wk.SetMaintenanceConfigResponse(error=str(e))
        return wk.SetMaintenanceConfigResponse()

    def snapshot(self) -> tuple[list[dict], list[dict]]:
        """(workers, tasks) rows for status UIs — the public view, so
        consumers never touch the registry's locking internals."""
        with self._lock:
            workers = [
                {
                    "worker_id": w.worker_id,
                    "capabilities": sorted(w.capabilities),
                    "backend": w.backend,
                    "active": w.active,
                    "max_concurrent": w.max_concurrent,
                }
                for w in self._workers.values()
            ]
            tasks = [
                {
                    "task_id": t.task_id,
                    "kind": t.kind,
                    "volume_id": t.volume_id,
                    "state": t.state,
                    "progress": t.progress,
                    "worker_id": t.worker_id,
                    "error": t.error,
                    "created": t.created,
                }
                for t in self._tasks.values()
            ]
        return workers, tasks

    def stop(self) -> None:
        self._stop.set()

    # ---------------------------------------------------------- detection

    def scan_for_ec_candidates(
        self, topo, fullness: float, volume_size_limit: int, quiet_seconds: float = 0.0
    ) -> list[str]:
        """Auto-detect volumes ready for EC (reference maintenance
        scanner / ec detection.go): full enough AND quiet — encoding
        freezes writes, so actively-written volumes must settle first.
        Quiet = reported size unchanged for quiet_seconds."""
        now = time.time()
        candidates = []
        with topo._lock:
            seen = set()
            for n in topo.nodes.values():
                for v in n.volumes.values():
                    if v.id in seen:
                        continue
                    seen.add(v.id)
                    if v.size >= fullness * volume_size_limit:
                        candidates.append((v.id, v.collection, v.size))
        submitted = []
        for vid, col, size in candidates:
            prev = self._size_watch.get(vid)
            if prev is None or prev[0] != size:
                self._size_watch[vid] = (size, now)
                if quiet_seconds > 0:
                    continue  # just started watching; not yet quiet
            elif now - prev[1] < quiet_seconds:
                continue
            try:
                submitted.append(self.submit("ec_encode", vid, col))
            except ValueError:
                # a live operator task for this volume, or a transient
                # validation issue — the PERIODIC scanner must never
                # kill its hosting loop over it
                continue
        return submitted

    def scan_for_balance_candidates(
        self, topo, spread: int
    ) -> list[str]:
        """Auto-detect imbalance (reference worker balance detection):
        when the busiest node holds >= `spread` more normal volumes
        than the idlest, submit ONE move of a volume the idle node does
        not already replicate. One task per sweep keeps the plane
        convergent instead of thrashing."""
        # full snapshot under the lock: heartbeats mutate node.volumes
        # live, and a KeyError here would kill the hosting scan loop
        with topo._lock:
            nodes = [
                (
                    f"{n.ip}:{n.grpc_port}",
                    {vid: v.collection for vid, v in n.volumes.items()},
                )
                for n in topo.nodes.values()
            ]
        if len(nodes) < 2:
            return []
        nodes.sort(key=lambda nv: len(nv[1]))
        low_addr, low_vols = nodes[0]
        high_addr, high_vols = nodes[-1]
        if len(high_vols) - len(low_vols) < max(spread, 1):
            return []
        movable = sorted(set(high_vols) - set(low_vols))
        if not movable:
            return []
        vid = movable[0]
        try:
            return [
                self.submit(
                    "balance",
                    vid,
                    high_vols[vid],
                    params={"source": high_addr, "target": low_addr},
                )
            ]
        except ValueError:
            return []

    def scan_for_ec_balance(self, topo) -> list[str]:
        """Auto-detect EC shard imbalance (reference worker ec_balance
        detection): run the SAME planner the shell and the worker task
        use over a topology snapshot; any planned drop or move means
        the cluster is out of shape, so submit ONE ec_balance task
        (which re-plans live and executes the full pass)."""
        from ..ec.placement import node_view_for, plan_ec_balance

        with topo._lock:
            views = [
                node_view_for(
                    f"{n.ip}:{n.grpc_port}",
                    n.rack,
                    n.data_center,
                    n.max_volume_count,
                    len(n.volumes),
                    list(n.ec_shards.values()),
                    # heartbeat-learned live chip load: the balance
                    # detector sees compute pressure the same way the
                    # executor's placement scoring will
                    ec_telemetry=n.ec_telemetry,
                )
                for n in topo.nodes.values()
            ]
        if len(views) < 2:
            return []
        drops, moves = plan_ec_balance(views)
        if not drops and not moves:
            return []
        try:
            return [self.submit("ec_balance", 0)]
        except ValueError:
            return []

    def scan_for_ec_scrub(self, topo, period: float) -> list[str]:
        """Fleet-coordinated scrub (reference: maintenance workers own
        hygiene, not each box): every EC volume's shards get verified
        once per `period` FLEET-WIDE — the ec_scrub task walks every
        holder of the volume, so spreading VOLUMES across the window
        spreads the I/O across holders. One submission per sweep (most
        overdue volume first), the same keep-the-plane-convergent rule
        as the balance scanners; with a tick interval well under the
        period, volumes naturally stagger instead of stampeding."""
        now = time.time()
        with topo._lock:
            vols = {
                e.id: e.collection
                for n in topo.nodes.values()
                for e in n.ec_shards.values()
            }
        # evict state for volumes that left the topology (deleted /
        # decoded back to a normal volume): a stale report would hold
        # the fleet gauges nonzero and list the gone volume as
        # unrebuildable forever, and the dict would grow unbounded
        with self._lock:
            gone = [v for v in self.scrub_reports if v not in vols]
            for v in gone:
                del self.scrub_reports[v]
            for v in [v for v in self._scrub_watch if v not in vols]:
                del self._scrub_watch[v]
            reports = list(self.scrub_reports.values())
        if gone:
            self._update_fleet_gauges(reports)
        due = [
            vid
            for vid in vols
            if now - self._scrub_watch.get(vid, 0.0) >= period
        ]
        if not due:
            return []
        due.sort(key=lambda v: (self._scrub_watch.get(v, 0.0), v))
        vid = due[0]
        try:
            tid = self.submit("ec_scrub", vid, vols[vid])
        except ValueError:
            return []  # a live operator task for this volume
        self._scrub_watch[vid] = now
        return [tid]

    def scan_for_ec_rebalance(
        self,
        topo,
        min_heat: int | None = None,
        max_moves: int | None = None,
        min_gain: float | None = None,
    ) -> list[str]:
        """Data-gravity sweep (ec/rebalance.py): rank every EC volume's
        per-holder heat (read/reconstruction byte DELTAS since the last
        sweep, heartbeat-learned) against the holder's chip-deficit and
        dispatch bounded `ec_migrate` worker tasks moving whole shard
        sets toward chip-rich low-load nodes. One planner drives the
        scanner AND the shell's dry-run so they cannot drift; the same
        keep-the-plane-convergent discipline as the other scanners
        (default one migration per sweep)."""
        from ..ec.placement import node_view_for
        from ..ec.rebalance import plan_hot_migrations, volume_heat

        with topo._lock:
            nodes = [
                (
                    f"{n.ip}:{n.grpc_port}",
                    n.rack,
                    n.data_center,
                    n.max_volume_count,
                    len(n.volumes),
                    list(n.ec_shards.values()),
                    dict(n.ec_telemetry),
                )
                for n in topo.nodes.values()
            ]
        if len(nodes) < 2:
            return []
        views = []
        heat: dict[str, dict[int, int]] = {}
        shard_bytes: dict[int, int] = {}
        collections: dict[int, str] = {}
        with self._lock:
            for nid, rack, dc, maxvol, nvol, ecs, tele in nodes:
                views.append(
                    node_view_for(
                        nid, rack, dc, maxvol, nvol, ecs,
                        ec_telemetry=tele,
                    )
                )
                for e in ecs:
                    if e.shard_size:
                        shard_bytes[e.id] = int(e.shard_size)
                    collections.setdefault(e.id, e.collection)
                deltas: dict[int, int] = {}
                for vid, total in volume_heat(tele).items():
                    prev = self._heat_prev.get((nid, vid))
                    self._heat_prev[(nid, vid)] = total
                    if prev is None:
                        continue  # first sighting: no window yet
                    # counter reset (volume-server restart without
                    # persisted heat): re-baseline with a ZERO window
                    # instead of crediting the full lifetime value —
                    # one restart must not read as a sudden hot spot
                    # and trigger spurious migrations. Servers that DO
                    # persist heat across restart (ec_volume .heat
                    # sidecar) never hit this branch: their counters
                    # resume monotonically.
                    deltas[vid] = total - prev if total >= prev else 0
                if deltas:
                    heat[nid] = deltas
            # evict state for (node, vid) pairs that left the topology
            live = {
                (nid, e.id)
                for nid, _r, _d, _m, _v, ecs, _t in nodes
                for e in ecs
            }
            for key in [k for k in self._heat_prev if k not in live]:
                del self._heat_prev[key]
        plans = plan_hot_migrations(
            views, heat, shard_bytes=shard_bytes,
            min_heat=min_heat, max_migrations=max_moves, min_gain=min_gain,
        )
        submitted = []
        records = []
        with self._lock:
            live_before = set(self._tasks)
        for mig in plans:
            rec = {
                "volume_id": mig.vid,
                "src": mig.src,
                "dst": mig.dst,
                "shards": list(mig.shard_ids),
                "heat": mig.heat,
                "src_gravity": round(mig.src_gravity, 3),
                "dst_gravity": round(mig.dst_gravity, 3),
                "ts": time.time(),
            }
            try:
                tid = self.submit(
                    "ec_migrate",
                    mig.vid,
                    collections.get(mig.vid, ""),
                    params={
                        "source": mig.src,
                        "target": mig.dst,
                        "shards": ",".join(str(s) for s in mig.shard_ids),
                    },
                )
            except ValueError as e:
                # a live operator task for this volume / param conflict:
                # the gravity loop must never die over a dispatch race
                _log.warning(
                    "ec_migrate dispatch for %d skipped: %s", mig.vid, e
                )
                continue
            if tid in live_before:
                # submit() deduped onto a migration already in flight
                # (one that outlives a sweep period): not a fresh
                # dispatch — counting/logging it would inflate the
                # counter and fill EcMigrations with duplicates
                continue
            _migrate_dispatch.inc()
            rec["task_id"] = tid
            records.append(rec)
            submitted.append(tid)
            _log.info(
                "dispatched ec_migrate: volume %d (%s -> %s, shards %s, "
                "heat %d B, gravity %.2f -> %.2f)",
                mig.vid, mig.src, mig.dst, list(mig.shard_ids),
                mig.heat, mig.src_gravity, mig.dst_gravity,
            )
        if records:
            with self._lock:
                self.last_migrations = (records + self.last_migrations)[:20]
        return submitted

    def _record_scrub_report(self, t: _Task, detail: str) -> None:
        """Fold one completed ec_scrub task's JSON report into the
        fleet view (master /cluster/status + Prometheus), and dispatch
        a peer-fetch rebuild for every holder the report marks
        quarantined-but-unrebuildable (< k verified-good local shards —
        the case per-server repair can never fix)."""
        try:
            doc = json.loads(detail)
        except ValueError:
            return
        holders = doc.get("holders", {})
        if not isinstance(holders, dict):
            return
        with self._lock:
            self.scrub_reports[t.volume_id] = {
                "ts": time.time(),
                "collection": t.collection,
                "holders": holders,
            }
            reports = list(self.scrub_reports.values())
        self._update_fleet_gauges(reports)
        dests = sorted(
            {
                h["grpc"]
                for h in holders.values()
                if h.get("unrebuildable") and h.get("grpc")
            }
        )
        if not dests:
            return
        try:
            # ONE task carrying every unrebuildable holder (comma-
            # separated): the worker drives them sequentially, because
            # two concurrent peer rebuilds of the same volume could
            # both regenerate a cluster-lost shard and mint duplicates
            self.submit(
                "ec_rebuild",
                t.volume_id,
                t.collection,
                params={"fromPeers": "true", "holder": ",".join(dests)},
            )
            _fleet_dispatch.inc()
            _log.info(
                "dispatched peer-fetch rebuild for ec %d on %s "
                "(unrebuildable holders)", t.volume_id, dests,
            )
        except ValueError as e:
            # duplicate live task / param conflict: the fleet loop
            # must never die over a dispatch race
            _log.warning(
                "peer-fetch dispatch for ec %d skipped: %s",
                t.volume_id, e,
            )

    @staticmethod
    def _update_fleet_gauges(reports: list[dict]) -> None:
        _fleet_volumes.set(len(reports))
        _fleet_corrupt.set(
            sum(
                len(h.get("bad", []))
                for r in reports
                for h in r["holders"].values()
            )
        )
        _fleet_missing.set(
            sum(
                len(h.get("missing", [])) + h.get("legacy_missing", 0)
                for r in reports
                for h in r["holders"].values()
            )
        )

    def scrub_summary(self) -> dict:
        """Fleet scrub health for status UIs: per-volume latest report
        plus roll-up counts."""
        with self._lock:
            reports = {
                vid: {
                    "ts": r["ts"],
                    "collection": r["collection"],
                    "holders": {
                        url: dict(h) for url, h in r["holders"].items()
                    },
                }
                for vid, r in self.scrub_reports.items()
            }
        corrupt = sum(
            len(h.get("bad", []))
            for r in reports.values()
            for h in r["holders"].values()
        )
        missing = sum(
            len(h.get("missing", [])) + h.get("legacy_missing", 0)
            for r in reports.values()
            for h in r["holders"].values()
        )
        unreb = sorted(
            {
                vid
                for vid, r in reports.items()
                if any(
                    h.get("unrebuildable") for h in r["holders"].values()
                )
            }
        )
        return {
            "volumes": len(reports),
            "corrupt_shards": corrupt,
            "missing_shards": missing,
            "unrebuildable_volumes": unreb,
            "reports": reports,
        }

    def scan_for_lifecycle(self, filer_addr: str) -> list[str]:
        """Submit the periodic lifecycle sweep against the configured
        filer (volume_id 0: the task is filer-scoped)."""
        if not filer_addr:
            return []
        try:
            return [
                self.submit(
                    "s3_lifecycle", 0, params={"filer": filer_addr}
                )
            ]
        except ValueError:
            return []
