"""Maintenance worker fleet (layer 8): control plane + workers; the
registration surface for the TPU EC sidecar."""

from .control import WorkerControl
from .worker import Worker
