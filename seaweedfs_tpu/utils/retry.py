"""Unified retry policy: exponential backoff + jitter, deadlines, and a
circuit breaker.

One policy implementation for every hand-rolled retry loop in the tree
(client/master_client.py leader-chasing, ec/scrub.py rebuild attempts,
ec/backend.py device-fallback gating). The reference scatters
equivalent loops across weed/wdclient and weed/operation; keeping one
here means backoff behavior, deadline math, and give-up semantics are
tested once.

Everything time-related is injectable (sleep/clock/rng) so tests run
deterministic schedules in zero wall time.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Callable, TypeVar

T = TypeVar("T")


class RetryError(Exception):
    """All attempts exhausted; __cause__ is the last underlying error."""

    def __init__(self, msg: str, attempts: int, elapsed: float):
        super().__init__(msg)
        self.attempts = attempts
        self.elapsed = elapsed


class CircuitOpenError(Exception):
    """Call rejected without being attempted: the breaker is open."""


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule + give-up rules.

    delay(attempt) for attempt = 1.. is
        min(base_delay * multiplier**(attempt-1), max_delay)
    ± a uniform jitter fraction. `deadline` bounds TOTAL elapsed time
    across attempts: a backoff that would overshoot it is CLAMPED so a
    final attempt lands exactly at the deadline (the caller asked for
    the full budget — a lease freed late is still won); only once the
    deadline is fully spent do retries stop.
    """

    max_attempts: int = 4
    base_delay: float = 0.1
    max_delay: float = 5.0
    multiplier: float = 2.0
    jitter: float = 0.2  # fraction of the delay randomized symmetrically
    deadline: float | None = None  # seconds of total budget, None = no cap
    retry_on: tuple[type[BaseException], ...] = (Exception,)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        d = min(self.base_delay * self.multiplier ** (attempt - 1), self.max_delay)
        if self.jitter and rng is not None:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(d, 0.0)


# A conservative default for cluster RPCs: quick first retry, bounded tail.
DEFAULT_POLICY = RetryPolicy()


def retry_call(
    fn: Callable[[], T],
    policy: RetryPolicy = DEFAULT_POLICY,
    *,
    retry_on: tuple[type[BaseException], ...] | None = None,
    on_retry: Callable[[BaseException, int], None] | None = None,
    sleep: Callable[[float], None] | None = None,
    clock: Callable[[], float] | None = None,
    rng: random.Random | None = None,
    describe: str = "operation",
) -> T:
    """Run fn() under `policy`. `on_retry(exc, attempt)` runs between
    attempts (leader re-resolution, cache invalidation, ...); an
    exception it raises propagates immediately (it is part of recovery,
    not the retried operation). sleep/clock default to time.sleep /
    time.monotonic, resolved at call time so they stay patchable."""
    kinds = retry_on if retry_on is not None else policy.retry_on
    if sleep is None:
        sleep = time.sleep
    if clock is None:
        clock = time.monotonic
    if rng is None:
        rng = random.Random()
    start = clock()
    last: BaseException | None = None
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return fn()
        except kinds as e:
            last = e
        elapsed = clock() - start
        if attempt >= policy.max_attempts:
            break
        d = policy.delay(attempt, rng)
        if policy.deadline is not None:
            remaining = policy.deadline - elapsed
            if remaining <= 0:
                break
            # clamp instead of dropping: the caller asked for the FULL
            # budget, so the last backoff shrinks to land a final
            # attempt at the deadline (a lease freed late is still won)
            d = min(d, remaining)
        if on_retry is not None:
            on_retry(last, attempt)
        sleep(d)
    elapsed = clock() - start
    raise RetryError(
        f"{describe} failed after {attempt} attempts in {elapsed:.2f}s: {last}",
        attempts=attempt,
        elapsed=elapsed,
    ) from last


class Backoff:
    """Consecutive-failure backoff for never-give-up daemon loops.

    retry_call() is for bounded operations; a tail/sync daemon instead
    loops forever and only needs the POLICY'S SCHEDULE: next_delay()
    walks the policy's backoff curve one failure at a time (saturating
    at the tail so delay stops growing), reset() snaps back to the
    first-retry delay after any success. Replaces the hand-rolled
    fixed-sleep loops in replication/ and remote/."""

    def __init__(self, policy: RetryPolicy, rng: random.Random | None = None):
        self.policy = policy
        self._rng = rng if rng is not None else random.Random()
        self._failures = 0

    @property
    def failures(self) -> int:
        return self._failures

    def reset(self) -> None:
        self._failures = 0

    def next_delay(self) -> float:
        self._failures = min(self._failures + 1, self.policy.max_attempts)
        return self.policy.delay(self._failures, self._rng)


class CircuitBreaker:
    """Three-state (closed / open / half-open) failure gate.

    closed: calls flow; `failure_threshold` consecutive failures open it.
    open: allows() is False until `reset_timeout` elapses.
    half-open: one probe call is allowed; success closes the breaker,
    failure re-opens it (with the full timeout again).

    Thread-safe enough for the GIL'd call patterns here: transitions are
    single attribute writes and the worst race admits one extra probe.
    """

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_timeout: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self._clock = clock
        self._failures = 0
        self._opened_at: float | None = None
        self._probe_started: float | None = None

    @property
    def state(self) -> str:
        if self._opened_at is None:
            return "closed"
        if self._clock() - self._opened_at >= self.reset_timeout:
            return "half-open"
        return "open"

    def allows(self) -> bool:
        st = self.state
        if st == "closed":
            return True
        if st == "half-open":
            now = self._clock()
            # One probe per half-open window — but an ABANDONED probe
            # (caller died between allows() and record_*) must not
            # wedge the breaker half-open forever; after a further
            # reset_timeout the probe slot reopens.
            if (
                self._probe_started is None
                or now - self._probe_started >= self.reset_timeout
            ):
                self._probe_started = now
                return True
        return False

    def record_success(self) -> None:
        self._failures = 0
        self._opened_at = None
        self._probe_started = None

    def record_failure(self) -> None:
        self._failures += 1
        self._probe_started = None
        if self._opened_at is not None or self._failures >= self.failure_threshold:
            self._opened_at = self._clock()

    def call(self, fn: Callable[[], T]) -> T:
        """Guarded invocation: raises CircuitOpenError without calling
        fn when the breaker rejects; records the outcome otherwise."""
        if not self.allows():
            raise CircuitOpenError(
                f"circuit open ({self._failures} consecutive failures)"
            )
        try:
            out = fn()
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out
