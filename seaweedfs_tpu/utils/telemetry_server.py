"""Telemetry collector server — the receiving end of the phone-home.

Reference: telemetry/server (the reference ships a collector storing
reports in Prometheus + a dashboard; `weed master -telemetry.url=...`
points at it). This one accepts the TelemetryCollector's POSTs,
keeps the latest report per cluster (bounded), persists them as JSONL
when a path is given, and exposes:

  POST /api/collect   report ingestion
  GET  /api/stats     summary JSON (clusters, aggregate counts)
  GET  /metrics       Prometheus text (per-cluster gauges + totals)
  GET  /healthz
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .glog import logger

log = logger("telemetry-server")

_NUMERIC_FIELDS = (
    "volume_count",
    "ec_volume_count",
    "server_count",
    "used_size",
    "file_count",
)


class TelemetryServer:
    MAX_CLUSTERS = 10_000  # bound memory against cluster-id churn

    def __init__(
        self,
        ip: str = "localhost",
        port: int = 9999,
        persist_path: str | None = None,
    ):
        self.ip = ip
        self._reports: dict[str, dict] = {}  # cluster_id -> latest
        self._lock = threading.Lock()
        self.persist_path = persist_path
        self._load()
        self._http = ThreadingHTTPServer((ip, port), self._handler_class())
        self.port = self._http.server_address[1]
        self._thread = threading.Thread(
            target=self._http.serve_forever, daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()

    # ------------------------------------------------------------ storage

    def _load(self) -> None:
        if not self.persist_path or not os.path.exists(self.persist_path):
            return
        with open(self.persist_path, encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                    cid = rec.get("cluster_id")
                    if cid:
                        self._reports[cid] = rec
                except json.JSONDecodeError:
                    continue
        # the MAX_CLUSTERS bound applies on REPLAY too (append-only
        # file, months of cluster-id churn): keep the newest
        if len(self._reports) > self.MAX_CLUSTERS:
            keep = sorted(
                self._reports,
                key=lambda k: self._reports[k].get("received_at", 0),
                reverse=True,
            )[: self.MAX_CLUSTERS]
            self._reports = {k: self._reports[k] for k in keep}
        # compact: rewrite with only the retained latest-per-cluster
        # records so the JSONL stops growing without limit
        tmp = self.persist_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in self._reports.values():
                f.write(json.dumps(rec) + "\n")
        os.replace(tmp, self.persist_path)

    def ingest(self, report: dict) -> None:
        cid = str(report.get("cluster_id") or "unknown")
        report = dict(report)
        report["received_at"] = int(time.time())
        with self._lock:
            if (
                cid not in self._reports
                and len(self._reports) >= self.MAX_CLUSTERS
            ):
                # drop the stalest cluster, never the newest report
                oldest = min(
                    self._reports, key=lambda k: self._reports[k]["received_at"]
                )
                del self._reports[oldest]
            self._reports[cid] = report
        if self.persist_path:
            with open(self.persist_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(report) + "\n")

    # ------------------------------------------------------------- views

    def summary(self) -> dict:
        with self._lock:
            reports = list(self._reports.values())
        out = {
            "clusters": len(reports),
            "versions": {},
            "os": {},
        }
        for fld in _NUMERIC_FIELDS:
            out[f"total_{fld}"] = 0
        for r in reports:
            out["versions"][r.get("version", "?")] = (
                out["versions"].get(r.get("version", "?"), 0) + 1
            )
            out["os"][r.get("os", "?")] = out["os"].get(r.get("os", "?"), 0) + 1
            for fld in _NUMERIC_FIELDS:
                try:
                    out[f"total_{fld}"] += int(r.get(fld, 0) or 0)
                except (TypeError, ValueError):
                    pass
        return out

    def prometheus(self) -> str:
        s = self.summary()
        lines = [
            "# TYPE seaweed_telemetry_clusters gauge",
            f"seaweed_telemetry_clusters {s['clusters']}",
        ]
        for fld in _NUMERIC_FIELDS:
            lines.append(f"# TYPE seaweed_telemetry_total_{fld} gauge")
            lines.append(
                f"seaweed_telemetry_total_{fld} {s[f'total_{fld}']}"
            )
        def esc(label: str) -> str:
            """Prometheus label escaping: a client-supplied cluster_id
            with quotes/newlines must not corrupt the exposition."""
            return (
                label.replace("\\", "\\\\")
                .replace('"', '\\"')
                .replace("\n", "\\n")
            )

        with self._lock:
            for cid, r in self._reports.items():
                for fld in _NUMERIC_FIELDS:
                    try:
                        v = int(r.get(fld, 0) or 0)
                    except (TypeError, ValueError):
                        continue
                    lines.append(
                        f'seaweed_telemetry_{fld}{{cluster="{esc(cid)}"}} {v}'
                    )
        return "\n".join(lines) + "\n"

    # ------------------------------------------------------------ handler

    def _handler_class(self):
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path.split("?")[0].rstrip("/") != "/api/collect":
                    return self._send(404, b"{}", "application/json")
                try:
                    n = int(self.headers.get("Content-Length", "0") or 0)
                    report = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(report, dict):
                        raise ValueError("report must be an object")
                except (ValueError, json.JSONDecodeError):
                    return self._send(
                        400, b'{"error": "bad report"}', "application/json"
                    )
                srv.ingest(report)
                self._send(200, b'{"ok": true}', "application/json")

            def do_GET(self):
                path = self.path.split("?")[0].rstrip("/") or "/"
                if path == "/healthz":
                    return self._send(200, b'{"ok": true}', "application/json")
                if path == "/api/stats":
                    return self._send(
                        200,
                        json.dumps(srv.summary()).encode(),
                        "application/json",
                    )
                if path == "/metrics":
                    return self._send(
                        200,
                        srv.prometheus().encode(),
                        "text/plain; version=0.0.4",
                    )
                self._send(404, b"not found", "text/plain")

        return Handler
