"""Bulk-read fast path (the RDMA-sidecar analog, SURVEY §2.10).

Control plane: the volume server's `GET /<fid>?locate=true` returns
{path, offset, size, socket} for a needle's payload. Data plane: this
module's client sends (path, offset, size) over the C++ server's Unix
socket (native/fastread.cpp) and the kernel sendfile()s the bytes —
no HTTP framing, no Python server-side byte handling.

Server side: start_server() runs the blocking C accept loop in a
daemon thread (ctypes releases the GIL for the duration).
"""

from __future__ import annotations

import ctypes
import os
import socket
import struct
import subprocess
import threading

_SO_NAME = "libseaweed_fastread.so"
_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")


class FastReadError(Exception):
    pass


def _stale(so: str) -> bool:
    """Rebuild when the sidecar's sources are newer than the .so. The
    sidecar shares native/sn_net.h with the core library (the sendfile
    loop and its fallback live there), so a header edit must rebuild
    this .so too — derive the source set from the directory like
    utils/native._stale, not from a hardcoded list."""
    import glob as _glob

    if not os.path.exists(so):
        return True
    so_mtime = os.path.getmtime(so)
    sources = [os.path.join(os.path.abspath(_NATIVE_DIR), "Makefile")]
    for pat in ("*.cpp", "*.cc", "*.h", "*.hpp"):
        sources.extend(_glob.glob(os.path.join(os.path.abspath(_NATIVE_DIR), pat)))
    return any(
        os.path.exists(p) and os.path.getmtime(p) > so_mtime for p in sources
    )


def _load_lib():
    # Same load contract as utils/native.py: a missing toolchain or a
    # bad .so surfaces as ImportError so callers' documented
    # `except ImportError` fallback (HTTP-only data plane) engages,
    # instead of a CalledProcessError escaping at first use.
    so = os.path.abspath(os.path.join(_NATIVE_DIR, _SO_NAME))
    try:
        if _stale(so):
            subprocess.run(
                ["make", "-C", os.path.abspath(_NATIVE_DIR), _SO_NAME],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(so)
    except (OSError, subprocess.CalledProcessError) as e:
        raise ImportError(
            f"fastread native core unavailable (build or load of {so} "
            f"failed): {e}"
        ) from e
    lib.sn_fastread_serve.restype = ctypes.c_int
    lib.sn_fastread_serve.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    return lib


_lib = None
_lib_err: ImportError | None = None
_lib_lock = threading.Lock()


def lib():
    """Load (building if stale) the sidecar library ONCE. A failed
    build/load is cached and re-raised: every later call degrades to
    the caller's documented Python/HTTP read path immediately instead
    of re-running `make` (and logging) per call — one warning total."""
    global _lib, _lib_err
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _lib_err is not None:
            raise _lib_err
        try:
            _lib = _load_lib()
        except ImportError as e:
            _lib_err = e
            from .glog import logger

            logger("fastread").warning(
                "native sidecar unavailable, HTTP read path only "
                "(cached for this process): %s", e,
            )
            raise
        return _lib


def start_server(socket_path: str, root_dir: str) -> threading.Thread:
    """Serve `root_dir` on `socket_path` until stop_server()."""
    l = lib()

    def run() -> None:
        rc = l.sn_fastread_serve(
            socket_path.encode(), root_dir.encode()
        )
        if rc not in (0,):
            from .glog import logger

            logger("fastread").warning(
                "server on %s exited rc=%d", socket_path, rc
            )

    t = threading.Thread(target=run, daemon=True, name="fastread")
    t.start()
    # wait for the socket to appear so callers can advertise it
    for _ in range(100):
        if os.path.exists(socket_path):
            break
        import time

        time.sleep(0.01)
    return t


def stop_server(socket_path: str) -> None:
    """Unlink the socket, then poke the accept loop so it notices."""
    try:
        os.unlink(socket_path)
    except OSError:
        return
    try:
        s = socket.socket(socket.AF_UNIX)
        s.settimeout(0.5)
        s.connect(socket_path)
        s.close()
    except OSError:
        pass


# Width-keyed pool of 4096-aligned landing buffers shared by every
# FastReadClient in the process — the same pool the peer-fetch ingress
# lands in (ec/native_io.landing_pool), so steady-state bulk reads
# allocate once and reuse forever instead of a bytearray per call.
def _landing_pool():
    from ..ec.native_io import landing_pool

    return landing_pool()


class FastReadClient:
    """Persistent connection to a fast-read socket."""

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self._sock = socket.socket(socket.AF_UNIX)
        self._sock.settimeout(30.0)
        self._sock.connect(socket_path)
        self._lock = threading.Lock()

    def read(self, path: str, offset: int, size: int) -> bytes:
        body, _ = self._request(path, offset, size)
        return body

    def read_into(self, path: str, offset: int, size: int, dst, *,
                  granule: int = 0):
        """Land the payload DIRECTLY in caller-owned `dst` (1-D uint8
        ndarray, e.g. a pooled aligned buffer) via the native
        recv-into path — no intermediate bytes object. With granule>0,
        returns the fused granule CRCs rolled during the copy-in
        (ndarray u32; granule == size gives the whole-payload CRC the
        ?locate contract demands, for free). Raises FastReadError on
        any server-side error or torn stream."""
        pb = path.encode()
        req = struct.pack("<H", len(pb)) + pb + struct.pack(
            "<QQ", offset, size
        )
        with self._lock:
            self._sock.sendall(req)
            head = self._recv_exact_py(9)
            status = head[0]
            (n,) = struct.unpack("<Q", head[1:])
            if status != 0:
                raise FastReadError(
                    self._recv_exact_py(n).decode(errors="replace")
                )
            if n != size:
                # n payload bytes are in flight on this persistent
                # connection; close rather than desync the framing for
                # the next request
                self.close()
                raise FastReadError(f"short response: {n}/{size} bytes")
            try:
                from . import native
            except ImportError:
                # python landing: recv_into the caller buffer directly
                view = memoryview(dst)[:size]
                got = 0
                while got < size:
                    r = self._sock.recv_into(view[got:], size - got)
                    if r == 0:
                        raise FastReadError(
                            "fastread server closed connection"
                        )
                    got += r
                if granule:
                    from .crc import crc32c as _crc

                    import numpy as _np

                    return _np.array(
                        [
                            _crc(dst[i : min(i + granule, size)])
                            for i in range(0, size, granule)
                        ],
                        dtype=_np.uint32,
                    )
                return None
            import numpy as _np

            crc_state = _np.zeros(1, _np.uint32)
            filled = _np.zeros(1, _np.uint64)
            max_out = (size // granule + 2) if granule else 1
            out_crcs = _np.zeros(max_out, _np.uint32)
            out_counts = _np.zeros(1, _np.int32)
            got = native.recv_into(
                self._sock.fileno(), dst, size,
                timeout_ms=int((self._sock.gettimeout() or 30.0) * 1000),
                granule=granule, crc_state=crc_state, filled_state=filled,
                out_crcs=out_crcs, out_counts=out_counts,
            )
            if got != size:
                self.close()  # mid-payload: the framing is gone
                raise FastReadError(
                    f"fastread server closed connection ({got}/{size})"
                )
            if not granule:
                return None
            crcs = list(out_crcs[: int(out_counts[0])])
            if size % granule:
                crcs.append(int(crc_state[0]))  # partial tail granule
            return _np.array(crcs, dtype=_np.uint32)

    def _request(self, path: str, offset: int, size: int):
        pb = path.encode()
        req = struct.pack("<H", len(pb)) + pb + struct.pack("<QQ", offset, size)
        with self._lock:
            self._sock.sendall(req)
            head = self._recv_exact_py(9)
            status = head[0]
            (n,) = struct.unpack("<Q", head[1:])
            body = self._recv_exact_py(n)
        if status != 0:
            raise FastReadError(body.decode(errors="replace"))
        return body, n

    def _recv_exact_py(self, n: int) -> bytes:
        # recv_into a preallocated buffer: bytes-concatenation would be
        # quadratic on multi-MB bodies and defeat the fast path
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            r = self._sock.recv_into(view[got:], n - got)
            if r == 0:
                raise FastReadError("fastread server closed connection")
            got += r
        return bytes(buf)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def read_fid_fast(locate: dict) -> bytes:
    """One-shot convenience: `locate` is the volume server's
    ?locate=true JSON ({path, offset, size, crc32c, socket}). The CRC
    is MANDATORY validation: the sidecar serves raw unlocked ranges, so
    a vacuum racing the read — or a stale locate replayed against the
    wrong host's sidecar — must fail loudly, never return wrong bytes.
    The payload lands in a pooled aligned buffer with the CRC rolled
    DURING the copy-in (granule = whole payload), so the mandatory
    verify costs no second byte pass."""
    size = int(locate["size"])
    c = FastReadClient(locate["socket"])
    buf = None
    try:
        if size > 0:
            pool = _landing_pool()
            buf = pool.get(size)
            try:
                crcs = c.read_into(
                    locate["path"], locate["offset"], size, buf[0],
                    granule=size,
                )
                if crcs is None:
                    from .crc import crc32c as _crc

                    got_crc = _crc(buf[0])
                else:
                    got_crc = int(crcs[0])
                if got_crc != locate.get("crc32c", -1):
                    raise FastReadError(
                        "payload checksum mismatch (stale locate?)"
                    )
                return buf[0].tobytes()
            finally:
                pool.put(buf)
        data = c.read(locate["path"], locate["offset"], size)
        return data
    finally:
        c.close()
