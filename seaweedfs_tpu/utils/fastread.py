"""Bulk-read fast path (the RDMA-sidecar analog, SURVEY §2.10).

Control plane: the volume server's `GET /<fid>?locate=true` returns
{path, offset, size, socket} for a needle's payload. Data plane: this
module's client sends (path, offset, size) over the C++ server's Unix
socket (native/fastread.cpp) and the kernel sendfile()s the bytes —
no HTTP framing, no Python server-side byte handling.

Server side: start_server() runs the blocking C accept loop in a
daemon thread (ctypes releases the GIL for the duration).
"""

from __future__ import annotations

import ctypes
import os
import socket
import struct
import subprocess
import threading

_SO_NAME = "libseaweed_fastread.so"
_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")


class FastReadError(Exception):
    pass


def _load_lib():
    # Same load contract as utils/native.py: a missing toolchain or a
    # bad .so surfaces as ImportError so callers' documented
    # `except ImportError` fallback (HTTP-only data plane) engages,
    # instead of a CalledProcessError escaping at first use.
    so = os.path.abspath(os.path.join(_NATIVE_DIR, _SO_NAME))
    try:
        if not os.path.exists(so):
            subprocess.run(
                ["make", "-C", os.path.abspath(_NATIVE_DIR), _SO_NAME],
                check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(so)
    except (OSError, subprocess.CalledProcessError) as e:
        raise ImportError(
            f"fastread native core unavailable (build or load of {so} "
            f"failed): {e}"
        ) from e
    lib.sn_fastread_serve.restype = ctypes.c_int
    lib.sn_fastread_serve.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    return lib


_lib = None
_lib_lock = threading.Lock()


def lib():
    global _lib
    with _lib_lock:
        if _lib is None:
            _lib = _load_lib()
        return _lib


def start_server(socket_path: str, root_dir: str) -> threading.Thread:
    """Serve `root_dir` on `socket_path` until stop_server()."""
    l = lib()

    def run() -> None:
        rc = l.sn_fastread_serve(
            socket_path.encode(), root_dir.encode()
        )
        if rc not in (0,):
            from .glog import logger

            logger("fastread").warning(
                "server on %s exited rc=%d", socket_path, rc
            )

    t = threading.Thread(target=run, daemon=True, name="fastread")
    t.start()
    # wait for the socket to appear so callers can advertise it
    for _ in range(100):
        if os.path.exists(socket_path):
            break
        import time

        time.sleep(0.01)
    return t


def stop_server(socket_path: str) -> None:
    """Unlink the socket, then poke the accept loop so it notices."""
    try:
        os.unlink(socket_path)
    except OSError:
        return
    try:
        s = socket.socket(socket.AF_UNIX)
        s.settimeout(0.5)
        s.connect(socket_path)
        s.close()
    except OSError:
        pass


class FastReadClient:
    """Persistent connection to a fast-read socket."""

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self._sock = socket.socket(socket.AF_UNIX)
        self._sock.settimeout(30.0)
        self._sock.connect(socket_path)
        self._lock = threading.Lock()

    def read(self, path: str, offset: int, size: int) -> bytes:
        pb = path.encode()
        req = struct.pack("<H", len(pb)) + pb + struct.pack("<QQ", offset, size)
        with self._lock:
            self._sock.sendall(req)
            head = self._read_exact(9)
            status = head[0]
            (n,) = struct.unpack("<Q", head[1:])
            body = self._read_exact(n)
        if status != 0:
            raise FastReadError(body.decode(errors="replace"))
        return body

    def _read_exact(self, n: int) -> bytes:
        # recv_into a preallocated buffer: bytes-concatenation would be
        # quadratic on multi-MB bodies and defeat the fast path
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            r = self._sock.recv_into(view[got:], n - got)
            if r == 0:
                raise FastReadError("fastread server closed connection")
            got += r
        return bytes(buf)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def read_fid_fast(locate: dict) -> bytes:
    """One-shot convenience: `locate` is the volume server's
    ?locate=true JSON ({path, offset, size, crc32c, socket}). The CRC
    is MANDATORY validation: the sidecar serves raw unlocked ranges, so
    a vacuum racing the read — or a stale locate replayed against the
    wrong host's sidecar — must fail loudly, never return wrong
    bytes."""
    c = FastReadClient(locate["socket"])
    try:
        data = c.read(locate["path"], locate["offset"], locate["size"])
    finally:
        c.close()
    if locate["size"] > 0:
        from .crc import crc32c

        if crc32c(data) != locate.get("crc32c", -1):
            raise FastReadError("payload checksum mismatch (stale locate?)")
    return data
