"""TOML configuration files beside the CLI flags.

Reference: weed/util/config.go:35-41 — viper loads `<name>.toml` from
the working directory, `~/.seaweedfs/`, and `/etc/seaweedfs/` (first
hit wins); `weed scaffold` emits commented templates
(weed/command/scaffold/*.toml). Here the same search order is applied,
and `python -m seaweedfs_tpu.server scaffold` emits the templates in
utils/scaffold.py.

Flags still win: launchers consult the config only for keys whose flag
was left at its default, mirroring the reference's precedence.

The TOML parser is stdlib ``tomllib`` WHEN PRESENT (Python >= 3.11) and
a minimal fallback otherwise: on a 3.10 interpreter an unconditional
``import tomllib`` crashed every spawned ``python -m
seaweedfs_tpu.server`` at import time — taking the whole server down
over an OPTIONAL config feature. The fallback covers the dialect the
scaffold templates use (tables, dotted tables, strings, ints, floats,
booleans, flat arrays, comments); anything fancier should ride a
3.11+ interpreter or stay in flags.
"""

from __future__ import annotations

import os
import re
from typing import Any

try:  # Python >= 3.11
    import tomllib as _tomllib
except ModuleNotFoundError:  # gated: 3.10 containers must still boot
    try:
        import tomli as _tomllib  # type: ignore[no-redef]
    except ModuleNotFoundError:
        _tomllib = None

CONFIG_DIRS = (".", "~/.seaweedfs_tpu", "/etc/seaweedfs_tpu")


class TomlDecodeError(ValueError):
    """Raised by the fallback parser; aliases tomllib.TOMLDecodeError
    when the stdlib parser is present so callers catch one type."""


if _tomllib is not None:
    TomlDecodeError = _tomllib.TOMLDecodeError  # type: ignore[misc]


_KEY_RE = re.compile(r"^[A-Za-z0-9_\-]+$")


def _parse_scalar(raw: str, lineno: int) -> Any:
    raw = raw.strip()
    if not raw:
        raise TomlDecodeError(f"line {lineno}: empty value")
    if raw.startswith('"') or raw.startswith("'"):
        quote = raw[0]
        if len(raw) < 2 or not raw.endswith(quote):
            raise TomlDecodeError(f"line {lineno}: unterminated string")
        body = raw[1:-1]
        if quote == '"':
            body = (
                body.replace("\\\\", "\x00")
                .replace('\\"', '"')
                .replace("\\n", "\n")
                .replace("\\t", "\t")
                .replace("\x00", "\\")
            )
        return body
    if raw == "true":
        return True
    if raw == "false":
        return False
    if raw.startswith("[") and raw.endswith("]"):
        inner = raw[1:-1].strip()
        if not inner:
            return []
        return [
            _parse_scalar(part.strip(), lineno)
            for part in _split_array(inner, lineno)
        ]
    try:
        return int(raw, 0)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    raise TomlDecodeError(f"line {lineno}: cannot parse value {raw!r}")


def _split_array(inner: str, lineno: int) -> list[str]:
    """Split a flat array body on commas OUTSIDE quotes."""
    parts, buf, quote = [], [], ""
    for ch in inner:
        if quote:
            buf.append(ch)
            if ch == quote:
                quote = ""
        elif ch in "\"'":
            quote = ch
            buf.append(ch)
        elif ch == ",":
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    if quote:
        raise TomlDecodeError(f"line {lineno}: unterminated string in array")
    if "".join(buf).strip():
        parts.append("".join(buf))
    return parts


def _strip_comment(line: str) -> str:
    """Drop a trailing # comment (outside quotes)."""
    quote = ""
    for i, ch in enumerate(line):
        if quote:
            if ch == quote:
                quote = ""
        elif ch in "\"'":
            quote = ch
        elif ch == "#":
            return line[:i]
    return line


def _fallback_loads(text: str) -> dict:
    root: dict = {}
    table = root
    for lineno, raw_line in enumerate(text.splitlines(), 1):
        line = _strip_comment(raw_line).strip()
        if not line:
            continue
        if line.startswith("["):
            if not line.endswith("]") or line.startswith("[["):
                raise TomlDecodeError(
                    f"line {lineno}: unsupported table header {line!r}"
                )
            table = root
            for part in line[1:-1].split("."):
                part = part.strip()
                if not _KEY_RE.match(part):
                    raise TomlDecodeError(
                        f"line {lineno}: bad table name {part!r}"
                    )
                nxt = table.setdefault(part, {})
                if not isinstance(nxt, dict):
                    raise TomlDecodeError(
                        f"line {lineno}: {part!r} is not a table"
                    )
                table = nxt
            continue
        key, sep, val = line.partition("=")
        key = key.strip()
        if not sep or not _KEY_RE.match(key):
            raise TomlDecodeError(f"line {lineno}: cannot parse {line!r}")
        table[key] = _parse_scalar(val, lineno)
    return root


def toml_loads(text: str) -> dict:
    """Parse TOML text: stdlib tomllib when available, else the
    fallback dialect. Raises :data:`TomlDecodeError` on bad input."""
    if _tomllib is not None:
        return _tomllib.loads(text)
    return _fallback_loads(text)


def toml_load(fp) -> dict:
    """Parse a binary file object (tomllib.load signature)."""
    return toml_loads(fp.read().decode("utf-8"))


class Config:
    """A parsed TOML file with viper-style dotted-key access."""

    def __init__(self, data: dict | None, path: str | None = None):
        self.data = data or {}
        self.path = path

    def __bool__(self) -> bool:
        return bool(self.data)

    def get(self, dotted: str, default: Any = None) -> Any:
        node: Any = self.data
        for part in dotted.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node

    def get_str(self, dotted: str, default: str = "") -> str:
        v = self.get(dotted, default)
        return default if v is None else str(v)


def find_config_file(name: str, dirs=CONFIG_DIRS) -> str | None:
    for d in dirs:
        path = os.path.join(os.path.expanduser(d), f"{name}.toml")
        if os.path.isfile(path):
            return path
    return None


def load_config(name: str, dirs=CONFIG_DIRS) -> Config:
    """Load `<name>.toml` from the search path; empty Config if absent
    or malformed (a bad config file must not take a node down — it is
    reported and ignored, like viper's soft failure)."""
    path = find_config_file(name, dirs)
    if path is None:
        return Config(None)
    try:
        with open(path, "rb") as f:
            return Config(toml_load(f), path)
    except (OSError, TomlDecodeError, ValueError, UnicodeDecodeError) as e:
        from .glog import logger

        logger("config").warning("ignoring %s: %s", path, e)
        return Config(None)
