"""TOML configuration files beside the CLI flags.

Reference: weed/util/config.go:35-41 — viper loads `<name>.toml` from
the working directory, `~/.seaweedfs/`, and `/etc/seaweedfs/` (first
hit wins); `weed scaffold` emits commented templates
(weed/command/scaffold/*.toml). Here the same search order is applied
with stdlib tomllib, and `python -m seaweedfs_tpu.server scaffold`
emits the templates in utils/scaffold.py.

Flags still win: launchers consult the config only for keys whose flag
was left at its default, mirroring the reference's precedence.
"""

from __future__ import annotations

import os
import tomllib
from typing import Any

CONFIG_DIRS = (".", "~/.seaweedfs_tpu", "/etc/seaweedfs_tpu")


class Config:
    """A parsed TOML file with viper-style dotted-key access."""

    def __init__(self, data: dict | None, path: str | None = None):
        self.data = data or {}
        self.path = path

    def __bool__(self) -> bool:
        return bool(self.data)

    def get(self, dotted: str, default: Any = None) -> Any:
        node: Any = self.data
        for part in dotted.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node

    def get_str(self, dotted: str, default: str = "") -> str:
        v = self.get(dotted, default)
        return default if v is None else str(v)


def find_config_file(name: str, dirs=CONFIG_DIRS) -> str | None:
    for d in dirs:
        path = os.path.join(os.path.expanduser(d), f"{name}.toml")
        if os.path.isfile(path):
            return path
    return None


def load_config(name: str, dirs=CONFIG_DIRS) -> Config:
    """Load `<name>.toml` from the search path; empty Config if absent
    or malformed (a bad config file must not take a node down — it is
    reported and ignored, like viper's soft failure)."""
    path = find_config_file(name, dirs)
    if path is None:
        return Config(None)
    try:
        with open(path, "rb") as f:
            return Config(tomllib.load(f), path)
    except (OSError, tomllib.TOMLDecodeError) as e:
        from .glog import logger

        logger("config").warning("ignoring %s: %s", path, e)
        return Config(None)
