"""Cluster-internal URL scheme selection.

When a node group runs TLS (utils/tls.py), every internal hop —
client → master, filer → volume, replica fan-out, shell → servers —
must speak https and verify against the cluster CA. The reference
threads this through security.toml-loaded gRPC/HTTP dialers; here one
process-wide switch covers the hand-rolled HTTP data plane: callers
build URLs via service_url() instead of hardcoding a scheme, and
enable_https() points `requests` at the CA via REQUESTS_CA_BUNDLE
(honored by every requests call in the process).
"""

from __future__ import annotations

import os

_scheme = "http"


def enable_https(ca_file: str | None = None) -> None:
    """Switch internal hops to https, trusting the cluster CA IN
    ADDITION to the public roots — overwriting the trust store with
    just the cluster CA would break every external https call (cloud
    tier backends, webhooks)."""
    global _scheme
    _scheme = "https"
    if not ca_file:
        return
    bundle = ca_file
    try:
        import tempfile

        import certifi

        with open(certifi.where(), "rb") as f:
            roots = f.read()
        with open(ca_file, "rb") as f:
            cluster = f.read()
        tmp = tempfile.NamedTemporaryFile(
            mode="wb", suffix=".pem", prefix="sw-ca-", delete=False
        )
        tmp.write(roots + b"\n" + cluster)
        tmp.close()
        bundle = tmp.name
    except Exception:
        pass  # fall back to the cluster CA alone
    os.environ["REQUESTS_CA_BUNDLE"] = bundle


def scheme() -> str:
    return _scheme


def service_url(hostport: str, path: str = "") -> str:
    """'host:port' (+ optional '/path') → full URL on the cluster
    scheme. Pass-through when the caller already has a scheme."""
    if hostport.startswith(("http://", "https://")):
        return hostport + path
    return f"{_scheme}://{hostport}{path}"
