"""X-Request-ID propagation.

Reference: weed/util/request_id — every HTTP hop carries the id; the
first server in the chain mints one. Stored in a contextvar so log
lines and downstream client calls inside one request see it without
threading it through signatures.
"""

from __future__ import annotations

import contextvars
import uuid

HEADER = "X-Request-ID"

_current: contextvars.ContextVar[str] = contextvars.ContextVar(
    "request_id", default=""
)


def get() -> str:
    return _current.get()


def ensure(incoming: str | None = None) -> str:
    """Adopt the caller's id or mint one; returns the active id."""
    rid = incoming or uuid.uuid4().hex[:16]
    _current.set(rid)
    return rid


def clear() -> None:
    _current.set("")


def inject(headers: dict) -> dict:
    """Add the active id to outgoing request headers (no-op outside a
    request context)."""
    rid = get()
    if rid:
        headers[HEADER] = rid
    return headers


class RequestTracingMixin:
    """Mix into a BaseHTTPRequestHandler (before it in the MRO): adopts
    or mints the request id when headers are parsed and echoes it on
    every response, so one id follows a request through
    client → filer → volume hops and appears in each server's logs."""

    def parse_request(self):  # type: ignore[override]
        ok = super().parse_request()
        if ok:
            ensure(self.headers.get(HEADER))
        return ok

    def send_response(self, code, message=None):  # type: ignore[override]
        super().send_response(code, message)
        rid = get()
        if rid:
            self.send_header(HEADER, rid)
