"""X-Request-ID propagation + per-request HTTP tracing.

Reference: weed/util/request_id — every HTTP hop carries the id; the
first server in the chain mints one. Stored in a contextvar so log
lines and downstream client calls inside one request see it without
threading it through signatures.

:class:`RequestTracingMixin` is also the HTTP end of the flight
recorder (utils/trace.py): when the tracer is armed, every request gets
a ROOT SPAN that adopts the trace id / parent span carried in the
``X-Sw-Trace-Id`` / ``X-Sw-Parent-Span`` request headers (minting a
fresh trace when absent), activates it as the ambient span for the
handler thread (downstream client calls and EC spans nest under it),
echoes the trace id on the response, and finishes it when the response
completes. Armed or not, every request lands in the
``sw_request_seconds{server,op}`` latency histogram — the per-op-class
SLO surface served at ``/debug/slo``.
"""

from __future__ import annotations

import contextvars
import time
import uuid

HEADER = "X-Request-ID"

_current: contextvars.ContextVar[str] = contextvars.ContextVar(
    "request_id", default=""
)


def get() -> str:
    return _current.get()


def ensure(incoming: str | None = None) -> str:
    """Adopt the caller's id or mint one; returns the active id."""
    rid = incoming or uuid.uuid4().hex[:16]
    _current.set(rid)
    return rid


def clear() -> None:
    _current.set("")


def inject(headers: dict) -> dict:
    """Add the active id to outgoing request headers (no-op outside a
    request context)."""
    rid = get()
    if rid:
        headers[HEADER] = rid
    return headers


class RequestTracingMixin:
    """Mix into a BaseHTTPRequestHandler (before it in the MRO): adopts
    or mints the request id when headers are parsed and echoes it on
    every response, so one id follows a request through
    client → filer → volume hops and appears in each server's logs.

    Per-request tracing rides the same hooks: ``parse_request`` opens
    (or adopts, via the ``X-Sw-*`` headers) a root span and installs it
    as the thread's ambient span; ``handle_one_request`` finishes it
    after the response and records the request into the
    ``sw_request_seconds{server,op}`` SLO histogram. Subclasses set
    ``trace_server_kind`` ("s3", "filer", "volume", "master",
    "webdav") and may refine the op class per request by assigning
    ``self._sw_op`` (defaults to the lowercased HTTP method)."""

    trace_server_kind = "http"

    def parse_request(self):  # type: ignore[override]
        ok = super().parse_request()
        if ok:
            ensure(self.headers.get(HEADER))
            self._sw_t0 = time.perf_counter()
            self._sw_code = 0
            self._sw_op = ""
            self._sw_span = None
            self._sw_token = None
            from . import trace

            if trace.armed:
                sp = trace.start_from_headers(
                    f"http.{self.trace_server_kind}",
                    self.headers,
                    name=f"{self.command} {self.path.split('?', 1)[0]}",
                    server=self.trace_server_kind,
                )
                self._sw_span = sp
                self._sw_token = trace.set_current(sp)
        return ok

    def send_response(self, code, message=None):  # type: ignore[override]
        super().send_response(code, message)
        rid = get()
        if rid:
            self.send_header(HEADER, rid)
        if not getattr(self, "_sw_code", 0):
            self._sw_code = code
        sp = getattr(self, "_sw_span", None)
        if sp is not None:
            from . import trace

            self.send_header(trace.TRACE_ID_HEADER, sp.trace_id)

    def handle_one_request(self):  # type: ignore[override]
        try:
            super().handle_one_request()
        finally:
            self._sw_finish_request()

    def _sw_finish_request(self) -> None:
        t0 = self.__dict__.pop("_sw_t0", None)
        if t0 is None:
            return  # parse failed / idle keep-alive close: no request
        from . import metrics
        from . import trace

        op = getattr(self, "_sw_op", "") or (self.command or "?").lower()
        dur = time.perf_counter() - t0
        metrics.request_seconds.observe(
            dur, server=self.trace_server_kind, op=op
        )
        metrics.request_total.inc(
            server=self.trace_server_kind,
            op=op,
            code=str(getattr(self, "_sw_code", 0) or 0),
        )
        sp = self.__dict__.pop("_sw_span", None)
        token = self.__dict__.pop("_sw_token", None)
        if sp is not None:
            sp.attrs["http_code"] = getattr(self, "_sw_code", 0)
            sp.attrs["op_class"] = op
            trace.finish(sp)
        trace.reset_current(token)

    def serve_slo_endpoint(self, path: str) -> bool:
        """Serve ``/debug/slo`` (this process's per-op-class p50/p99
        from ``sw_request_seconds``); True when the request was
        handled. Open like /metrics — it holds latency stats only.

        Status/control-plane servers only (master, volume, filer): the
        S3 and WebDAV DATA planes deliberately do not call this — a
        bucket literally named ``debug`` must stay addressable, and an
        unauthenticated status response would bypass SigV4. Their op
        classes still appear in ``/metrics`` and in any co-resident
        server's ``/debug/slo`` (the registry is process-wide)."""
        if path == "/debug/gateway":
            return self._serve_debug_json(self._gateway_doc())
        if path != "/debug/slo":
            return False
        from . import metrics

        return self._serve_debug_json(metrics.slo_summary())

    def _gateway_doc(self) -> dict:
        """``/debug/gateway``: the serving-path pressure surface beside
        /debug/slo — this server's HTTP front-end state (worker pool /
        accept budget / rejects) plus the process-wide hot-cache and
        inflight counters (sw_gateway_*)."""
        from . import metrics
        from .http_pool import status_of

        doc = metrics.gateway_summary()
        doc["front_end"] = status_of(self.server)
        return doc

    def _serve_debug_json(self, obj) -> bool:
        import json

        body = json.dumps(obj, sort_keys=True).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        return True
