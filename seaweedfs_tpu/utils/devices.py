"""Watchdogged accelerator probing.

jax backend init happens in C and NEVER times out: with a dead TPU
relay as the default platform, the first `jax.devices()` call blocks the
process forever. Every "is there a TPU?" decision in the framework must
therefore go through this subprocess probe, which bounds the damage to
a timeout and caches the verdict for the process lifetime.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading

_lock = threading.Lock()
_cached: int | None = None


def probe_accelerators(timeout: float | None = None, refresh: bool = False) -> int:
    """Number of non-CPU jax devices reachable right now (0 on hang or
    error). Cached after the first call."""
    global _cached
    with _lock:
        if _cached is not None and not refresh:
            return _cached
        if timeout is None:
            try:
                timeout = float(
                    os.environ.get("SEAWEED_DEVICE_PROBE_TIMEOUT", "30")
                )
            except ValueError:
                timeout = 30.0
        code = (
            "import jax;"
            "print(len([d for d in jax.devices() if d.platform != 'cpu']))"
        )
        count = 0
        try:
            out = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=timeout,
            )
            for line in reversed(out.stdout.splitlines()):
                try:
                    count = int(line.strip())
                    break
                except ValueError:
                    continue
        except (subprocess.TimeoutExpired, OSError):
            count = 0
        _cached = count
        return count


def accelerator_available(timeout: float | None = None) -> bool:
    return probe_accelerators(timeout) > 0
