"""On-the-fly image resize/crop for blob reads.

Reference: weed/images/resizing.go + orientation.go, invoked from the
volume read handler (volume_server_handlers_read.go:362-421) when a
GET carries ?width/?height. Modes follow the reference:

  (none) : fit within width x height, keep aspect ratio
  fit    : same, but also scale up small images
  fill   : cover width x height then center-crop to exactly that size

JPEG EXIF orientation is normalized before resizing, like the
reference's FixJpgOrientation.
"""

from __future__ import annotations

import io

_MAGIC = {
    b"\xff\xd8\xff": "JPEG",
    b"\x89PNG": "PNG",
    b"GIF8": "GIF",
}


def detect_format(data: bytes) -> str | None:
    for magic, fmt in _MAGIC.items():
        if data[: len(magic)] == magic:
            return fmt
    return None


def resized(
    data: bytes, width: int = 0, height: int = 0, mode: str = ""
) -> tuple[bytes, int, int]:
    """Returns (bytes, w, h); input unchanged when it is not an image,
    no dimensions were asked for, or decoding fails (serving the
    original beats a 500 — reference behavior)."""
    fmt = detect_format(data)
    if fmt is None or (width <= 0 and height <= 0):
        return data, 0, 0
    try:
        from PIL import Image, ImageOps

        img = Image.open(io.BytesIO(data))
        img.load()
        if fmt == "JPEG":
            img = ImageOps.exif_transpose(img)
        ow, oh = img.size
        w, h = width or ow, height or oh
        if mode == "fill":
            img = ImageOps.fit(img, (w, h))
        else:
            if mode != "fit" and w >= ow and h >= oh:
                return data, ow, oh  # default mode never upscales
            ratio = min(w / ow, h / oh)
            img = img.resize(
                (max(1, round(ow * ratio)), max(1, round(oh * ratio)))
            )
        out = io.BytesIO()
        save_fmt = fmt if fmt != "GIF" else "PNG"
        if save_fmt == "JPEG" and img.mode not in ("RGB", "L"):
            img = img.convert("RGB")
        img.save(out, save_fmt)
        return out.getvalue(), img.size[0], img.size[1]
    except Exception:
        return data, 0, 0
