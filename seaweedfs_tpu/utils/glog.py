"""Leveled logging (reference weed/glog: V(n) verbosity, leveled
prefixes, one stream). Stdlib-logging-free on purpose: one process-wide
verbosity knob, glog-style line format:

  I0729 12:34:56.789 volume_server] message
"""

from __future__ import annotations

import os
import sys
import threading
import time

_verbosity = int(os.environ.get("SEAWEED_V", "0"))
_lock = threading.Lock()


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = v


def v_enabled(level: int) -> bool:
    return level <= _verbosity


def _emit(sev: str, component: str, msg: str) -> None:
    t = time.time()  # one read: HH:MM:SS and .ms must agree at boundaries
    ts = time.strftime("%m%d %H:%M:%S", time.localtime(t))
    ms = int((t % 1) * 1000)
    with _lock:
        sys.stderr.write(f"{sev}{ts}.{ms:03d} {component}] {msg}\n")
        sys.stderr.flush()


class Logger:
    """Per-component logger: glog.logger('master').info(...)"""

    def __init__(self, component: str):
        self.component = component

    def v(self, level: int, msg: str, *args) -> None:
        if v_enabled(level):
            _emit("I", self.component, msg % args if args else msg)

    def info(self, msg: str, *args) -> None:
        _emit("I", self.component, msg % args if args else msg)

    def warning(self, msg: str, *args) -> None:
        _emit("W", self.component, msg % args if args else msg)

    def error(self, msg: str, *args) -> None:
        _emit("E", self.component, msg % args if args else msg)


def logger(component: str) -> Logger:
    return Logger(component)
