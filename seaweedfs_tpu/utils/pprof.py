"""pprof-style live profiling endpoints.

Reference: weed/util/grace/pprof.go (-cpuprofile flags) and Go's
/debug/pprof handlers. Python equivalents:

  dump_stacks()            — /debug/pprof/goroutine: one stack per
                             live thread (post-mortem for hangs)
  sample_profile(seconds)  — /debug/pprof/profile: statistical sampler
                             over sys._current_frames at ~100 Hz,
                             emitted as collapsed stacks (one
                             `frame;frame;frame count` line each),
                             directly flamegraph.pl-compatible.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter


def dump_stacks() -> str:
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"thread {names.get(tid, '?')} (id {tid}):")
        out.extend(l.rstrip() for l in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out)


def require_loopback(handler, what: str = "debug") -> bool:
    """Shared operator gate for /debug/* surfaces (pprof, traces):
    True when the caller is local; otherwise a 403 has been sent.
    One implementation so a future hardening change cannot leave the
    debug endpoints with inconsistent exposure."""
    peer = handler.client_address[0]
    if peer in ("127.0.0.1", "::1", "localhost"):
        return True
    body = f"{what} endpoints are loopback-only\n".encode()
    handler.send_response(403)
    handler.send_header("Content-Type", "text/plain")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)
    return False


def handle_debug_endpoint(handler, parsed) -> bool:
    """Serve /debug/pprof/* on any BaseHTTPRequestHandler; True when
    the path was one of ours.

    Loopback-only: stack dumps leak internals and the sampler costs
    CPU, so remote callers get 403 (the reference gates profiling
    behind operator-only flags)."""
    from urllib.parse import parse_qs

    if not parsed.path.startswith("/debug/pprof"):
        return False
    if not require_loopback(handler, "pprof"):
        return True
    q = parse_qs(parsed.query)
    if parsed.path.endswith("/profile"):
        try:
            secs = float(q.get("seconds", ["5"])[0])
        except ValueError:
            secs = 5.0
        body = sample_profile(min(secs, 30.0)).encode()
    else:  # /debug/pprof and /debug/pprof/goroutine
        body = dump_stacks().encode()
    handler.send_response(200)
    handler.send_header("Content-Type", "text/plain; charset=utf-8")
    handler.send_header("Content-Length", str(len(body)))
    handler.end_headers()
    handler.wfile.write(body)
    return True


def sample_profile(seconds: float = 5.0, hz: int = 100) -> str:
    """Sample all thread stacks for `seconds`; collapsed-stack text."""
    me = threading.get_ident()
    period = 1.0 / hz
    counts: Counter[str] = Counter()
    deadline = time.monotonic() + max(0.1, min(seconds, 120.0))
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            parts = []
            f = frame
            while f is not None:
                code = f.f_code
                parts.append(f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})")
                f = f.f_back
            counts[";".join(reversed(parts))] += 1
        time.sleep(period)
    return "\n".join(f"{stack} {n}" for stack, n in counts.most_common())
