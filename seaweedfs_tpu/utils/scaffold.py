"""`scaffold` templates — commented TOML the operator edits in place.

Reference: weed/command/scaffold/*.toml (security, master, volume,
filer, notification, replication) emitted by `weed scaffold -config=X`.
"""

from __future__ import annotations

TEMPLATES: dict[str, str] = {
    "security": """\
# security.toml — searched in ./, ~/.seaweedfs_tpu/, /etc/seaweedfs_tpu/
# Flags override these values.

[jwt.signing]
# shared secret for write-authorization JWTs minted by the master at
# Assign time and checked by volume servers before accepting writes
key = ""
expires_after_seconds = 10

[https.default]
# cert/key turn every HTTP listener on this node into TLS (hot-reload
# on file change); ca additionally enforces mutual TLS
cert = ""
key = ""
ca = ""

[access]
# ip whitelist for admin endpoints ("" = allow all)
ui = ""
""",
    "master": """\
# master.toml
[master.volume_growth]
# how many volumes to grow per replication class when none is writable
copy_1 = 7
copy_2 = 6
copy_3 = 3
copy_other = 1

[master.maintenance]
# auto-EC scanner: volumes at this fraction of the size limit (and
# write-quiet for quiet_seconds) get ec_encode tasks
ec_auto_fullness = 0.0
ec_quiet_seconds = 60

[master.vacuum]
garbage_threshold = 0.3
interval_seconds = 60
""",
    "volume": """\
# volume.toml
[volume]
# durable needle map: "sqlite" reopens in O(delta); "memory" is O(live)
index = "memory"
# erasure-coding backend: auto | cpu | xla | pallas | native
ec_backend = "auto"

[volume.store]
max_volumes = 8
""",
    "filer": """\
# filer.toml — store backend selection
[sqlite]
enabled = true
dbFile = "./filerdb/filer.db"

[memory]
# volatile, for tests only
enabled = false
""",
    "s3": """\
# s3.toml
[s3]
region = "us-east-1"
# identities/roles JSON (same schema as -s3Config)
config = ""
""",
    "notification": """\
# notification.toml — filer event sinks
[notification.webhook]
enabled = false
endpoint = "http://localhost:8999/hook"

[notification.mq]
enabled = false
broker = "localhost:17777"
topic = "filer-events"
""",
    "replication": """\
# replication.toml — cross-cluster sync (filer.sync daemon),
# consumed by `python -m seaweedfs_tpu.replication`
[source.filer]
address = "localhost:8888"

[sink.filer]
address = "localhost:28888"
directory = "/"
""",
}


def scaffold(name: str) -> str:
    if name not in TEMPLATES:
        raise KeyError(
            f"unknown config {name!r}; one of {', '.join(sorted(TEMPLATES))}"
        )
    return TEMPLATES[name]
