"""Write/read authorization JWTs.

Reference: weed/security/jwt.go — the master signs a short-lived token
scoped to one fid at Assign time; volume servers verify it before
accepting writes (maybeCheckJwtAuthorization,
volume_server_handlers_write.go:37). HMAC-SHA256 compact JWS, stdlib
only.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time


class JwtError(Exception):
    pass


def _b64(b: bytes) -> bytes:
    return base64.urlsafe_b64encode(b).rstrip(b"=")


def _unb64(s: bytes) -> bytes:
    return base64.urlsafe_b64decode(s + b"=" * (-len(s) % 4))


def sign_jwt(key: str, fid: str, ttl_seconds: int = 10) -> str:
    """Token authorizing one operation on one fid."""
    header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64(
        json.dumps(
            {"fid": fid, "exp": int(time.time()) + ttl_seconds}
        ).encode()
    )
    msg = header + b"." + payload
    sig = _b64(hmac.new(key.encode(), msg, hashlib.sha256).digest())
    return (msg + b"." + sig).decode()


def verify_jwt(key: str, token: str, fid: str) -> None:
    """Raises JwtError unless the token is valid, unexpired, and scoped
    to this fid."""
    try:
        header_b, payload_b, sig_b = token.encode().split(b".")
    except ValueError:
        raise JwtError("malformed token") from None
    msg = header_b + b"." + payload_b
    want = _b64(hmac.new(key.encode(), msg, hashlib.sha256).digest())
    if not hmac.compare_digest(want, sig_b):
        raise JwtError("bad signature")
    try:
        payload = json.loads(_unb64(payload_b))
    except (ValueError, json.JSONDecodeError):
        raise JwtError("malformed payload") from None
    if payload.get("exp", 0) < time.time():
        raise JwtError("token expired")
    claimed = payload.get("fid", "")
    # tokens scoped to a fid also cover its volume ("vid,fid" or "vid")
    if claimed not in (fid, fid.split(",")[0]):
        raise JwtError("token not valid for this fid")
