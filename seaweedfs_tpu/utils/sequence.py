"""File-id sequencers.

Reference: weed/sequence — snowflake or raft-replicated max. A plain
counter resets on master restart, and a reused needle id OVERWRITES the
existing blob in its volume; snowflake ids (timestamp | node | seq) stay
unique across restarts with no persisted state.
"""

from __future__ import annotations

import threading
import time

_EPOCH_MS = 1_600_000_000_000  # 2020-09-13; keeps ids in 63 bits for decades
_NODE_BITS = 10
_SEQ_BITS = 12


class SnowflakeSequencer:
    """64-bit ids: [timestamp_ms(41) | node(10) | seq(12)], monotonic."""

    def __init__(self, node_id: int = 0):
        self.node_id = node_id & ((1 << _NODE_BITS) - 1)
        self._lock = threading.Lock()
        self._last_ms = 0
        self._seq = 0

    def next_id(self) -> int:
        with self._lock:
            now = int(time.time() * 1000)
            if now < self._last_ms:
                now = self._last_ms  # clock went backwards: hold position
            if now == self._last_ms:
                self._seq += 1
                if self._seq >= (1 << _SEQ_BITS):
                    # 4096 ids in one ms: borrow the next tick instead of
                    # busy-waiting with the lock held (a stepped-back
                    # clock would otherwise stall assigns for seconds)
                    now += 1
                    self._seq = 0
            else:
                self._seq = 0
            self._last_ms = now
            return (
                ((now - _EPOCH_MS) << (_NODE_BITS + _SEQ_BITS))
                | (self.node_id << _SEQ_BITS)
                | self._seq
            )


class CounterSequencer:
    """Monotonic in-memory counter (tests / ephemeral clusters)."""

    def __init__(self, start: int = 0):
        self._lock = threading.Lock()
        self._n = start

    def next_id(self) -> int:
        with self._lock:
            self._n += 1
            return self._n
