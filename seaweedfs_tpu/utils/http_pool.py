"""Bounded worker-pool HTTP front end for the gateway data planes.

``ThreadingHTTPServer`` spawns one thread per CONNECTION and holds it
for the connection's whole life: at production concurrency (100+
keep-alive clients) that is unbounded thread growth, GIL thrash, and —
past the thread limit — silent collapse. :class:`PooledHTTPServer`
replaces it on the S3/filer/volume data planes (ISSUE 11) with the
classic acceptor/poller/worker shape:

- a FIXED worker pool (``workers``) handles requests; a connection
  occupies a worker only while a request is in flight;
- between requests a keep-alive connection is PARKED in a selector —
  10k idle connections cost file descriptors, not threads;
- a bounded accept budget (``workers + accept_queue`` live
  connections): past it, a new connection is answered immediately with
  ``503 Service Unavailable`` + ``Retry-After`` and a server-kind error
  body (an S3 XML error document on the S3 plane) — graceful
  degradation with an explicit client signal, not collapse;
- saturation and load are observable: ``sw_gateway_inflight{server}``,
  ``sw_gateway_rejected_total{server}``, and :meth:`pool_status` for
  the ``/debug/gateway`` surface.

The stdlib ``BaseHTTPRequestHandler`` contract is preserved: the same
handler classes run unmodified (request tracing mixin included); one
handler instance lives per connection, driven one ``handle_one_request``
at a time by whichever worker the dispatcher picks.

TLS: servers wrap their listener AFTER construction
(``utils/tls.py``); the pooled front end is used on plain-HTTP data
planes only — a TLS-configured server keeps ``ThreadingHTTPServer``
(the non-blocking readiness probe below is not SSLSocket-safe).
"""

from __future__ import annotations

import queue
import selectors
import socket
import threading
import time
from http.server import HTTPServer

# How many back-to-back requests one dispatch may serve before the
# connection is re-queued behind other ready work — bounds how long a
# pipelining client can monopolize a worker.
_MAX_REQUESTS_PER_DISPATCH = 32

_IDLE_SWEEP_INTERVAL = 5.0


def _plain_reject_body() -> tuple[str, bytes]:
    return (
        "text/plain",
        b"503 server saturated: worker pool and accept queue are full\n",
    )


class _Conn:
    """One live client connection: its socket, its persistent handler
    instance (rfile/wfile survive across requests — keep-alive), and
    its idle bookkeeping."""

    __slots__ = ("sock", "handler", "last_active")

    def __init__(self, sock, handler):
        self.sock = sock
        self.handler = handler
        self.last_active = time.monotonic()


def _deferred_handler(cls, request_timeout: float):
    """Subclass `cls` so constructing it runs ONLY setup (rfile/wfile
    creation): the pool drives `handle_one_request` itself, one request
    per dispatch, instead of the stdlib's construct-and-serve-to-close.
    """

    class Deferred(cls):
        timeout = request_timeout  # setup() applies it to the socket

        def handle(self):  # the pool dispatches requests itself
            pass

        def finish(self):  # the pool closes the connection itself
            pass

        def _pool_finish(self):
            try:
                cls.finish(self)  # the real flush-and-close chain
            except Exception:
                pass

    Deferred.__name__ = f"Pooled{cls.__name__}"
    return Deferred


class PooledHTTPServer(HTTPServer):
    """Drop-in for ``ThreadingHTTPServer`` (same ``serve_forever`` /
    ``shutdown`` / ``server_close`` lifecycle) with a fixed worker pool
    and explicit backpressure. See the module docstring."""

    allow_reuse_address = 1
    # Kernel accept-queue depth (socket.listen backlog). The stdlib
    # default of 5 would drop SYNs from a 100-client connection burst
    # long before the pool's own explicit-503 admission logic ever saw
    # them (retransmit stalls of 1s+ on exactly the concurrency path
    # this server exists for). The kernel clamps to somaxconn.
    request_queue_size = 1024

    def __init__(
        self,
        server_address,
        RequestHandlerClass,
        workers: int = 32,
        accept_queue: int = 128,
        idle_timeout: float = 30.0,
        request_timeout: float = 120.0,
        server_kind: str = "http",
        reject_body=None,
        retry_after: int = 1,
    ):
        """`workers`: threads handling requests. `accept_queue`: live
        connections allowed beyond the worker count before new ones are
        503-rejected. `idle_timeout`: parked keep-alive connections idle
        longer than this are closed. `request_timeout`: socket timeout
        while a request is in flight (a stalled mid-request peer gets
        its connection closed, stdlib semantics). `reject_body`: zero-
        arg callable -> (content_type, bytes) for the 503 body — the S3
        plane passes an XML error-document builder so rejected SDK
        clients still parse a well-formed S3 error."""
        super().__init__(server_address, RequestHandlerClass)
        self.workers = max(1, int(workers))
        self.accept_queue = max(0, int(accept_queue))
        self.max_connections = self.workers + self.accept_queue
        self.idle_timeout = float(idle_timeout)
        self.request_timeout = float(request_timeout)
        self.server_kind = server_kind
        self.retry_after = int(retry_after)
        self._reject_body = reject_body or _plain_reject_body
        self._handler_cls = _deferred_handler(
            RequestHandlerClass, self.request_timeout
        )
        self._ready: "queue.Queue[_Conn | None]" = queue.Queue()
        self._park_q: "queue.Queue[_Conn]" = queue.Queue()
        self._conns: set[_Conn] = set()
        self._conns_lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._loop_done = threading.Event()
        self._loop_done.set()  # not serving yet
        self._threads: list[threading.Thread] = []
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self.rejected = 0
        self.requests_served = 0

    # ----------------------------------------------------------- lifecycle

    def serve_forever(self, poll_interval: float = 0.5) -> None:
        self._stop_evt.clear()
        self._loop_done.clear()
        self._threads = [
            threading.Thread(
                target=self._worker,
                name=f"http-pool-{self.server_kind}-{i}",
                daemon=True,
            )
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()
        sel = selectors.DefaultSelector()
        self.socket.setblocking(False)
        sel.register(self.socket, selectors.EVENT_READ, "accept")
        sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        last_sweep = time.monotonic()
        try:
            while not self._stop_evt.is_set():
                for key, _ in sel.select(timeout=poll_interval):
                    if key.data == "accept":
                        self._accept()
                    elif key.data == "wake":
                        self._drain_wake(sel)
                    else:
                        # parked connection has bytes (or EOF): hand it
                        # to the pool; the selector forgets it until the
                        # worker parks it again
                        sel.unregister(key.fileobj)
                        conn = key.data
                        conn.last_active = time.monotonic()
                        self._ready.put(conn)
                now = time.monotonic()
                if now - last_sweep >= _IDLE_SWEEP_INTERVAL:
                    last_sweep = now
                    self._sweep_idle(sel)
        finally:
            for t in self._threads:
                self._ready.put(None)
            for key in list(sel.get_map().values()):
                if isinstance(key.data, _Conn):
                    self._close_conn(key.data)
            sel.close()
            for t in self._threads:
                t.join(timeout=2.0)
            # connections still queued or mid-request: close them so
            # server_close leaves no fds behind
            while True:
                try:
                    c = self._ready.get_nowait()
                except queue.Empty:
                    break
                if c is not None:
                    self._close_conn(c)
            self._loop_done.set()

    def shutdown(self) -> None:
        self._stop_evt.set()
        self._wake()
        self._loop_done.wait(timeout=10.0)

    def server_close(self) -> None:
        super().server_close()
        with self._conns_lock:
            leftover = list(self._conns)
        for c in leftover:
            self._close_conn(c)
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass

    # ------------------------------------------------------------- accept

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self.socket.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            with self._conns_lock:
                saturated = len(self._conns) >= self.max_connections
            if saturated:
                self._send_503(sock)
                continue
            try:
                sock.settimeout(self.request_timeout)
                handler = self._handler_cls(sock, addr, self)
                handler.close_connection = True
            except Exception:
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            conn = _Conn(sock, handler)
            with self._conns_lock:
                self._conns.add(conn)
            # straight into the selector: the request bytes may not
            # have arrived yet, and readiness is what dispatches work
            self._park_q.put(conn)
            self._wake()

    def _send_503(self, sock) -> None:
        """Explicit saturation signal: never accepted into the pool, so
        the client sees immediate, parseable backpressure instead of a
        connect that hangs until some thread frees up."""
        self.rejected += 1
        from . import metrics

        metrics.gateway_rejected_total.inc(server=self.server_kind)
        try:
            ctype, body = self._reject_body()
        except Exception:
            ctype, body = _plain_reject_body()
        head = (
            "HTTP/1.1 503 Service Unavailable\r\n"
            f"Retry-After: {self.retry_after}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            "Connection: close\r\n\r\n"
        ).encode("ascii")
        try:
            sock.settimeout(2.0)
            sock.sendall(head + body)
        except OSError:
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    # ----------------------------------------------------------- dispatch

    def _worker(self) -> None:
        while True:
            conn = self._ready.get()
            if conn is None:
                return
            try:
                self._serve_dispatch(conn)
            except Exception:
                self._close_conn(conn)

    def _serve_dispatch(self, conn: _Conn) -> None:
        """Serve request(s) on one ready connection, then park or
        close. The worker is pinned only while requests are actually
        flowing."""
        from . import metrics

        h = conn.handler
        for _ in range(_MAX_REQUESTS_PER_DISPATCH):
            metrics.gateway_inflight.inc(server=self.server_kind)
            try:
                h.handle_one_request()
                with self._conns_lock:  # += is not atomic across workers
                    self.requests_served += 1
            except Exception:
                h.close_connection = True
            finally:
                metrics.gateway_inflight.dec(server=self.server_kind)
            if getattr(h, "close_connection", True):
                self._close_conn(conn)
                return
            if not self._readable_now(conn):
                conn.last_active = time.monotonic()
                self._park_q.put(conn)
                self._wake()
                return
        # fairness: a pipelining client with more buffered requests goes
        # to the back of the ready queue instead of monopolizing this
        # worker
        self._ready.put(conn)

    def _readable_now(self, conn: _Conn) -> bool:
        """True when the connection's NEXT request is already here —
        either buffered in the handler's rfile (pipelining) or sitting
        in the kernel — so the worker keeps serving instead of paying a
        park/wake round trip. A momentary non-blocking peek: rfile.peek
        returns buffered bytes without a raw read, and an empty buffer
        does one non-blocking raw read that yields b'' when the wire is
        quiet."""
        try:
            conn.sock.setblocking(False)
        except OSError:
            return False
        try:
            return bool(conn.handler.rfile.peek(1))
        except Exception:
            return False
        finally:
            try:
                conn.sock.settimeout(self.request_timeout)
            except OSError:
                pass

    # ------------------------------------------------------------ parking

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    def _drain_wake(self, sel) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            pass
        while True:
            try:
                conn = self._park_q.get_nowait()
            except queue.Empty:
                return
            try:
                sel.register(conn.sock, selectors.EVENT_READ, conn)
            except (ValueError, KeyError, OSError):
                self._close_conn(conn)

    def _sweep_idle(self, sel) -> None:
        now = time.monotonic()
        for key in list(sel.get_map().values()):
            conn = key.data
            if not isinstance(conn, _Conn):
                continue
            if now - conn.last_active > self.idle_timeout:
                try:
                    sel.unregister(key.fileobj)
                except (KeyError, ValueError):
                    continue
                self._close_conn(conn)

    def _close_conn(self, conn: _Conn) -> None:
        with self._conns_lock:
            self._conns.discard(conn)
        conn.handler._pool_finish()
        try:
            conn.sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------- status

    def pool_status(self) -> dict:
        """Live front-end state for /debug/gateway and /status."""
        with self._conns_lock:
            open_conns = len(self._conns)
        return {
            "kind": "pooled",
            "server": self.server_kind,
            "workers": self.workers,
            "accept_queue": self.accept_queue,
            "max_connections": self.max_connections,
            "open_connections": open_conns,
            "ready_backlog": self._ready.qsize(),
            "requests_served": self.requests_served,
            "rejected_total": self.rejected,
        }


def build_http_server(
    server_address,
    RequestHandlerClass,
    server_kind: str = "http",
    workers: int = 32,
    accept_queue: int = 128,
    tls=None,
    reject_body=None,
    idle_timeout: float = 30.0,
    request_timeout: float = 120.0,
):
    """The data-plane server factory: a :class:`PooledHTTPServer`
    (bounded workers + backpressure) unless `workers` is 0 (explicit
    opt-out to the unbounded one-thread-per-connection stdlib server)
    or `tls` is configured (the TLS wrapper targets the threaded
    server; see the module docstring). Returned servers all share the
    ``serve_forever``/``shutdown``/``server_close`` lifecycle."""
    if workers and tls is None:
        return PooledHTTPServer(
            server_address,
            RequestHandlerClass,
            workers=workers,
            accept_queue=accept_queue,
            server_kind=server_kind,
            reject_body=reject_body,
            idle_timeout=idle_timeout,
            request_timeout=request_timeout,
        )
    from http.server import ThreadingHTTPServer

    return ThreadingHTTPServer(server_address, RequestHandlerClass)


def status_of(http_server) -> dict:
    """`pool_status` for either server flavor (the threaded fallback
    reports its kind so /debug/gateway always answers)."""
    if isinstance(http_server, PooledHTTPServer):
        return http_server.pool_status()
    return {"kind": "threading", "server": "", "workers": 0}


# --------------------------------------------------------------------------
# Native response-body egress (ISSUE 12). PR 11 measured the warm
# gateway path at ~180 GETs/s on 2 cores with the ceiling squarely in
# Python HTTP byte handling under the GIL: every worker's
# wfile.write(body) serializes the hot path through the interpreter.
# `send_body` hands body-bytes egress to the native scatter-gather
# sender (sn_sendv — writev straight from the body buffers, GIL
# RELEASED for the whole send, poll-driven on the pool's non-blocking
# sockets), so N workers push N responses concurrently.
#
# Engages only when ALL hold: the handler runs under a
# PooledHTTPServer (the ThreadingHTTPServer fallback is untouched), the
# native .so loaded and SEAWEED_EC_NATIVE != 0, the body clears
# _NATIVE_BODY_MIN (header-sized bodies are cheaper under the GIL than
# a flush + ctypes call), and the connection is not TLS. Everything
# else — and any import race — falls back to wfile.write, emitting the
# SAME bytes on the wire.
# --------------------------------------------------------------------------

_NATIVE_BODY_MIN = 8 << 10


def _native_mod():
    import os as _os

    if _os.environ.get("SEAWEED_EC_NATIVE", "1") == "0":
        return None
    try:
        from . import native

        return native
    except ImportError:
        return None


def send_body(handler, *parts) -> int:
    """Write an HTTP response body (already-framed: headers sent via
    end_headers) through the native egress when available, else through
    wfile — bit-identical on the wire either way. Returns bytes
    written. A short/failed native send marks the connection dead and
    raises (the framing is broken; the pool closes the socket), exactly
    like a wfile.write OSError."""
    parts = [p for p in parts if len(p)]
    total = sum(len(p) for p in parts)
    if handler.command == "HEAD" or total == 0:
        return 0
    from . import metrics

    srv = getattr(handler, "server", None)
    if (
        total >= _NATIVE_BODY_MIN
        and isinstance(srv, PooledHTTPServer)
    ):
        native = _native_mod()
        if native is not None and not _is_tls(handler.connection):
            handler.wfile.flush()
            try:
                native.sendv(
                    handler.connection.fileno(), parts,
                    timeout_ms=int(srv.request_timeout * 1000),
                )
            except OSError:
                # partial body = broken framing: never reuse this
                # connection, and surface like a stdlib write error
                handler.close_connection = True
                raise
            metrics.net_bytes_sent_total.inc(total, plane="native", direction="read")
            return total
    for p in parts:
        handler.wfile.write(p)
    metrics.net_bytes_sent_total.inc(total, plane="python", direction="read")
    metrics.net_bytes_copied_total.inc(total, plane="python", direction="read")
    return total


def _is_tls(sock) -> bool:
    try:
        import ssl

        return isinstance(sock, ssl.SSLSocket)
    except ImportError:  # pragma: no cover
        return False
