"""Pipeline flight recorder: stage-attributed spans for the EC data path.

The bench verdict (ROADMAP "bench reality check") is that e2e encode is
I/O-bound while the device kernel is effectively free — but the only
evidence is aggregate counters after the fact. This module attributes
wall time to every STAGE of an EC operation (admission wait, queue
wait, disk read, H2D dispatch, device drain, fused write+CRC sink,
verify, publish/rename) and stitches the stages into one span tree per
operation, across threads and — via gRPC metadata — across servers.

Model
-----

- A :class:`Span` is one timed node: a root per EC op (``ec.encode``,
  ``ec.rebuild``, ``ec.decode``, ``ec.degraded_read``,
  ``ec.peer_rebuild``, ``rpc.ec_shard_read`` …), children for sub-ops
  (per-peer fetches, the nested rebuild inside a decode). Spans carry
  per-stage ACCUMULATORS (total seconds + count per stage name) rather
  than one child span per pipeline batch — a 1 GiB encode is thousands
  of batches, and the interesting question is "where did the op's time
  go", not "what did batch #3817 do".
- Completed LOCAL ROOTS (spans with no local parent — including spans
  whose parent lives on another server) land in a bounded ring,
  dumpable as Chrome ``trace_event`` JSON (``/debug/traces``,
  ``bench.py --trace-out``; load the file in Perfetto / chrome://tracing).
- Trace identity crosses RPC hops in gRPC metadata
  (:data:`TRACE_ID_KEY` / :data:`PARENT_SPAN_KEY`) alongside
  ``X-Request-ID``, so a fleet-dispatched peer-fetch rebuild yields ONE
  trace id spanning master task → rebuilding holder → every peer's
  shard-read stream.

Canonical stage names (the Prometheus ``stage`` label of
``sw_ec_stage_seconds``):

=================  =====================================================
``admission_wait`` blocked in the device-queue scheduler before dispatch
``queue_wait``     blocked on a full bounded pipeline queue
                   (backpressure; accumulated from BOTH pipeline
                   threads, so its total may exceed the op wall)
``disk_read``      source reads (shards, .dat) in the reader thread
``sibling_read``   degraded-read sibling shard reads (local + remote)
``h2d_dispatch``   host→device upload + async kernel dispatch
``device_drain``   blocked in ``to_host`` (device compute not yet hidden
                   + D2H)
``write_sink``     fused write+CRC sink appends (or plain output writes)
``crc_verify``     sidecar CRC verification of streamed/reconstructed
                   bytes
``verify``         dedicated whole-shard sidecar verification passes
``reconstruct``    synchronous (non-staged) Reed-Solomon apply
``fsync_publish``  flush/fsync/rename publication windows
``stream``         server-side RPC response streaming
=================  =====================================================

Overlap efficiency
------------------

Per completed root, over the WHOLE span tree: let ``device`` be the
summed device-stage time (``h2d_dispatch`` + ``device_drain``),
``host`` the summed non-device stage time, and ``wall`` the root span
duration. Wall time not explained by host stages must have been spent
exposed to device work — and time measurably blocked in ``to_host``
(``device_drain``) is exposed by definition, which keeps the number
honest when host stages overlap EACH OTHER across pipeline threads
(their sum can exceed wall, zeroing the residue)::

    exposed = clamp(max(wall - host, drain), 0, device)
    overlap_efficiency = (device - exposed) / device

1.0 = every device second hid behind I/O (PR 3's staging is doing its
job on this host); 0.0 = fully serial. Exported per op class as
``sw_ec_overlap_efficiency`` — the single number that says whether the
staged pipeline actually overlaps.

Disarm discipline (same as ``faults/``): the tracer is OFF by default
and every production call site is a single module-bool (or is-None)
check when disarmed — no allocation, no lock, no contextvar read. Hot
per-batch helpers (:func:`stage`, :func:`add_stage`, :func:`current`)
take only positional arguments so the disarmed path cannot even box a
kwargs dict.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from contextvars import ContextVar

from . import metrics as _M
from . import request_id as _rid
from .glog import logger

_log = logger("trace")

# gRPC metadata keys (lowercase: gRPC normalizes ASCII keys).
TRACE_ID_KEY = "x-sw-trace-id"
PARENT_SPAN_KEY = "x-sw-parent-span"
REQUEST_ID_KEY = "x-request-id"

# The SAME trace identity over HTTP: the gateway hops (client → S3 →
# filer → volume) carry these beside X-Request-ID, so one S3 GET yields
# ONE trace id across every server it crosses. Canonical casing for
# send; HTTP header lookup is case-insensitive on receive.
TRACE_ID_HEADER = "X-Sw-Trace-Id"
PARENT_SPAN_HEADER = "X-Sw-Parent-Span"

DEFAULT_RING = 256
# Ring is additionally bounded by TOTAL SPAN COUNT across all retained
# trace docs: one span-heavy op class (a wide gateway fan-out op can
# carry hundreds of child spans) must not pin an unbounded share of
# memory behind a trace-count-only bound.
DEFAULT_RING_SPANS = 20_000

# Canonical stage names — the ONLY values legal as the `stage` label of
# ``sw_ec_stage_seconds``. tests/test_trace.py lints every stage literal
# in the package against this registry, so a typo'd label fails tier-1
# instead of silently forking a histogram series.
STAGES = frozenset({
    # device-queue / pipeline (PR 4-7)
    "admission_wait", "queue_wait", "disk_read", "stage_batch",
    "sibling_read", "h2d_dispatch", "device_drain", "write_sink",
    "crc_verify", "verify", "reconstruct", "fsync_publish", "stream",
    "index_sort", "peer_fetch",
    # leaf repair (PR 8)
    "repair_patch", "repair_fetch",
    # streaming EC (PR 14): incremental parity math + delta pwrites
    "parity_update",
    # gateway read path (PR 9): where a slow S3 GET burned its budget
    "s3.auth", "filer.lookup", "chunk.fetch", "volume.read",
})

# Stages that count as device time for the overlap-efficiency gauge.
DEVICE_STAGES = frozenset({"h2d_dispatch", "device_drain"})

_stage_seconds = _M.REGISTRY.histogram(
    "sw_ec_stage_seconds",
    "per-stage wall time of EC operations (tracer armed only)",
    ("op", "stage", "chip"),
)
_overlap_eff = _M.REGISTRY.gauge(
    "sw_ec_overlap_efficiency",
    "device time hidden behind I/O / total device time, per op class "
    "(latest completed trace)",
    ("op",),
)
_traces_total = _M.REGISTRY.counter(
    "sw_ec_traces_total", "completed root spans by op class", ("op",)
)
_slow_ops_total = _M.REGISTRY.counter(
    "sw_ec_slow_ops_total", "root spans exceeding the slow-op threshold",
    ("op",),
)

# Module-level fast-path flag, read unlocked by every instrumentation
# site. configure() flips it under _lock AFTER the ring/threshold are in
# place, so an armed reader never sees half-configured state; a racing
# reader at worst misses the first op after arming.
armed = False

_lock = threading.Lock()
_ring: deque = deque(maxlen=DEFAULT_RING)
_ring_spans = 0  # total span count across the retained docs
_max_ring_spans = DEFAULT_RING_SPANS
_slow_op_s = 0.0

# Per-(op, stage) exponentially-weighted moving averages of stage
# seconds (armed only — fed by Span.add_stage). These ride volume-server
# heartbeats to the master as part of the telemetry plane, giving the
# fleet a "where does this host's op time go" signal without shipping
# whole traces.
EWMA_ALPHA = 0.2
_ewma_lock = threading.Lock()
_stage_ewma: dict[tuple[str, str], float] = {}

_current: ContextVar["Span | None"] = ContextVar("sw_trace_span", default=None)


class _Noop:
    """Singleton no-op context manager: the disarmed fast path of
    :func:`stage` and :func:`activate` returns this, so span-enter/exit
    when disarmed is one is-None check and zero allocations."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


class _StageTimer:
    __slots__ = ("span", "name", "chip", "t0")

    def __init__(self, span: "Span", name: str, chip: str):
        self.span = span
        self.name = name
        self.chip = chip

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.span.add_stage(
            self.name, time.perf_counter() - self.t0, self.chip
        )
        return False


class _Activation:
    """Sets the ambient span contextvar for the with-block (children
    started inside pick it up as their parent; grpc_metadata() reads
    it for outgoing hops)."""

    __slots__ = ("span", "_token")

    def __init__(self, span: "Span"):
        self.span = span

    def __enter__(self):
        self._token = _current.set(self.span)
        return self.span

    def __exit__(self, *exc):
        _current.reset(self._token)
        return False


class Span:
    """One timed node of a trace. Thread-safe for stage/event/child
    recording (pipeline stages run in reader/writer threads
    concurrently); start/finish happen in the owning thread."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "op", "name", "server",
        "request_id", "start_ts", "_t0", "duration_s", "attrs",
        "stages", "events", "children", "_lock", "_local_root",
        "_finished",
    )

    def __init__(
        self,
        op: str,
        name: str = "",
        trace_id: str = "",
        parent_id: str = "",
        server: str = "",
        attrs: dict | None = None,
        local_root: bool = True,
    ):
        self.trace_id = trace_id or uuid.uuid4().hex[:16]
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent_id
        self.op = op
        self.name = name or op
        self.server = server
        self.request_id = _rid.get()
        self.start_ts = time.time()
        self._t0 = time.perf_counter()
        self.duration_s = 0.0
        self.attrs = dict(attrs) if attrs else {}
        # stage -> [total_seconds, count, chip] (chip: last writer wins
        # — one stream runs on one chip; a mesh stream reports "")
        self.stages: dict[str, list] = {}
        self.events: list[dict] = []
        self.children: list["Span"] = []
        self._lock = threading.Lock()
        self._local_root = local_root
        self._finished = False

    # -------------------------------------------------------- recording

    def child(self, op: str, name: str = "", **attrs) -> "Span":
        c = Span(
            op,
            name=name,
            trace_id=self.trace_id,
            parent_id=self.span_id,
            server=self.server,
            attrs=attrs,
            local_root=False,
        )
        with self._lock:
            self.children.append(c)
        return c

    def add_stage(self, stage: str, seconds: float, chip: str = "") -> None:
        if seconds < 0.0:
            seconds = 0.0
        with self._lock:
            acc = self.stages.get(stage)
            if acc is None:
                self.stages[stage] = [seconds, 1, chip]
            else:
                acc[0] += seconds
                acc[1] += 1
                if chip:
                    acc[2] = chip
        _stage_seconds.observe(seconds, op=self.op, stage=stage, chip=chip)
        with _ewma_lock:
            key = (self.op, stage)
            prev = _stage_ewma.get(key)
            _stage_ewma[key] = (
                seconds
                if prev is None
                else prev + EWMA_ALPHA * (seconds - prev)
            )

    def stage(self, name: str, chip: str = "") -> _StageTimer:
        return _StageTimer(self, name, chip)

    def event(self, name: str, **attrs) -> None:
        with self._lock:
            self.events.append(
                {"ts": time.time(), "name": name, "attrs": attrs}
            )

    # --------------------------------------------------------- lifecycle

    def finish(self) -> None:
        with self._lock:
            if self._finished:
                return
            self._finished = True
            self.duration_s = time.perf_counter() - self._t0
        if self._local_root:
            _complete_root(self)

    # ------------------------------------------------------------ export

    def to_dict(self) -> dict:
        with self._lock:
            dur = (
                self.duration_s
                if self._finished
                else time.perf_counter() - self._t0
            )
            return {
                "trace_id": self.trace_id,
                "span_id": self.span_id,
                "parent_span_id": self.parent_id,
                "op": self.op,
                "name": self.name,
                "server": self.server,
                "request_id": self.request_id,
                "start_ts": self.start_ts,
                "duration_s": dur,
                "attrs": dict(self.attrs),
                "stages": {
                    s: {"seconds": a[0], "count": a[1], "chip": a[2]}
                    for s, a in self.stages.items()
                },
                "events": [dict(e) for e in self.events],
                "children": [c.to_dict() for c in self.children],
            }


# --------------------------------------------------------------------------
# Root completion: ring + derived metrics + slow-op log.
# --------------------------------------------------------------------------


def _tree_stage_totals(doc: dict) -> dict[str, float]:
    totals: dict[str, float] = {}
    stack = [doc]
    while stack:
        d = stack.pop()
        for s, a in d["stages"].items():
            totals[s] = totals.get(s, 0.0) + a["seconds"]
        stack.extend(d["children"])
    return totals


def overlap_efficiency(doc: dict) -> float | None:
    """Device time hidden behind I/O / total device time for one root
    span dict (None when the op did no device work). See the module
    docstring for the derivation.

    Two estimators of exposed device time, combined by max:

    - wall residue ``wall - host``: host stages run in parallel
      threads (reader disk_read vs sink write_sink vs both sides'
      queue_wait), so their SUM can exceed wall and the residue alone
      would then read 0 ("fully hidden") no matter what the device did;
    - ``device_drain``: a DIRECT measurement — every second blocked in
      ``to_host`` is a second the device was not hidden.
    """
    totals = _tree_stage_totals(doc)
    device = sum(v for s, v in totals.items() if s in DEVICE_STAGES)
    if device <= 0.0:
        return None
    host = sum(v for s, v in totals.items() if s not in DEVICE_STAGES)
    residue = max(doc["duration_s"] - host, 0.0)
    exposed = min(max(residue, totals.get("device_drain", 0.0)), device)
    return (device - exposed) / device


def _doc_span_count(doc: dict) -> int:
    n = 0
    stack = [doc]
    while stack:
        d = stack.pop()
        n += 1
        stack.extend(d["children"])
    return n


def _complete_root(span: Span) -> None:
    global _ring_spans
    doc = span.to_dict()
    _traces_total.inc(op=span.op)
    eff = overlap_efficiency(doc)
    if eff is not None:
        doc["overlap_efficiency"] = round(eff, 4)
    # Gauge per op CLASS over each EC subtree, not just the local root:
    # behind an RPC adoption the root op is rpc.*, but the tuning
    # question — "is encode/rebuild staging actually overlapping on
    # this host?" — is asked per ec.* op.
    stack = [doc]
    while stack:
        d = stack.pop()
        if d is doc or d["op"].startswith("ec."):
            e = overlap_efficiency(d)
            if e is not None:
                _overlap_eff.set(e, op=d["op"])
        stack.extend(d["children"])
    doc["span_count"] = _doc_span_count(doc)
    with _lock:
        # manual maxlen handling so the span-count budget stays exact:
        # deque's own eviction on append would bypass the accounting
        while len(_ring) >= (_ring.maxlen or DEFAULT_RING):
            _ring_spans -= _ring.popleft().get("span_count", 1)
        _ring.append(doc)
        _ring_spans += doc["span_count"]
        # byte-bound analog: a span-heavy op class evicts oldest docs
        # beyond the trace-count bound too (always keep the newest)
        while _ring_spans > _max_ring_spans and len(_ring) > 1:
            _ring_spans -= _ring.popleft().get("span_count", 1)
        slow = _slow_op_s
    if 0.0 < slow <= doc["duration_s"]:
        _slow_ops_total.inc(op=span.op)
        _log.warning(
            "slow op %s (%.3fs > %.3fs) request_id=%s trace=%s\n%s",
            span.op, doc["duration_s"], slow,
            doc["request_id"] or "-", span.trace_id, format_tree(doc),
        )


def format_tree(doc: dict, indent: int = 0) -> str:
    """Human-readable span tree with per-stage durations (the slow-op
    log body). The root line carries the request id and root op so a
    logged tree can be joined against gateway access logs even when the
    surrounding log prefix is stripped."""
    pad = "  " * indent
    stages = " ".join(
        f"{s}={a['seconds'] * 1000:.1f}ms/{a['count']}"
        for s, a in sorted(doc["stages"].items())
    )
    line = (
        f"{pad}{doc['op']}"
        f"{' [' + doc['name'] + ']' if doc['name'] != doc['op'] else ''}"
        f" {doc['duration_s'] * 1000:.1f}ms"
    )
    if indent == 0:
        line += (
            f" root={doc['op']}"
            f" rid={doc.get('request_id') or '-'}"
            f" trace={doc.get('trace_id', '')}"
        )
    if doc.get("server"):
        line += f" @{doc['server']}"
    if stages:
        line += f" | {stages}"
    out = [line]
    for ev in doc["events"]:
        out.append(f"{pad}  * {ev['name']} {ev['attrs']}")
    for c in doc["children"]:
        out.append(format_tree(c, indent + 1))
    return "\n".join(out)


# --------------------------------------------------------------------------
# Module API (production call sites).
# --------------------------------------------------------------------------


def configure(
    enabled: bool | None = None,
    ring_size: int | None = None,
    slow_op_s: float | None = None,
    ring_spans: int | None = None,
) -> dict:
    """Arm/disarm the tracer and tune the ring / slow-op threshold.
    ``slow_op_s`` <= 0 disables the slow-op log. ``ring_spans`` bounds
    the TOTAL span count retained across the ring (memory bound for
    span-heavy op classes). Returns the effective config."""
    global armed, _ring, _ring_spans, _max_ring_spans, _slow_op_s
    with _lock:
        if ring_size is not None and ring_size > 0:
            if _ring.maxlen != ring_size:
                _ring = deque(_ring, maxlen=int(ring_size))
                _ring_spans = sum(
                    d.get("span_count", 1) for d in _ring
                )
        if ring_spans is not None and ring_spans > 0:
            _max_ring_spans = int(ring_spans)
            while _ring_spans > _max_ring_spans and len(_ring) > 1:
                _ring_spans -= _ring.popleft().get("span_count", 1)
        if slow_op_s is not None:
            _slow_op_s = max(float(slow_op_s), 0.0)
        if enabled is not None:
            armed = bool(enabled)
        return {
            "enabled": armed,
            "ring_size": _ring.maxlen,
            "ring_spans": _max_ring_spans,
            "slow_op_s": _slow_op_s,
        }


def reset() -> None:
    """Drop recorded traces (tests)."""
    global _ring_spans
    with _lock:
        _ring.clear()
        _ring_spans = 0
    with _ewma_lock:
        _stage_ewma.clear()


def stage_ewmas() -> dict[str, float]:
    """Per-``op/stage`` EWMA of stage seconds (armed runs only) — the
    heartbeat telemetry payload."""
    with _ewma_lock:
        return {f"{op}/{st}": v for (op, st), v in _stage_ewma.items()}


def start(op: str, name: str = "", parent: "Span | None" = None, **attrs):
    """Open a span (None when disarmed — every downstream helper
    accepts None). With no explicit ``parent`` the ambient span (set by
    :func:`activate`) is the parent; no ambient span = a new local
    root."""
    if not armed:
        return None
    p = parent if parent is not None else _current.get()
    if p is not None:
        return p.child(op, name, **attrs)
    return Span(op, name=name, attrs=attrs)


def start_from_metadata(
    op: str, md: dict, name: str = "", server: str = "", **attrs
):
    """Server-side span adoption: continue the trace carried in gRPC
    metadata (a LOCAL root here — its parent lives on the caller).
    None when disarmed."""
    if not armed:
        return None
    return Span(
        op,
        name=name,
        trace_id=md.get(TRACE_ID_KEY, ""),
        parent_id=md.get(PARENT_SPAN_KEY, ""),
        server=server,
        attrs=attrs,
    )


def start_from_headers(op: str, headers, name: str = "", server: str = "",
                       **attrs):
    """HTTP-side span adoption: continue the trace carried in request
    headers (a LOCAL root here — its parent span lives on the calling
    server/client). ``headers`` is any case-insensitive mapping with
    ``.get`` (http.client/BaseHTTPRequestHandler message objects
    qualify). None when disarmed."""
    if not armed:
        return None
    return Span(
        op,
        name=name,
        trace_id=headers.get(TRACE_ID_HEADER) or "",
        parent_id=headers.get(PARENT_SPAN_HEADER) or "",
        server=server,
        attrs=attrs,
    )


def http_headers(span=None, headers: dict | None = None) -> dict | None:
    """Outgoing HTTP headers carrying the trace context of ``span`` (or
    the ambient span). Returns ``headers`` with the two trace headers
    merged in, or None when there is nothing to carry (the request id
    rides separately via request_id.inject)."""
    sp = span
    if sp is None and armed:
        sp = _current.get()
    if sp is None:
        return headers
    h = headers if headers is not None else {}
    h[TRACE_ID_HEADER] = sp.trace_id
    h[PARENT_SPAN_HEADER] = sp.span_id
    return h


def set_current(span):
    """Install ``span`` as the ambient span; returns a token for
    :func:`reset_current` (the non-with-block form of :func:`activate`,
    for request handlers whose enter/exit live in different methods).
    None-safe: returns None when ``span`` is None."""
    if span is None:
        return None
    return _current.set(span)


def reset_current(token) -> None:
    if token is not None:
        _current.reset(token)


def current():
    """The ambient span, or None (always None when disarmed — the
    contextvar is not even read)."""
    if not armed:
        return None
    return _current.get()


def activate(span):
    """Context manager setting the ambient span for the with-block;
    no-op singleton when ``span`` is None."""
    if span is None:
        return _NOOP
    return _Activation(span)


def finish(span) -> None:
    if span is not None:
        span.finish()


def stage(span, name: str, chip: str = ""):
    """Per-batch stage timer: ``with trace.stage(sp, "disk_read"): …``.
    One is-None check and the singleton no-op when disarmed."""
    if span is None:
        return _NOOP
    return _StageTimer(span, name, chip)


def add_stage(span, name: str, seconds: float, chip: str = "") -> None:
    if span is not None:
        span.add_stage(name, seconds, chip)


def event(span, name: str, **attrs) -> None:
    if span is not None:
        span.event(name, **attrs)


def grpc_metadata(span=None, extra=None):
    """Outgoing gRPC metadata carrying the active request id and (when
    armed and a span is active) the trace context. Returns None when
    there is nothing to carry — ``grpc`` accepts ``metadata=None``.
    ``extra`` is an iterable of additional (key, value) pairs."""
    md = list(extra) if extra else []
    rid = _rid.get()
    if rid:
        md.append((REQUEST_ID_KEY, rid))
    sp = span
    if sp is None and armed:
        sp = _current.get()
    if sp is not None:
        md.append((TRACE_ID_KEY, sp.trace_id))
        md.append((PARENT_SPAN_KEY, sp.span_id))
    return tuple(md) if md else None


def metadata_dict(context) -> dict:
    """Lower-cased invocation metadata of a gRPC servicer context
    (empty for in-process calls passing context=None)."""
    md: dict = {}
    if context is None:
        return md
    try:
        for k, v in context.invocation_metadata():
            md[k.lower()] = v
    except Exception:
        pass
    return md


# --------------------------------------------------------------------------
# Ring export.
# --------------------------------------------------------------------------


def traces(
    trace_id: str = "", op: str = "", min_ms: float = 0.0
) -> list[dict]:
    """Completed root spans, oldest first. Filters: one trace id (a
    cross-server trace is several roots sharing it), a root ``op``
    class, and/or a minimum root duration in milliseconds — the
    ``/debug/traces?op=&min_ms=`` query surface."""
    with _lock:
        docs = list(_ring)
    if trace_id:
        docs = [d for d in docs if d["trace_id"] == trace_id]
    if op:
        docs = [d for d in docs if d["op"] == op]
    if min_ms > 0.0:
        docs = [d for d in docs if d["duration_s"] * 1000.0 >= min_ms]
    return docs


def chrome_trace(trace_id: str = "", docs: list[dict] | None = None) -> dict:
    """Chrome ``trace_event`` JSON (the dict; ``json.dump`` it) for the
    recorded traces — loadable in Perfetto / chrome://tracing. Each
    server becomes a process row, each root span a thread row; stages
    and attrs ride in ``args``."""
    if docs is None:
        docs = traces(trace_id)
    events: list[dict] = []
    pids: dict[str, int] = {}
    tid_next: dict[int, int] = {}

    def emit(doc: dict, pid: int, tid: int) -> None:
        args = {
            "trace_id": doc["trace_id"],
            "span_id": doc["span_id"],
            "request_id": doc["request_id"],
            "stages_ms": {
                s: round(a["seconds"] * 1000.0, 3)
                for s, a in doc["stages"].items()
            },
        }
        if doc.get("overlap_efficiency") is not None:
            args["overlap_efficiency"] = doc["overlap_efficiency"]
        args.update(doc["attrs"])
        events.append(
            {
                "name": doc["name"],
                "cat": doc["op"],
                "ph": "X",
                "ts": doc["start_ts"] * 1e6,
                "dur": max(doc["duration_s"], 1e-6) * 1e6,
                "pid": pid,
                "tid": tid,
                "args": args,
            }
        )
        for ev in doc["events"]:
            events.append(
                {
                    "name": ev["name"],
                    "cat": doc["op"],
                    "ph": "i",
                    "s": "t",
                    "ts": ev["ts"] * 1e6,
                    "pid": pid,
                    "tid": tid,
                    "args": dict(ev["attrs"]),
                }
            )
        for c in doc["children"]:
            emit(c, pid, tid)

    for doc in docs:
        server = doc.get("server") or "proc"
        pid = pids.get(server)
        if pid is None:
            pid = pids[server] = len(pids) + 1
            events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": 0,
                    "args": {"name": server},
                }
            )
        tid = tid_next.get(pid, 0) + 1
        tid_next[pid] = tid
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {
                    "name": f"{doc['op']} {doc['trace_id'][:8]}"
                },
            }
        )
        emit(doc, pid, tid)
    return {"traceEvents": events, "displayTimeUnit": "ms"}
