"""Opt-in cluster telemetry phone-home.

Reference: weed/telemetry/collector.go:14 — the leader master
periodically posts a small report {version, os, volume counts, enabled
features} to a configured telemetry endpoint. Off unless a URL is
given; report contents are size/count aggregates only, never names or
data.
"""

from __future__ import annotations

import json
import platform
import threading
import urllib.request
import uuid

from .glog import logger

log = logger("telemetry")

VERSION = "seaweedfs-tpu/0.2"


class TelemetryCollector:
    def __init__(
        self,
        url: str,
        stats_fn,
        interval: float = 24 * 3600.0,
        is_leader_fn=None,
    ):
        """stats_fn() -> dict of count aggregates merged into the
        report; is_leader_fn gates sending to the raft leader so an HA
        group phones home once."""
        self.url = url
        self.stats_fn = stats_fn
        self.interval = interval
        self.is_leader_fn = is_leader_fn or (lambda: True)
        self.cluster_id = str(uuid.uuid4())
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> None:
        if self.url:
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def report(self) -> dict:
        data = {
            "version": VERSION,
            "os": f"{platform.system()}/{platform.machine()}",
            "cluster_id": self.cluster_id,
        }
        try:
            data.update(self.stats_fn() or {})
        except Exception as e:  # stats must never break the loop
            log.warning("stats collection failed: %s", e)
        return data

    def send_once(self) -> bool:
        if not self.is_leader_fn():
            return False
        body = json.dumps(self.report()).encode()
        req = urllib.request.Request(
            self.url,
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                return 200 <= r.status < 300
        except Exception as e:
            log.v(1, "telemetry post failed: %s", e)
            return False

    def _loop(self) -> None:
        # first report shortly after boot, then every interval
        if not self._stop.wait(60.0):
            self.send_once()
        while not self._stop.wait(self.interval):
            self.send_once()
