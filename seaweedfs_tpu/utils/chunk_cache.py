"""Byte-bounded LRU chunk cache (reference weed/util/chunk_cache, the
memory tier), plus the read-through/singleflight layer the gateway hot
path rides (ISSUE 11): N concurrent misses on one key collapse to ONE
loader call — under concurrent serving traffic a degraded chunk is
reconstructed exactly once, everyone else waits for the leader's bytes.

Two cache tiers use this module on the GET path:

- the filer chunk cache (``tier="filer_chunk"``): fid-keyed, immutable
  bytes (a fid's content never changes), so entries need no
  invalidation, only eviction;
- the EC reconstructed-interval cache (``tier="ec_interval"``):
  generation-qualified ``<vol>:<shard>:<gen>:<lo>:<hi>`` keys, so
  remount/rebuild/leaf-patch invalidate by bumping the generation (a
  stale in-flight load parks its result under the old key where no new
  reader looks).

Counter deltas surface as ``sw_gateway_hot_cache_{hits,misses,
singleflight_waits}_total{tier}``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict


class _Flight:
    """One in-progress load: the leader computes, followers wait.
    ``doomed`` is the invalidation fence — set (under the CACHE's
    lock) when a drop superseded this flight: its result still goes to
    the callers that joined before the invalidation, but it must not
    be admitted, and new callers must not join it."""

    __slots__ = ("done", "value", "exc", "doomed")

    def __init__(self):
        self.done = threading.Event()
        self.value = None
        self.exc: BaseException | None = None
        self.doomed = False


class SingleFlight:
    """Per-key call collapsing (golang.org/x/sync/singleflight): while
    one ``do(key, fn)`` is in progress, other callers with the same key
    block and receive the leader's result (or its exception) instead of
    re-running ``fn``. Keys are independent; distinct keys run
    concurrently."""

    def __init__(self):
        self._lock = threading.Lock()
        self._flights: dict = {}

    def do(self, key, fn):
        """Returns ``(value, waited)`` — ``waited`` is True when this
        call joined another caller's in-progress load instead of
        running ``fn`` itself. The leader's ``fn`` receives the flight
        object (its ``doomed`` flag is the admission fence); the
        leader's exception propagates to every joined caller."""
        with self._lock:
            fl = self._flights.get(key)
            if fl is not None:
                lead = False
            else:
                fl = self._flights[key] = _Flight()
                lead = True
        if not lead:
            fl.done.wait()
            if fl.exc is not None:
                raise fl.exc
            return fl.value, True
        try:
            fl.value = fn(fl)
        except BaseException as e:
            fl.exc = e
            raise
        finally:
            with self._lock:
                # a doomed flight was already detached (and the key may
                # now belong to a FRESH post-invalidation flight): only
                # remove our own entry
                if self._flights.get(key) is fl:
                    del self._flights[key]
            fl.done.set()
        return fl.value, False

    def active_keys(self) -> list:
        """Keys with a load currently in flight (invalidation fencing
        enumerates these to doom matching flights)."""
        with self._lock:
            return list(self._flights)

    def doom(self, key) -> "_Flight | None":
        """Detach and fence the in-flight load for `key` (if any):
        callers already joined still receive its result, but new
        ``do`` calls for the key start a FRESH load, and the flight's
        ``doomed`` flag tells its leader not to admit. The caller must
        hold whatever lock serializes admission against invalidation
        (the ChunkCache holds its own lock across both)."""
        with self._lock:
            fl = self._flights.pop(key, None)
        if fl is not None:
            fl.doomed = True
        return fl


class ChunkCache:
    def __init__(self, capacity_bytes: int = 64 * 1024 * 1024, tier: str = ""):
        """`tier` labels this cache's hit/miss/singleflight counters in
        the ``sw_gateway_hot_cache_*`` metrics ("" = don't export —
        private caches outside the serving path stay silent)."""
        self.capacity = capacity_bytes
        self.tier = tier
        self._lock = threading.Lock()
        self._data: "OrderedDict[str, bytes]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.singleflight_waits = 0
        self.loads = 0
        self._sf = SingleFlight()

    def get(self, fid: str) -> bytes | None:
        with self._lock:
            val = self._data.get(fid)
            if val is None:
                self.misses += 1
            else:
                self._data.move_to_end(fid)
                self.hits += 1
        # metric inc OUTSIDE the cache lock: both tiers share one
        # counter object, so chaining its lock under ours would
        # serialize independent caches' hot hits
        self._count("misses" if val is None else "hits")
        return val

    def _count(self, kind: str) -> None:
        if self.tier:
            from . import metrics

            counter = {
                "hits": metrics.gateway_hot_cache_hits_total,
                "misses": metrics.gateway_hot_cache_misses_total,
                "singleflight_waits":
                    metrics.gateway_hot_cache_singleflight_waits_total,
            }[kind]
            counter.inc(tier=self.tier)

    def get_or_load(self, key: str, loader, admit=None):
        """Read-through with singleflight collapse: a hit returns the
        cached bytes; concurrent misses on `key` run `loader()` exactly
        ONCE (everyone receives the leader's bytes — or its exception).
        The leader's result is admitted into the cache unless `admit`
        (bytes -> bool) rejects it (e.g. the filer's "one streaming
        chunk must not flush the hot set" rule).

        Returns ``(data, source)`` with source one of ``"hit"`` (cache),
        ``"load"`` (this caller ran the loader), ``"wait"`` (joined
        another caller's in-flight load).

        A zero-capacity cache (the cache-off/naive configuration) is a
        pure pass-through: no storage, no collapsing — every caller
        pays its own loader call.
        """
        if self.capacity <= 0:
            with self._lock:
                self.misses += 1
                self.loads += 1
            self._count("misses")
            return loader(), "load"
        val = self.get(key)
        if val is not None:
            return val, "hit"

        def lead(fl):
            data = loader()
            # doomed-check + admission are ONE critical section: an
            # invalidation (which removes entries, detaches this
            # flight, and sets fl.doomed — all under this same lock,
            # see drop_*) either ran before — we see the doom and skip
            # the put — or runs after and removes what we just
            # inserted; there is no window to admit stale bytes.
            admit_ok = admit is None or admit(data)
            with self._lock:
                self.loads += 1
                if not fl.doomed and admit_ok:
                    self._put_locked(key, data)
            return data

        data, waited = self._sf.do(key, lead)
        if waited:
            with self._lock:
                self.singleflight_waits += 1
            self._count("singleflight_waits")
            return data, "wait"
        return data, "load"

    def put(self, fid: str, data: bytes) -> None:
        with self._lock:
            self._put_locked(fid, data)

    def _put_locked(self, fid: str, data: bytes) -> None:
        if len(data) > self.capacity:
            return  # never let one chunk flush the whole cache
        old = self._data.pop(fid, None)
        if old is not None:
            self._bytes -= len(old)
        self._data[fid] = data
        self._bytes += len(data)
        while self._bytes > self.capacity and self._data:
            _, evicted = self._data.popitem(last=False)
            self._bytes -= len(evicted)

    def drop(self, fid: str) -> None:
        """Drop one key. A load already in flight for it is fenced
        exactly like drop_prefix: its result goes to the callers that
        joined, but it is never admitted — so an invalidation racing a
        read-through (the filer entry cache's write-vs-lookup race)
        cannot be repopulated by the pre-invalidation load."""
        with self._lock:
            old = self._data.pop(fid, None)
            if old is not None:
                self._bytes -= len(old)
            self._sf.doom(fid)

    def _doom_inflight_locked(self, match) -> None:
        """Fence in-flight loads whose key satisfies `match`: each
        matching flight is DETACHED (new readers start a fresh
        post-invalidation load instead of joining it — a reader that
        begins after a leaf patch must never receive the pre-patch
        reconstruction) and marked doomed (its result goes to the
        callers that already joined, but is never admitted). Caller
        holds self._lock — entry removal, flight detach/doom, and
        lead()'s doomed-check+put all serialize on it, so a leader can
        never slip a stale put past an invalidation. (Lock order
        cache._lock -> SingleFlight._lock; the reverse is never
        taken.)"""
        for k in self._sf.active_keys():
            if match(k):
                self._sf.doom(k)

    def drop_prefix(self, prefix: str) -> int:
        """Drop every entry whose key starts with `prefix` (targeted
        invalidation — e.g. one shard's extents in the EC interval
        cache); returns how many were dropped. O(n) over keys, fine for
        a byte-bounded cache of large values. A matching load already
        in flight is fenced: it completes for its callers but is not
        admitted."""
        with self._lock:
            doomed = [k for k in self._data if k.startswith(prefix)]
            for k in doomed:
                self._bytes -= len(self._data.pop(k))
            self._doom_inflight_locked(lambda k: k.startswith(prefix))
            return len(doomed)

    def drop_matching(self, prefix: str, pred) -> int:
        """Drop entries whose key starts with `prefix` AND satisfies
        `pred(key)` — finer than drop_prefix when only part of a
        namespace went stale (e.g. the byte ranges a leaf repair just
        patched, leaving the shard's other cached extents hot). A
        matching load already in flight is fenced (returned to its
        callers, never admitted), so a reconstruction started over the
        pre-patch bytes cannot repopulate the just-dropped range."""
        with self._lock:
            doomed = [
                k for k in self._data if k.startswith(prefix) and pred(k)
            ]
            for k in doomed:
                self._bytes -= len(self._data.pop(k))
            self._doom_inflight_locked(
                lambda k: k.startswith(prefix) and pred(k)
            )
            return len(doomed)

    def clear(self) -> None:
        """Drop every entry (bulk invalidation — e.g. the EC interval
        cache on shard remount/rebuild/delete). Hit/miss counters are
        deliberately kept: they describe the cache's lifetime, not one
        population of it. In-flight loads are fenced like drop_*."""
        with self._lock:
            self._data.clear()
            self._bytes = 0
            self._doom_inflight_locked(lambda k: True)

    def stats(self) -> dict:
        """Lifetime counters for status surfaces (/debug/gateway)."""
        with self._lock:
            return {
                "capacity_bytes": self.capacity,
                "size_bytes": self._bytes,
                "entries": len(self._data),
                "hits": self.hits,
                "misses": self.misses,
                "loads": self.loads,
                "singleflight_waits": self.singleflight_waits,
            }

    @property
    def size_bytes(self) -> int:
        return self._bytes
