"""Byte-bounded LRU chunk cache (reference weed/util/chunk_cache, the
memory tier). Chunk fids are immutable — a fid's bytes never change —
so entries need no invalidation, only eviction."""

from __future__ import annotations

import threading
from collections import OrderedDict


class ChunkCache:
    def __init__(self, capacity_bytes: int = 64 * 1024 * 1024):
        self.capacity = capacity_bytes
        self._lock = threading.Lock()
        self._data: "OrderedDict[str, bytes]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def get(self, fid: str) -> bytes | None:
        with self._lock:
            val = self._data.get(fid)
            if val is None:
                self.misses += 1
                return None
            self._data.move_to_end(fid)
            self.hits += 1
            return val

    def put(self, fid: str, data: bytes) -> None:
        if len(data) > self.capacity:
            return  # never let one chunk flush the whole cache
        with self._lock:
            old = self._data.pop(fid, None)
            if old is not None:
                self._bytes -= len(old)
            self._data[fid] = data
            self._bytes += len(data)
            while self._bytes > self.capacity and self._data:
                _, evicted = self._data.popitem(last=False)
                self._bytes -= len(evicted)

    def drop(self, fid: str) -> None:
        with self._lock:
            old = self._data.pop(fid, None)
            if old is not None:
                self._bytes -= len(old)

    def drop_prefix(self, prefix: str) -> int:
        """Drop every entry whose key starts with `prefix` (targeted
        invalidation — e.g. one shard's extents in the EC interval
        cache); returns how many were dropped. O(n) over keys, fine for
        a byte-bounded cache of large values."""
        with self._lock:
            doomed = [k for k in self._data if k.startswith(prefix)]
            for k in doomed:
                self._bytes -= len(self._data.pop(k))
            return len(doomed)

    def drop_matching(self, prefix: str, pred) -> int:
        """Drop entries whose key starts with `prefix` AND satisfies
        `pred(key)` — finer than drop_prefix when only part of a
        namespace went stale (e.g. the byte ranges a leaf repair just
        patched, leaving the shard's other cached extents hot)."""
        with self._lock:
            doomed = [
                k for k in self._data if k.startswith(prefix) and pred(k)
            ]
            for k in doomed:
                self._bytes -= len(self._data.pop(k))
            return len(doomed)

    def clear(self) -> None:
        """Drop every entry (bulk invalidation — e.g. the EC interval
        cache on shard remount/rebuild/delete). Hit/miss counters are
        deliberately kept: they describe the cache's lifetime, not one
        population of it."""
        with self._lock:
            self._data.clear()
            self._bytes = 0

    @property
    def size_bytes(self) -> int:
        return self._bytes
