"""Minimal Prometheus-style metrics registry (text exposition format).

Reference: weed/stats/metrics.go (~80 collectors over master/filer/
volume/S3, pull via /metrics or push). Stdlib-only: counters, gauges,
histograms with labels, rendered in the text format Prometheus scrapes.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable


class _Metric:
    def __init__(self, name: str, help_text: str, label_names: tuple[str, ...]):
        self.name = name
        self.help = help_text
        self.label_names = label_names
        self._lock = threading.Lock()

    def snapshot(self) -> dict:
        """{label_values_tuple: value} copy (status surfaces)."""
        with self._lock:
            return dict(self._values)


class Counter(_Metric):
    def __init__(self, name, help_text="", label_names=()):
        super().__init__(name, help_text, tuple(label_names))
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def collect(self):
        yield f"# HELP {self.name} {_escape_help(self.help)}"
        yield f"# TYPE {self.name} counter"
        with self._lock:
            for key, v in sorted(self._values.items()):
                yield f"{self.name}{_fmt_labels(self.label_names, key)} {_num(v)}"


class Gauge(_Metric):
    def __init__(self, name, help_text="", label_names=(), fn: Callable | None = None):
        super().__init__(name, help_text, tuple(label_names))
        self._values: dict[tuple, float] = {}
        self._fn = fn  # callback gauges sample at scrape time

    def set(self, value: float, **labels) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._values[key] = value

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def snapshot(self) -> dict:
        """Like _Metric.snapshot, but a callback gauge samples its fn
        (matching collect) instead of returning stale set() state."""
        if self._fn is not None:
            try:
                return {
                    tuple(labels.get(n, "") for n in self.label_names): v
                    for labels, v in self._fn()
                }
            except Exception:
                return {}
        return super().snapshot()

    def collect(self):
        yield f"# HELP {self.name} {_escape_help(self.help)}"
        yield f"# TYPE {self.name} gauge"
        if self._fn is not None:
            try:
                for labels, value in self._fn():
                    key = tuple(labels.get(n, "") for n in self.label_names)
                    yield f"{self.name}{_fmt_labels(self.label_names, key)} {_num(value)}"
            except Exception:
                pass
            return
        with self._lock:
            for key, v in sorted(self._values.items()):
                yield f"{self.name}{_fmt_labels(self.label_names, key)} {_num(v)}"


DEFAULT_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0
)


class Histogram(_Metric):
    def __init__(self, name, help_text="", label_names=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_text, tuple(label_names))
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def time(self, **labels):
        return _Timer(self, labels)

    def snapshot(self) -> dict:
        """{label_values_tuple: (cumulative_bucket_counts, total, sum)}
        — the quantile-derivation input (bucket counts are cumulative
        by construction of observe())."""
        with self._lock:
            return {
                key: (list(counts), self._totals[key], self._sums[key])
                for key, counts in self._counts.items()
            }

    def quantile(self, q: float, key: tuple) -> float:
        """Prometheus histogram_quantile-style estimate for one label
        set: linear interpolation inside the first bucket whose
        cumulative count covers rank q*total. Values beyond the last
        finite bucket clamp to it (same caveat as PromQL's +Inf)."""
        with self._lock:
            counts = list(self._counts.get(key) or ())
            total = self._totals.get(key, 0)
        if not counts or total <= 0:
            return 0.0
        return bucket_quantile(self.buckets, counts, total, q)

    def collect(self):
        yield f"# HELP {self.name} {_escape_help(self.help)}"
        yield f"# TYPE {self.name} histogram"
        with self._lock:
            for key in sorted(self._counts):
                for i, b in enumerate(self.buckets):
                    lbl = _fmt_labels(
                        self.label_names + ("le",), key + (_num(b),)
                    )
                    yield f"{self.name}_bucket{lbl} {self._counts[key][i]}"
                lbl = _fmt_labels(self.label_names + ("le",), key + ("+Inf",))
                yield f"{self.name}_bucket{lbl} {self._totals[key]}"
                base = _fmt_labels(self.label_names, key)
                yield f"{self.name}_sum{base} {_num(self._sums[key])}"
                yield f"{self.name}_count{base} {self._totals[key]}"


class _Timer:
    def __init__(self, hist: Histogram, labels: dict):
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self.t0, **self.labels)


class Registry:
    def __init__(self):
        self._metrics: list = []
        self._names: set[str] = set()
        self._lock = threading.Lock()

    def register(self, metric):
        # Duplicate names invalidate the whole exposition (Prometheus
        # rejects a scrape with two metric families of one name), so a
        # second registration is a programming error worth a loud,
        # immediate failure — not a silently corrupt /metrics page.
        with self._lock:
            if metric.name in self._names:
                raise ValueError(
                    f"metric {metric.name!r} is already registered; "
                    f"re-use the existing collector instead of "
                    f"registering a second one"
                )
            self._names.add(metric.name)
            self._metrics.append(metric)
        return metric

    def counter(self, name, help_text="", label_names=()):
        return self.register(Counter(name, help_text, label_names))

    def gauge(self, name, help_text="", label_names=(), fn=None):
        return self.register(Gauge(name, help_text, label_names, fn))

    def histogram(self, name, help_text="", label_names=(), buckets=DEFAULT_BUCKETS):
        return self.register(Histogram(name, help_text, label_names, buckets))

    def render(self) -> bytes:
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            lines.extend(m.collect())
        return ("\n".join(lines) + "\n").encode()


def _fmt_labels(names: Iterable[str], values: Iterable[str]) -> str:
    pairs = [
        f'{n}="{_escape(str(v))}"' for n, v in zip(names, values)
    ]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _escape(v: str) -> str:
    """Label-value escaping per the text exposition format: backslash,
    double-quote, and line feed (in that order — escaping the escape
    character first keeps the transform reversible)."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    """HELP-text escaping: backslash and line feed only (quotes are
    legal in help text; a raw newline would terminate the comment line
    and corrupt the exposition)."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def bucket_quantile(
    buckets: tuple, counts: list, total: int, q: float
) -> float:
    """Quantile from cumulative bucket counts (see Histogram.quantile).
    Pure function so the shell/SLO surfaces can derive p50/p99 from a
    scraped snapshot without a live Histogram."""
    q = min(max(q, 0.0), 1.0)
    rank = q * total
    prev_count = 0
    prev_le = 0.0
    for le, c in zip(buckets, counts):
        if c >= rank and c > prev_count:
            span = c - prev_count
            frac = (rank - prev_count) / span if span else 1.0
            return prev_le + (le - prev_le) * min(max(frac, 0.0), 1.0)
        # the interpolation base is the PREVIOUS bucket's bound even
        # when that bucket is empty (Prometheus histogram_quantile
        # semantics) — advancing only on non-empty buckets would bias
        # every quantile low when the low buckets are empty
        prev_count = c
        prev_le = le
    return buckets[-1] if buckets else 0.0


def slo_summary() -> dict:
    """Per-``server.op`` request-latency SLO snapshot derived from
    ``sw_request_seconds``: count, mean, p50/p90/p99 (ms). The payload
    of ``/debug/slo`` and the shell ``cluster.status`` SLO block."""
    out: dict[str, dict] = {}
    for key, (counts, total, s) in request_seconds.snapshot().items():
        labels = dict(zip(request_seconds.label_names, key))
        name = f"{labels.get('server', '')}.{labels.get('op', '')}"
        buckets = request_seconds.buckets
        out[name] = {
            "count": total,
            "mean_ms": round(s / total * 1000.0, 3) if total else 0.0,
            **{
                f"p{int(q * 100)}_ms": round(
                    bucket_quantile(buckets, counts, total, q) * 1000.0, 3
                )
                for q in (0.5, 0.9, 0.99)
            },
        }
    return out


def gateway_summary() -> dict:
    """Serving-path pressure snapshot for ``/debug/gateway``: per-tier
    hot-cache counters and per-server front-end inflight/rejected —
    the SLO-adjacent "why is p99 moving" surface next to /debug/slo."""
    hot: dict[str, dict] = {}
    for counter, kind in (
        (gateway_hot_cache_hits_total, "hits"),
        (gateway_hot_cache_misses_total, "misses"),
        (gateway_hot_cache_singleflight_waits_total, "singleflight_waits"),
    ):
        for (tier,), v in counter.snapshot().items():
            hot.setdefault(tier, {})[kind] = int(v)
    try:
        # chip residency ledger (budget/inflight/shed per tenant) —
        # lazy import: metrics must not pull the EC package at startup
        from ..ec.device_queue import residency_snapshot

        residency = residency_snapshot()
    except Exception:  # advisory; the debug page must never 500
        residency = {}
    return {
        "hot_cache": hot,
        "inflight": {
            srv: int(v) for (srv,), v in gateway_inflight.snapshot().items()
        },
        "rejected": {
            srv: int(v)
            for (srv,), v in gateway_rejected_total.snapshot().items()
        },
        "residency": residency,
    }


def _num(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


# process-wide default registry (the reference's stats.Gather equivalent)
REGISTRY = Registry()

request_total = REGISTRY.counter(
    "sw_request_total", "requests by server/op/code", ("server", "op", "code")
)
request_seconds = REGISTRY.histogram(
    "sw_request_seconds", "request latency", ("server", "op")
)
volume_count = REGISTRY.gauge(
    "sw_volumes", "volumes on this server", ("kind", "addr")
)
volume_bytes = REGISTRY.gauge(
    "sw_volume_bytes", "bytes stored", ("kind", "addr")
)
ec_ops_total = REGISTRY.counter(
    "sw_ec_ops_total", "EC operations", ("op", "backend")
)
ec_bytes_total = REGISTRY.counter(
    "sw_ec_bytes_total", "bytes through the EC pipeline", ("op", "backend")
)
ec_leaf_repairs_total = REGISTRY.counter(
    "sw_ec_leaf_repairs_total",
    "leaf-granular in-place EC shard repairs by outcome "
    "(repaired/refused/failed)",
    ("outcome",),
)
ec_repair_journal_total = REGISTRY.counter(
    "sw_ec_repair_journal_total",
    "repair-journal recovery actions (replayed/rolled_back/kept/swept)",
    ("action",),
)

# Gateway serving path (ISSUE 11): the hot-object/chunk read-through
# cache tiers (tier = filer_chunk | ec_interval) and the bounded
# worker-pool HTTP front ends (server = s3 | filer | volume).
gateway_hot_cache_hits_total = REGISTRY.counter(
    "sw_gateway_hot_cache_hits_total",
    "hot-cache hits on the gateway read path", ("tier",)
)
gateway_hot_cache_misses_total = REGISTRY.counter(
    "sw_gateway_hot_cache_misses_total",
    "hot-cache misses on the gateway read path", ("tier",)
)
gateway_hot_cache_singleflight_waits_total = REGISTRY.counter(
    "sw_gateway_hot_cache_singleflight_waits_total",
    "concurrent misses that joined another caller's in-flight load "
    "instead of re-running it",
    ("tier",),
)
gateway_inflight = REGISTRY.gauge(
    "sw_gateway_inflight",
    "HTTP requests currently being handled by the worker pool",
    ("server",),
)
gateway_rejected_total = REGISTRY.counter(
    "sw_gateway_rejected_total",
    "connections refused with 503 because the worker pool + accept "
    "queue were saturated",
    ("server",),
)

# Network byte plane (ISSUE 12): payload bytes over the wire per plane
# (native = sendfile/writev/recv-into with the GIL released; python =
# the bit-identical fallback through Python buffers). The copied
# counter tracks payload bytes MATERIALIZED into Python-level buffers
# at the instrumented seams (gRPC chunk joins, wfile writes, pread
# bytes) — bytes_copied_per_byte_served in bench.py is
# copied(plane) / served(plane), ~0 for the native plane.
# `direction` (ISSUE 18) splits the read-serving path from the write
# path (needle/blob WRITE opcode, replica fan-out, stream-shard push)
# so the copies-per-byte derivation covers PUTs too.
net_bytes_sent_total = REGISTRY.counter(
    "sw_net_bytes_sent_total",
    "payload bytes sent on the network byte path (shard net plane, "
    "EC shard-read RPC, gateway HTTP body egress, write-opcode egress)",
    ("plane", "direction"),
)
net_bytes_received_total = REGISTRY.counter(
    "sw_net_bytes_received_total",
    "payload bytes landed from the network byte path (peer-fetch "
    "ingress, write-opcode landing)",
    ("plane", "direction"),
)
net_bytes_copied_total = REGISTRY.counter(
    "sw_net_bytes_copied_total",
    "payload bytes materialized into Python-level buffers on the "
    "network byte path (the bytes-copied-per-byte-served numerator)",
    ("plane", "direction"),
)

mq_produce_bytes_total = REGISTRY.counter(
    "sw_mq_produce_bytes_total",
    "record-batch bytes accepted by the Kafka gateway produce path",
    ("plane",),
)
mq_fetch_bytes_total = REGISTRY.counter(
    "sw_mq_fetch_bytes_total",
    "fetch-response payload bytes served by the Kafka gateway, by "
    "egress plane (native = sn_sendv/sn_send_file, python = fallback)",
    ("plane",),
)
mq_group_commit_windows_total = REGISTRY.counter(
    "sw_mq_group_commit_windows_total",
    "broker group-commit flush windows completed",
)

# Warm-path control plane (ISSUE 13): SigV4 verdict-memo outcomes on
# header-auth requests. hit = the full canonical-request + HMAC chain
# was skipped (freshness/identity/session-token still re-checked);
# bypass = presigned or streaming auth, or the memo is disabled.
s3_auth_memo_total = REGISTRY.counter(
    "sw_s3_auth_memo_total",
    "SigV4 verdict-memo outcomes (hit/miss/bypass) on the S3 auth path",
    ("result",),
)
