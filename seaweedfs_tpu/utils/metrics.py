"""Minimal Prometheus-style metrics registry (text exposition format).

Reference: weed/stats/metrics.go (~80 collectors over master/filer/
volume/S3, pull via /metrics or push). Stdlib-only: counters, gauges,
histograms with labels, rendered in the text format Prometheus scrapes.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable


class _Metric:
    def __init__(self, name: str, help_text: str, label_names: tuple[str, ...]):
        self.name = name
        self.help = help_text
        self.label_names = label_names
        self._lock = threading.Lock()


class Counter(_Metric):
    def __init__(self, name, help_text="", label_names=()):
        super().__init__(name, help_text, tuple(label_names))
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def collect(self):
        yield f"# HELP {self.name} {_escape_help(self.help)}"
        yield f"# TYPE {self.name} counter"
        with self._lock:
            for key, v in sorted(self._values.items()):
                yield f"{self.name}{_fmt_labels(self.label_names, key)} {_num(v)}"


class Gauge(_Metric):
    def __init__(self, name, help_text="", label_names=(), fn: Callable | None = None):
        super().__init__(name, help_text, tuple(label_names))
        self._values: dict[tuple, float] = {}
        self._fn = fn  # callback gauges sample at scrape time

    def set(self, value: float, **labels) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._values[key] = value

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def collect(self):
        yield f"# HELP {self.name} {_escape_help(self.help)}"
        yield f"# TYPE {self.name} gauge"
        if self._fn is not None:
            try:
                for labels, value in self._fn():
                    key = tuple(labels.get(n, "") for n in self.label_names)
                    yield f"{self.name}{_fmt_labels(self.label_names, key)} {_num(value)}"
            except Exception:
                pass
            return
        with self._lock:
            for key, v in sorted(self._values.items()):
                yield f"{self.name}{_fmt_labels(self.label_names, key)} {_num(v)}"


DEFAULT_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0
)


class Histogram(_Metric):
    def __init__(self, name, help_text="", label_names=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_text, tuple(label_names))
        self.buckets = tuple(sorted(buckets))
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}

    def observe(self, value: float, **labels) -> None:
        key = tuple(labels.get(n, "") for n in self.label_names)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def time(self, **labels):
        return _Timer(self, labels)

    def collect(self):
        yield f"# HELP {self.name} {_escape_help(self.help)}"
        yield f"# TYPE {self.name} histogram"
        with self._lock:
            for key in sorted(self._counts):
                for i, b in enumerate(self.buckets):
                    lbl = _fmt_labels(
                        self.label_names + ("le",), key + (_num(b),)
                    )
                    yield f"{self.name}_bucket{lbl} {self._counts[key][i]}"
                lbl = _fmt_labels(self.label_names + ("le",), key + ("+Inf",))
                yield f"{self.name}_bucket{lbl} {self._totals[key]}"
                base = _fmt_labels(self.label_names, key)
                yield f"{self.name}_sum{base} {_num(self._sums[key])}"
                yield f"{self.name}_count{base} {self._totals[key]}"


class _Timer:
    def __init__(self, hist: Histogram, labels: dict):
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self.t0, **self.labels)


class Registry:
    def __init__(self):
        self._metrics: list = []
        self._names: set[str] = set()
        self._lock = threading.Lock()

    def register(self, metric):
        # Duplicate names invalidate the whole exposition (Prometheus
        # rejects a scrape with two metric families of one name), so a
        # second registration is a programming error worth a loud,
        # immediate failure — not a silently corrupt /metrics page.
        with self._lock:
            if metric.name in self._names:
                raise ValueError(
                    f"metric {metric.name!r} is already registered; "
                    f"re-use the existing collector instead of "
                    f"registering a second one"
                )
            self._names.add(metric.name)
            self._metrics.append(metric)
        return metric

    def counter(self, name, help_text="", label_names=()):
        return self.register(Counter(name, help_text, label_names))

    def gauge(self, name, help_text="", label_names=(), fn=None):
        return self.register(Gauge(name, help_text, label_names, fn))

    def histogram(self, name, help_text="", label_names=(), buckets=DEFAULT_BUCKETS):
        return self.register(Histogram(name, help_text, label_names, buckets))

    def render(self) -> bytes:
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            lines.extend(m.collect())
        return ("\n".join(lines) + "\n").encode()


def _fmt_labels(names: Iterable[str], values: Iterable[str]) -> str:
    pairs = [
        f'{n}="{_escape(str(v))}"' for n, v in zip(names, values)
    ]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _escape(v: str) -> str:
    """Label-value escaping per the text exposition format: backslash,
    double-quote, and line feed (in that order — escaping the escape
    character first keeps the transform reversible)."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    """HELP-text escaping: backslash and line feed only (quotes are
    legal in help text; a raw newline would terminate the comment line
    and corrupt the exposition)."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _num(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


# process-wide default registry (the reference's stats.Gather equivalent)
REGISTRY = Registry()

request_total = REGISTRY.counter(
    "sw_request_total", "requests by server/op/code", ("server", "op", "code")
)
request_seconds = REGISTRY.histogram(
    "sw_request_seconds", "request latency", ("server", "op")
)
volume_count = REGISTRY.gauge(
    "sw_volumes", "volumes on this server", ("kind", "addr")
)
volume_bytes = REGISTRY.gauge(
    "sw_volume_bytes", "bytes stored", ("kind", "addr")
)
ec_ops_total = REGISTRY.counter(
    "sw_ec_ops_total", "EC operations", ("op", "backend")
)
ec_bytes_total = REGISTRY.counter(
    "sw_ec_bytes_total", "bytes through the EC pipeline", ("op", "backend")
)
ec_leaf_repairs_total = REGISTRY.counter(
    "sw_ec_leaf_repairs_total",
    "leaf-granular in-place EC shard repairs by outcome "
    "(repaired/refused/failed)",
    ("outcome",),
)
ec_repair_journal_total = REGISTRY.counter(
    "sw_ec_repair_journal_total",
    "repair-journal recovery actions (replayed/rolled_back/kept/swept)",
    ("action",),
)
