"""Durability helpers: the fsync + atomic-rename + dir-fsync discipline
the reference applies to every published artifact (ec_decoder.go:44-90,
volume_vacuum.go:228)."""

from __future__ import annotations

import os


def fsync_dir(path: str) -> None:
    """fsync the directory containing `path` so renames survive power loss."""
    dirfd = os.open(os.path.dirname(os.path.abspath(path)) or ".", os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)


def atomic_write(path: str, data: bytes) -> None:
    """Write-temp + fsync + rename + dir-fsync publication."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
