"""CRC32C (Castagnoli) — needle checksums and the .ecsum bitrot sidecar.

The reference uses CRC32-Castagnoli for both needle checksums and the
per-shard-block bitrot sums (weed/storage/needle/crc.go,
weed/storage/erasure_coding/ec_bitrot.go). Uses the C++ native core
(native/libseaweed_native.so, hardware CRC32C when available) and falls
back to a numpy slice-by-8 table implementation.
"""

from __future__ import annotations

import functools

import numpy as np

CASTAGNOLI_POLY = 0x82F63B78  # reflected


def _make_tables(n: int = 8) -> np.ndarray:
    t = np.zeros((n, 256), dtype=np.uint32)
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (CASTAGNOLI_POLY if crc & 1 else 0)
        t[0, i] = crc
    for k in range(1, n):
        for i in range(256):
            t[k, i] = (t[k - 1, i] >> 8) ^ t[0, t[k - 1, i] & 0xFF]
    return t


_TABLES = _make_tables()


def _crc32c_py(data: bytes | np.ndarray, crc: int = 0) -> int:
    """Slice-by-8 in a python loop over 8-byte strides (fallback path)."""
    buf = np.frombuffer(bytes(data), dtype=np.uint8)
    crc = (~crc) & 0xFFFFFFFF
    t = _TABLES
    n = len(buf)
    i = 0
    # process unaligned prefix bytewise
    while i < n and i % 8 != 0:
        crc = (crc >> 8) ^ int(t[0, (crc ^ buf[i]) & 0xFF])
        i += 1
    n8 = (n - i) // 8
    if n8:
        words = buf[i : i + n8 * 8].reshape(n8, 8)
        for row in words:
            w = crc ^ int(row[0]) ^ (int(row[1]) << 8) ^ (int(row[2]) << 16) ^ (
                int(row[3]) << 24
            )
            crc = (
                int(t[7, w & 0xFF])
                ^ int(t[6, (w >> 8) & 0xFF])
                ^ int(t[5, (w >> 16) & 0xFF])
                ^ int(t[4, (w >> 24) & 0xFF])
                ^ int(t[3, int(row[4])])
                ^ int(t[2, int(row[5])])
                ^ int(t[1, int(row[6])])
                ^ int(t[0, int(row[7])])
            )
        i += n8 * 8
    while i < n:
        crc = (crc >> 8) ^ int(t[0, (crc ^ int(buf[i])) & 0xFF])
        i += 1
    return (~crc) & 0xFFFFFFFF


_native_crc = None


def _load_native():
    global _native_crc
    if _native_crc is None:
        try:
            from . import native

            _native_crc = native.crc32c
        except Exception:
            _native_crc = False
    return _native_crc


def crc32c(data, crc: int = 0) -> int:
    """CRC32C of `data`, optionally continuing from a previous value."""
    fn = _load_native()
    if fn:
        return fn(data, crc)
    return _crc32c_py(data, crc)


# ---------------------------------------------------------------- combine
#
# crc32c(A || B) from crc32c(A), crc32c(B), len(B) without touching the
# bytes (zlib's crc32_combine GF(2) matrix method, Castagnoli polynomial).
# Lets the .ecsum v2 sidecar derive block-level CRCs from its per-leaf
# CRCs in one pass: each leaf is checksummed independently while
# cache-hot, and the 16 MiB block CRC is folded from the leaf CRCs in
# O(leaves * 32) XORs instead of re-reading the block.


def _gf2_matrix_times(mat: list[int], vec: int) -> int:
    out = 0
    i = 0
    while vec:
        if vec & 1:
            out ^= mat[i]
        vec >>= 1
        i += 1
    return out


def _gf2_matrix_square(mat: list[int]) -> list[int]:
    return [_gf2_matrix_times(mat, mat[n]) for n in range(32)]


def _gf2_matrix_mul(a: list[int], b: list[int]) -> list[int]:
    """Operator composition: (a∘b)[n] = a * b[n] (columns are uint32)."""
    return [_gf2_matrix_times(a, b[n]) for n in range(32)]


@functools.lru_cache(maxsize=64)
def _zero_operator(nbytes: int) -> tuple[int, ...]:
    """32x32 GF(2) matrix advancing a finalized CRC32C over `nbytes`
    zero bytes. Cached per length: .ecsum leaves are uniform-size, so a
    whole sidecar's combines reuse one or two cached operators."""
    odd = [0] * 32
    odd[0] = CASTAGNOLI_POLY  # one zero BIT, reflected form
    row = 1
    for n in range(1, 32):
        odd[n] = row
        row <<= 1
    even = _gf2_matrix_square(odd)  # 2 bits
    odd = _gf2_matrix_square(even)  # 4 bits
    mat = odd
    op: list[int] | None = None
    n = nbytes
    while n:
        mat = _gf2_matrix_square(mat)  # 8 bits = 1 byte, then doubling
        if n & 1:
            op = list(mat) if op is None else _gf2_matrix_mul(mat, op)
        n >>= 1
    assert op is not None  # nbytes > 0 guaranteed by caller
    return tuple(op)


def crc32c_combine(crc1: int, crc2: int, len2: int) -> int:
    """crc32c of the concatenation of two streams whose individual
    (finalized) CRCs are crc1 and crc2, where the second stream is
    `len2` bytes long."""
    if len2 <= 0:
        return crc1
    return _gf2_matrix_times(list(_zero_operator(len2)), crc1) ^ crc2
