"""CRC32C (Castagnoli) — needle checksums and the .ecsum bitrot sidecar.

The reference uses CRC32-Castagnoli for both needle checksums and the
per-shard-block bitrot sums (weed/storage/needle/crc.go,
weed/storage/erasure_coding/ec_bitrot.go). Uses the C++ native core
(native/libseaweed_native.so, hardware CRC32C when available) and falls
back to a numpy slice-by-8 table implementation.
"""

from __future__ import annotations

import numpy as np

CASTAGNOLI_POLY = 0x82F63B78  # reflected


def _make_tables(n: int = 8) -> np.ndarray:
    t = np.zeros((n, 256), dtype=np.uint32)
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (CASTAGNOLI_POLY if crc & 1 else 0)
        t[0, i] = crc
    for k in range(1, n):
        for i in range(256):
            t[k, i] = (t[k - 1, i] >> 8) ^ t[0, t[k - 1, i] & 0xFF]
    return t


_TABLES = _make_tables()


def _crc32c_py(data: bytes | np.ndarray, crc: int = 0) -> int:
    """Slice-by-8 in a python loop over 8-byte strides (fallback path)."""
    buf = np.frombuffer(bytes(data), dtype=np.uint8)
    crc = (~crc) & 0xFFFFFFFF
    t = _TABLES
    n = len(buf)
    i = 0
    # process unaligned prefix bytewise
    while i < n and i % 8 != 0:
        crc = (crc >> 8) ^ int(t[0, (crc ^ buf[i]) & 0xFF])
        i += 1
    n8 = (n - i) // 8
    if n8:
        words = buf[i : i + n8 * 8].reshape(n8, 8)
        for row in words:
            w = crc ^ int(row[0]) ^ (int(row[1]) << 8) ^ (int(row[2]) << 16) ^ (
                int(row[3]) << 24
            )
            crc = (
                int(t[7, w & 0xFF])
                ^ int(t[6, (w >> 8) & 0xFF])
                ^ int(t[5, (w >> 16) & 0xFF])
                ^ int(t[4, (w >> 24) & 0xFF])
                ^ int(t[3, int(row[4])])
                ^ int(t[2, int(row[5])])
                ^ int(t[1, int(row[6])])
                ^ int(t[0, int(row[7])])
            )
        i += n8 * 8
    while i < n:
        crc = (crc >> 8) ^ int(t[0, (crc ^ int(buf[i])) & 0xFF])
        i += 1
    return (~crc) & 0xFFFFFFFF


_native_crc = None


def _load_native():
    global _native_crc
    if _native_crc is None:
        try:
            from . import native

            _native_crc = native.crc32c
        except Exception:
            _native_crc = False
    return _native_crc


def crc32c(data, crc: int = 0) -> int:
    """CRC32C of `data`, optionally continuing from a previous value."""
    fn = _load_native()
    if fn:
        return fn(data, crc)
    return _crc32c_py(data, crc)
