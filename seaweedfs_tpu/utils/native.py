"""ctypes loader for the C++ native core (native/libseaweed_native.so).

Builds on first use if the shared object is missing (make in native/).
All callers must tolerate ImportError and fall back to pure Python —
the native core is an accelerator, not a dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_SO_PATH = os.path.join(_NATIVE_DIR, "libseaweed_native.so")


def _build() -> None:
    subprocess.run(
        ["make", "-s", "-C", _NATIVE_DIR], check=True, capture_output=True
    )


def _stale() -> bool:
    """Rebuild when sources are newer than the .so — a stale library
    missing newly-added symbols would otherwise fail the whole module
    import and silently disable ALL native acceleration."""
    if not os.path.exists(_SO_PATH):
        return True
    so_mtime = os.path.getmtime(_SO_PATH)
    for src in ("seaweed_native.cpp", "Makefile"):
        p = os.path.join(_NATIVE_DIR, src)
        if os.path.exists(p) and os.path.getmtime(p) > so_mtime:
            return True
    return False


if _stale():
    _build()

_lib = ctypes.CDLL(_SO_PATH)

_lib.sn_crc32c.restype = ctypes.c_uint32
_lib.sn_crc32c.argtypes = [ctypes.c_uint32, ctypes.c_void_p, ctypes.c_size_t]
_lib.sn_rs_apply.restype = None
_lib.sn_rs_apply.argtypes = [
    ctypes.c_char_p,
    ctypes.c_int,
    ctypes.c_int,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_size_t,
]
_lib.sn_gf_mul.restype = ctypes.c_uint8
_lib.sn_gf_mul.argtypes = [ctypes.c_uint8, ctypes.c_uint8]
_lib.sn_rs_apply_mt.restype = None
_lib.sn_rs_apply_mt.argtypes = [
    ctypes.c_char_p,
    ctypes.c_int,
    ctypes.c_int,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_size_t,
    ctypes.c_int,
]
_lib.sn_shard_append.restype = ctypes.c_int
_lib.sn_shard_append.argtypes = [
    ctypes.POINTER(ctypes.c_int),
    ctypes.POINTER(ctypes.c_void_p),
    ctypes.c_int,
    ctypes.c_size_t,
    ctypes.c_uint32,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_int32,
]
_lib.sn_has_avx2.restype = ctypes.c_int
_lib.sn_scan_dat.restype = ctypes.c_int64
_lib.sn_scan_dat.argtypes = [
    ctypes.c_char_p,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_int64,
]


def crc32c(data, crc: int = 0) -> int:
    """Zero-copy over bytes/ndarray/memoryview/bytearray (buffer protocol)."""
    if isinstance(data, bytes):
        return _lib.sn_crc32c(crc, data, len(data))
    if not isinstance(data, np.ndarray):
        data = np.frombuffer(data, dtype=np.uint8)  # zero-copy view
    data = np.ascontiguousarray(data)
    return _lib.sn_crc32c(
        crc, ctypes.c_void_p(data.ctypes.data), data.nbytes
    )


def rs_apply(coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
    """out[r] = XOR_j gf_mul(coeffs[r,j], data[j]) over contiguous rows."""
    coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    out_rows, in_rows = coeffs.shape
    if data.shape[0] != in_rows:
        raise ValueError(f"coeffs expect {in_rows} rows, got {data.shape[0]}")
    n = data.shape[1]
    out = np.empty((out_rows, n), dtype=np.uint8)
    _lib.sn_rs_apply(
        coeffs.tobytes(),
        out_rows,
        in_rows,
        data.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        n,
    )
    return out


def rs_apply_mt(coeffs: np.ndarray, data: np.ndarray, threads: int = 0) -> np.ndarray:
    """rs_apply with columns split across `threads` workers (0 = all cores).
    Bit-exact vs rs_apply: parity is columnwise-independent."""
    coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    out_rows, in_rows = coeffs.shape
    if data.shape[0] != in_rows:
        raise ValueError(f"coeffs expect {in_rows} rows, got {data.shape[0]}")
    if threads <= 0:
        threads = os.cpu_count() or 1
    n = data.shape[1]
    out = np.empty((out_rows, n), dtype=np.uint8)
    _lib.sn_rs_apply_mt(
        coeffs.tobytes(),
        out_rows,
        in_rows,
        data.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        n,
        threads,
    )
    return out


def shard_append(
    fds: list[int],
    row_ptrs: list[int],
    width: int,
    block_size: int,
    crc_state: np.ndarray,
    filled_state: np.ndarray,
    out_crcs: np.ndarray,
    out_counts: np.ndarray,
) -> None:
    """Fused batch append: write `width` bytes from row_ptrs[i] to fds[i]
    and roll shard i's block-CRC32C state — one GIL-releasing call per
    batch, a worker thread per shard, no Python-side copies.

    crc_state (u32[n]) / filled_state (u64[n]) carry across calls;
    completed block CRCs land in out_crcs (u32[n, max_out]) with counts
    in out_counts (i32[n]). Raises OSError on any shard write failure.
    """
    n = len(fds)
    assert len(row_ptrs) == n
    assert crc_state.dtype == np.uint32 and filled_state.dtype == np.uint64
    assert out_crcs.dtype == np.uint32 and out_crcs.flags.c_contiguous
    assert out_counts.dtype == np.int32
    rc = _lib.sn_shard_append(
        (ctypes.c_int * n)(*fds),
        (ctypes.c_void_p * n)(*row_ptrs),
        n,
        width,
        block_size,
        ctypes.c_void_p(crc_state.ctypes.data),
        ctypes.c_void_p(filled_state.ctypes.data),
        ctypes.c_void_p(out_crcs.ctypes.data),
        ctypes.c_void_p(out_counts.ctypes.data),
        out_crcs.shape[1],
    )
    if rc != 0:
        raise OSError(f"sn_shard_append failed on shard {-rc - 1}")


def gf_mul(a: int, b: int) -> int:
    return _lib.sn_gf_mul(a, b)


def has_avx2() -> bool:
    return bool(_lib.sn_has_avx2())


def scan_dat(path: str):
    """Fast .dat scan: -> (ids u64, offsets u32 [8-byte units],
    body_sizes i32, crc_ok u8) parallel arrays, append order.
    Raises OSError on unreadable/short files."""
    import os

    size = os.path.getsize(path)
    max_entries = max(size // 24 + 2, 16)  # min padded record is 24 bytes (v2 tombstone)
    ids = np.empty(max_entries, dtype=np.uint64)
    offsets = np.empty(max_entries, dtype=np.uint32)
    sizes = np.empty(max_entries, dtype=np.int32)
    crc_ok = np.empty(max_entries, dtype=np.uint8)
    n = _lib.sn_scan_dat(
        path.encode(),
        ids.ctypes.data_as(ctypes.c_void_p),
        offsets.ctypes.data_as(ctypes.c_void_p),
        sizes.ctypes.data_as(ctypes.c_void_p),
        crc_ok.ctypes.data_as(ctypes.c_void_p),
        max_entries,
    )
    if n < 0:
        raise OSError(f"sn_scan_dat({path}) failed: {n}")
    return ids[:n], offsets[:n], sizes[:n], crc_ok[:n].astype(bool)
