"""ctypes loader for the C++ native core (native/libseaweed_native.so).

Builds on first use if the shared object is missing (make in native/).
All callers must tolerate ImportError and fall back to pure Python —
the native core is an accelerator, not a dependency.
"""

from __future__ import annotations

import ctypes
import glob as _glob
import os
import subprocess

import numpy as np

_NATIVE_DIR = os.environ.get(
    "SEAWEED_NATIVE_DIR",
    os.path.join(
        os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ),
        "native",
    ),
)
_SO_PATH = os.path.join(_NATIVE_DIR, "libseaweed_native.so")


def _build() -> None:
    subprocess.run(
        ["make", "-s", "-C", _NATIVE_DIR], check=True, capture_output=True
    )


def _stale() -> bool:
    """Rebuild when sources are newer than the .so — a stale library
    missing newly-added symbols would otherwise fail the whole module
    import and silently disable ALL native acceleration. The source set
    is derived from the directory (every .cpp/.h plus the Makefile), not
    a hardcoded list, so adding a source file triggers rebuilds too."""
    if not os.path.exists(_SO_PATH):
        return True
    so_mtime = os.path.getmtime(_SO_PATH)
    sources = [os.path.join(_NATIVE_DIR, "Makefile")]
    for pat in ("*.cpp", "*.cc", "*.h", "*.hpp"):
        sources.extend(_glob.glob(os.path.join(_NATIVE_DIR, pat)))
    for p in sources:
        if os.path.exists(p) and os.path.getmtime(p) > so_mtime:
            return True
    return False


# Load contract: every caller is documented to tolerate ImportError and
# fall back to pure Python. A missing C++ toolchain surfaces as
# subprocess.CalledProcessError from make, a bad .so as OSError from
# CDLL — both would otherwise escape import and crash callers that
# correctly guard with `except ImportError`. Wrap them so the fallback
# actually engages; the original failure rides along as __cause__.
try:
    if _stale():
        _build()
    _lib = ctypes.CDLL(_SO_PATH)
except (OSError, subprocess.CalledProcessError) as e:
    detail = e
    if isinstance(e, subprocess.CalledProcessError) and e.stderr:
        detail = e.stderr.decode(errors="replace")[-500:]
    raise ImportError(
        f"native core unavailable (build or load of {_SO_PATH} failed): "
        f"{detail}"
    ) from e

_lib.sn_crc32c.restype = ctypes.c_uint32
_lib.sn_crc32c.argtypes = [ctypes.c_uint32, ctypes.c_void_p, ctypes.c_size_t]
_lib.sn_rs_apply.restype = None
_lib.sn_rs_apply.argtypes = [
    ctypes.c_char_p,
    ctypes.c_int,
    ctypes.c_int,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_size_t,
]
_lib.sn_gf_mul.restype = ctypes.c_uint8
_lib.sn_gf_mul.argtypes = [ctypes.c_uint8, ctypes.c_uint8]
_lib.sn_rs_apply_mt.restype = None
_lib.sn_rs_apply_mt.argtypes = [
    ctypes.c_char_p,
    ctypes.c_int,
    ctypes.c_int,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_size_t,
    ctypes.c_int,
]
_lib.sn_shard_append.restype = ctypes.c_int
_lib.sn_shard_append.argtypes = [
    ctypes.POINTER(ctypes.c_int),
    ctypes.POINTER(ctypes.c_void_p),
    ctypes.c_int,
    ctypes.c_size_t,
    ctypes.c_uint32,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_int32,
]
_lib.sn_batch_pread.restype = ctypes.c_int
_lib.sn_batch_pread.argtypes = [
    ctypes.POINTER(ctypes.c_int),     # fds
    ctypes.POINTER(ctypes.c_uint64),  # offsets
    ctypes.c_int,                     # nrows
    ctypes.c_void_p,                  # dst
    ctypes.c_size_t,                  # width
    ctypes.c_size_t,                  # stride
    ctypes.c_int,                     # pad_eof
    ctypes.c_uint32,                  # granule
    ctypes.c_void_p,                  # crc_state
    ctypes.c_void_p,                  # filled_state
    ctypes.c_void_p,                  # out_crcs
    ctypes.c_void_p,                  # out_counts
    ctypes.c_int32,                   # max_out
]
_lib.sn_fadvise_willneed.restype = ctypes.c_int
_lib.sn_fadvise_willneed.argtypes = [
    ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64,
]
_lib.sn_crc32c_combine.restype = ctypes.c_uint32
_lib.sn_crc32c_combine.argtypes = [
    ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint64,
]
_lib.sn_sink_create.restype = ctypes.c_void_p
_lib.sn_sink_create.argtypes = [
    ctypes.POINTER(ctypes.c_int),
    ctypes.c_int,
    ctypes.c_uint32,
    ctypes.c_uint32,
    ctypes.c_uint32,
]
_lib.sn_sink_append.restype = ctypes.c_int
_lib.sn_sink_append.argtypes = [
    ctypes.c_void_p,                   # handle
    ctypes.POINTER(ctypes.c_void_p),   # rows
    ctypes.c_size_t,                   # width
    ctypes.c_void_p,                   # out_block_crcs
    ctypes.c_void_p,                   # out_block_counts
    ctypes.c_void_p,                   # out_leaf_crcs
    ctypes.c_void_p,                   # out_leaf_counts
    ctypes.c_int32,                    # max_out
]
_lib.sn_sink_finish.restype = ctypes.c_int
_lib.sn_sink_finish.argtypes = [
    ctypes.c_void_p,
    ctypes.c_void_p,  # tail_block_crc (u32[n])
    ctypes.c_void_p,  # tail_block_valid (u8[n])
    ctypes.c_void_p,  # tail_leaf_crc (u32[n])
    ctypes.c_void_p,  # tail_leaf_valid (u8[n])
    ctypes.c_void_p,  # sizes (u64[n])
]
_lib.sn_sink_destroy.restype = None
_lib.sn_sink_destroy.argtypes = [ctypes.c_void_p]
# Network byte plane (ISSUE 12): socket egress/ingress with the GIL
# released for the whole transfer. A stale .so missing these symbols
# fails HERE at import (AttributeError -> ImportError below would not
# catch it, which is deliberate: _stale() rebuilds first, and the
# tier-1 symbol gate in tests/test_native_plane.py asserts the ABI).
_lib.sn_send_file.restype = ctypes.c_int64
_lib.sn_send_file.argtypes = [
    ctypes.c_int,     # out_fd (socket)
    ctypes.c_int,     # in_fd (file)
    ctypes.c_uint64,  # offset
    ctypes.c_uint64,  # len
    ctypes.c_int,     # timeout_ms (-1 = block)
]
_lib.sn_sendv.restype = ctypes.c_int64
_lib.sn_sendv.argtypes = [
    ctypes.c_int,
    ctypes.POINTER(ctypes.c_void_p),  # bufs
    ctypes.POINTER(ctypes.c_uint64),  # lens
    ctypes.c_int,                     # n
    ctypes.c_int,                     # timeout_ms
]
_lib.sn_recv_into.restype = ctypes.c_int64
_lib.sn_recv_into.argtypes = [
    ctypes.c_int,     # fd
    ctypes.c_void_p,  # dst
    ctypes.c_uint64,  # len
    ctypes.c_int,     # timeout_ms
    ctypes.c_uint32,  # granule
    ctypes.c_void_p,  # crc_state (u32[1])
    ctypes.c_void_p,  # filled_state (u64[1])
    ctypes.c_void_p,  # out_crcs (u32[max_out])
    ctypes.c_void_p,  # out_count (i32[1])
    ctypes.c_int32,   # max_out
    ctypes.c_int32,   # overlap_mode (0 serial / 1 overlap / -1 auto)
]
_lib.sn_recv_overlap_active.restype = ctypes.c_int
_lib.sn_recv_overlap_active.argtypes = [ctypes.c_uint64]
# Write-opcode blob landing (ISSUE 18): socket -> disk with the CRC
# fused into the bounce-buffer loop. Guarded so a prebuilt .so from an
# older tree (no toolchain to rebuild) degrades to the Python landing
# instead of failing the whole module import.
try:
    _lib.sn_recv_file.restype = ctypes.c_int64
    _lib.sn_recv_file.argtypes = [
        ctypes.c_int,     # fd (socket)
        ctypes.c_int,     # out_fd (file)
        ctypes.c_uint64,  # offset
        ctypes.c_uint64,  # len
        ctypes.c_int,     # timeout_ms
        ctypes.c_void_p,  # crc_out (u32[1])
    ]
    _HAS_RECV_FILE = True
except AttributeError:  # pragma: no cover - stale prebuilt .so
    _HAS_RECV_FILE = False
_lib.sn_sink_direct_flags.restype = ctypes.c_int
_lib.sn_sink_direct_flags.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
_lib.sn_has_avx2.restype = ctypes.c_int
_lib.sn_scan_dat.restype = ctypes.c_int64
_lib.sn_scan_dat.argtypes = [
    ctypes.c_char_p,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_void_p,
    ctypes.c_int64,
]


def crc32c(data, crc: int = 0) -> int:
    """Zero-copy over bytes/ndarray/memoryview/bytearray (buffer protocol)."""
    if isinstance(data, bytes):
        return _lib.sn_crc32c(crc, data, len(data))
    if not isinstance(data, np.ndarray):
        data = np.frombuffer(data, dtype=np.uint8)  # zero-copy view
    data = np.ascontiguousarray(data)
    return _lib.sn_crc32c(
        crc, ctypes.c_void_p(data.ctypes.data), data.nbytes
    )


def rs_apply(coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
    """out[r] = XOR_j gf_mul(coeffs[r,j], data[j]) over contiguous rows."""
    coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    out_rows, in_rows = coeffs.shape
    if data.shape[0] != in_rows:
        raise ValueError(f"coeffs expect {in_rows} rows, got {data.shape[0]}")
    n = data.shape[1]
    out = np.empty((out_rows, n), dtype=np.uint8)
    _lib.sn_rs_apply(
        coeffs.tobytes(),
        out_rows,
        in_rows,
        data.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        n,
    )
    return out


def rs_apply_mt(coeffs: np.ndarray, data: np.ndarray, threads: int = 0) -> np.ndarray:
    """rs_apply with columns split across `threads` workers (0 = all cores).
    Bit-exact vs rs_apply: parity is columnwise-independent."""
    coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    out_rows, in_rows = coeffs.shape
    if data.shape[0] != in_rows:
        raise ValueError(f"coeffs expect {in_rows} rows, got {data.shape[0]}")
    if threads <= 0:
        threads = os.cpu_count() or 1
    n = data.shape[1]
    out = np.empty((out_rows, n), dtype=np.uint8)
    _lib.sn_rs_apply_mt(
        coeffs.tobytes(),
        out_rows,
        in_rows,
        data.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        n,
        threads,
    )
    return out


def shard_append(
    fds: list[int],
    row_ptrs: list[int],
    width: int,
    block_size: int,
    crc_state: np.ndarray,
    filled_state: np.ndarray,
    out_crcs: np.ndarray,
    out_counts: np.ndarray,
) -> None:
    """Fused batch append: write `width` bytes from row_ptrs[i] to fds[i]
    and roll shard i's block-CRC32C state — one GIL-releasing call per
    batch, a worker thread per shard, no Python-side copies.

    crc_state (u32[n]) / filled_state (u64[n]) carry across calls;
    completed block CRCs land in out_crcs (u32[n, max_out]) with counts
    in out_counts (i32[n]). Raises OSError on any shard write failure.
    """
    n = len(fds)
    assert len(row_ptrs) == n
    assert crc_state.dtype == np.uint32 and filled_state.dtype == np.uint64
    assert out_crcs.dtype == np.uint32 and out_crcs.flags.c_contiguous
    assert out_counts.dtype == np.int32
    rc = _lib.sn_shard_append(
        (ctypes.c_int * n)(*fds),
        (ctypes.c_void_p * n)(*row_ptrs),
        n,
        width,
        block_size,
        ctypes.c_void_p(crc_state.ctypes.data),
        ctypes.c_void_p(filled_state.ctypes.data),
        ctypes.c_void_p(out_crcs.ctypes.data),
        ctypes.c_void_p(out_counts.ctypes.data),
        out_crcs.shape[1],
    )
    if rc != 0:
        raise OSError(f"sn_shard_append failed on shard {-rc - 1}")


def batch_pread(
    fds: list[int],
    offsets: list[int],
    dst: np.ndarray,
    *,
    width: int | None = None,
    pad_eof: bool = True,
    granule: int = 0,
    crc_state: np.ndarray | None = None,
    filled_state: np.ndarray | None = None,
    out_crcs: np.ndarray | None = None,
    out_counts: np.ndarray | None = None,
) -> None:
    """Fill row i of `dst` (2-D C-contiguous uint8, or 1-D for n=1) with
    `width` bytes read from fds[i] at offsets[i] — one GIL-releasing
    call, a worker thread per row, no intermediate bytes objects.

    `dst` is CALLER-OWNED: rows land in place (the buffer-protocol /
    numpy-view contract of the zero-copy plane). `width` defaults to the
    full row; a narrower width fills a left-aligned slice of each row
    (the pool-backed ragged tail), leaving the remainder untouched.
    pad_eof zero-fills past EOF (encode semantics); pad_eof=False raises
    OSError on any short row (rebuild semantics).

    With granule > 0, each row's rolling CRC32C state
    (crc_state u32[n] / filled_state u64[n], persisting across calls)
    advances over the bytes read, completed granule CRCs landing in
    out_crcs (u32[n, max_out]) with counts in out_counts (i32[n]) — the
    fused read+verify used by the rebuild source path.
    """
    n = len(fds)
    assert len(offsets) == n
    if dst.ndim == 1:
        dst = dst.reshape(1, -1)
    assert dst.dtype == np.uint8 and dst.flags.c_contiguous
    assert dst.shape[0] == n
    stride = dst.shape[1]
    if width is None:
        width = stride
    assert 0 < width <= stride
    max_out = 0
    if granule:
        assert crc_state is not None and filled_state is not None
        assert out_crcs is not None and out_counts is not None
        assert crc_state.dtype == np.uint32
        assert filled_state.dtype == np.uint64
        assert out_crcs.dtype == np.uint32 and out_crcs.flags.c_contiguous
        assert out_counts.dtype == np.int32
        max_out = out_crcs.shape[1]
    rc = _lib.sn_batch_pread(
        (ctypes.c_int * n)(*fds),
        (ctypes.c_uint64 * n)(*offsets),
        n,
        ctypes.c_void_p(dst.ctypes.data),
        width,
        stride,
        1 if pad_eof else 0,
        granule,
        ctypes.c_void_p(crc_state.ctypes.data) if granule else None,
        ctypes.c_void_p(filled_state.ctypes.data) if granule else None,
        ctypes.c_void_p(out_crcs.ctypes.data) if granule else None,
        ctypes.c_void_p(out_counts.ctypes.data) if granule else None,
        max_out,
    )
    if rc != 0:
        err = OSError(
            f"sn_batch_pread failed on row {-rc - 1} "
            f"(fd {fds[-rc - 1]} offset {offsets[-rc - 1]})"
        )
        err.sn_row = -rc - 1  # callers map the row back to a shard id
        raise err


def fadvise_willneed(fd: int, offset: int, length: int) -> None:
    """Best-effort readahead hint (errors ignored — a filesystem that
    rejects the advice just loses the prefetch)."""
    try:
        _lib.sn_fadvise_willneed(fd, offset, length)
    except Exception:  # pragma: no cover - defensive
        pass


# ---------------------------------------------------------------- network
# Socket egress/ingress (ISSUE 12). All three release the GIL for the
# whole transfer; `timeout_ms` bounds each poll() wait on a
# Python-timeout (O_NONBLOCK) socket, -1 blocks forever.


def send_file(
    out_fd: int, in_fd: int, offset: int, length: int, timeout_ms: int = -1
) -> int:
    """sendfile(2) `length` bytes of in_fd@offset into out_fd — kernel
    to kernel, zero userspace copies (one, via the C-side fallback
    buffer, where the kernel path is unsupported). Returns bytes sent;
    SHORT only when in_fd hits EOF. Raises OSError on socket errors or
    timeout."""
    sent = _lib.sn_send_file(out_fd, in_fd, offset, length, timeout_ms)
    if sent < 0:
        raise OSError(-sent, f"sn_send_file: {os.strerror(-sent)}")
    return int(sent)


def _part_ptr_len(part, keepalive: list):
    """(address, nbytes) of a bytes-like without copying it; appends
    whatever must outlive the call to `keepalive`."""
    if isinstance(part, np.ndarray):
        assert part.dtype == np.uint8 and part.flags.c_contiguous
        keepalive.append(part)
        return part.ctypes.data, part.nbytes
    if isinstance(part, bytes):
        p = ctypes.cast(ctypes.c_char_p(part), ctypes.c_void_p)
        keepalive.append((part, p))
        return p.value or 0, len(part)
    a = np.frombuffer(part, dtype=np.uint8)  # zero-copy view
    keepalive.append((part, a))
    return a.ctypes.data, a.nbytes


def sendv(out_fd: int, parts, timeout_ms: int = -1) -> int:
    """Scatter-gather write of `parts` (bytes / memoryview / uint8
    ndarray) to out_fd via writev — no Python-side join, no per-chunk
    GIL round trips. Returns total bytes sent (== sum of lengths);
    raises OSError on failure, ETIMEDOUT included, because a partial
    HTTP body is a broken connection, not a result."""
    n = len(parts)
    keep: list = []
    ptrs = (ctypes.c_void_p * n)()
    lens = (ctypes.c_uint64 * n)()
    total = 0
    for i, part in enumerate(parts):
        addr, ln = _part_ptr_len(part, keep)
        ptrs[i] = addr
        lens[i] = ln
        total += ln
    sent = _lib.sn_sendv(out_fd, ptrs, lens, n, timeout_ms)
    if sent < 0:
        raise OSError(-sent, f"sn_sendv: {os.strerror(-sent)}")
    if sent != total:  # pragma: no cover - C side only shorts on error
        raise OSError(f"sn_sendv short write: {sent}/{total}")
    return int(sent)


def recv_overlap_active(length: int) -> bool:
    """Whether a fused recv+CRC of `length` bytes would run the
    OVERLAPPED core (socket reads on a helper thread, CRC chasing the
    landed bytes) under the current host/env. Auto: >=4 hardware
    threads AND >=256 KiB; ``SEAWEED_EC_NET_OVERLAP=1|0`` forces the
    core gate on/off (the size floor always applies). Read live, so
    the multi-core re-measure recipe can flip it per run."""
    return bool(_lib.sn_recv_overlap_active(length))


def _overlap_mode() -> int:
    """SEAWEED_EC_NET_OVERLAP -> the overlap_mode parameter of
    sn_recv_into. Read HERE (under the GIL, where os.environ mutation
    also happens) and passed down — a getenv on the C hot path would
    race a concurrent setenv, which is undefined behavior."""
    env = os.environ.get("SEAWEED_EC_NET_OVERLAP", "")
    if env == "1":
        return 1
    if env == "0":
        return 0
    return -1


def recv_into(
    fd: int,
    dst: np.ndarray,
    length: int | None = None,
    *,
    timeout_ms: int = -1,
    granule: int = 0,
    crc_state: np.ndarray | None = None,
    filled_state: np.ndarray | None = None,
    out_crcs: np.ndarray | None = None,
    out_counts: np.ndarray | None = None,
) -> int:
    """Land up to `length` bytes from fd DIRECTLY in `dst` (1-D
    C-contiguous uint8, e.g. a pooled rebuild-matrix row) — the ingress
    half of the zero-copy network plane. Returns bytes received; SHORT
    means the peer closed mid-stream (the caller's torn-stream
    contract). With granule > 0, the rolling granule-CRC32C
    (crc_state u32[1] / filled_state u64[1]) advances over the bytes
    during the copy-in, completed granule CRCs landing in out_crcs with
    the count in out_counts[0] — fused sidecar verify, no extra byte
    pass."""
    assert dst.dtype == np.uint8 and dst.ndim == 1
    assert dst.flags.c_contiguous
    if length is None:
        length = dst.nbytes
    assert 0 <= length <= dst.nbytes
    max_out = 0
    if granule:
        assert crc_state is not None and filled_state is not None
        assert out_crcs is not None and out_counts is not None
        assert crc_state.dtype == np.uint32
        assert filled_state.dtype == np.uint64
        assert out_crcs.dtype == np.uint32 and out_crcs.flags.c_contiguous
        assert out_counts.dtype == np.int32
        max_out = out_crcs.shape[-1]
    got = _lib.sn_recv_into(
        fd,
        ctypes.c_void_p(dst.ctypes.data),
        length,
        timeout_ms,
        granule,
        ctypes.c_void_p(crc_state.ctypes.data) if granule else None,
        ctypes.c_void_p(filled_state.ctypes.data) if granule else None,
        ctypes.c_void_p(out_crcs.ctypes.data) if granule else None,
        ctypes.c_void_p(out_counts.ctypes.data) if granule else None,
        max_out,
        _overlap_mode(),
    )
    if got < 0:
        raise OSError(-got, f"sn_recv_into: {os.strerror(-got)}")
    return int(got)


def has_recv_file() -> bool:
    """Whether the loaded .so exports sn_recv_file (older prebuilt
    libraries may not; callers then land blob writes in Python)."""
    return _HAS_RECV_FILE


def recv_file(
    fd: int, out_fd: int, offset: int, length: int, *,
    timeout_ms: int = -1,
) -> tuple[int, int]:
    """Land `length` bytes from socket `fd` straight into file `out_fd`
    at `offset` — the write-opcode blob ingress: socket -> bounce
    buffer -> pwrite(2) with one CRC32C rolled over the payload while
    each chunk is cache-hot, no Python-side byte handling. Returns
    (bytes_landed, crc32c); SHORT means the peer closed mid-stream (the
    partial extent is on disk but callers must not ACK it). Raises
    OSError on socket or pwrite failure."""
    if not _HAS_RECV_FILE:
        raise OSError("sn_recv_file not available in loaded .so")
    crc_out = np.zeros(1, np.uint32)
    got = _lib.sn_recv_file(
        fd, out_fd, offset, length, timeout_ms,
        ctypes.c_void_p(crc_out.ctypes.data),
    )
    if got < 0:
        raise OSError(-got, f"sn_recv_file: {os.strerror(-got)}")
    return int(got), int(crc_out[0])


class NativeSink:
    """Stateful fused write+CRC sink handle (sn_sink_*): pwrite-
    positioned appends straight from caller buffers, leaf AND block
    sidecar CRC levels rolled in the same cache-hot pass, optional
    early-writeback. Callers own the fds (and their lifetime: destroy
    the sink BEFORE closing them); the sink owns only its offsets and
    CRC state."""

    EARLY_WB = 1
    DIRECT = 2

    def __init__(
        self,
        fds: list[int],
        block_size: int,
        leaf_size: int = 0,
        # Off by default: sync_file_range measured -15% on filesystems
        # whose write(2) is already synchronous (9p); the env-gated
        # policy lives in pipeline.FusedShardSink.
        early_writeback: bool = False,
        # Opt-in O_DIRECT writes while every append stays 4096-aligned
        # (pointer, width, file offset); a misaligned append (the
        # ragged tail) or a write the filesystem rejects drops that fd
        # back to buffered transparently — same bytes, same offsets.
        # Gated by SEAWEED_EC_ODIRECT in pipeline.FusedShardSink.
        direct: bool = False,
    ):
        n = len(fds)
        self.n = n
        self.block_size = block_size
        self.leaf_size = leaf_size
        flags = self.EARLY_WB if early_writeback else 0
        if direct:
            flags |= self.DIRECT
        self._h = _lib.sn_sink_create(
            (ctypes.c_int * n)(*fds), n, block_size, leaf_size, flags
        )
        if not self._h:
            raise OSError("sn_sink_create failed (bad block/leaf sizes?)")

    def direct_flags(self) -> np.ndarray:
        """Per-shard O_DIRECT state (u8[n], 1 = still direct): whether
        the page-cache-bypassing path engaged and survived alignment."""
        if self._h is None:
            raise OSError("sink already destroyed")
        out = np.zeros(self.n, np.uint8)
        _lib.sn_sink_direct_flags(self._h, ctypes.c_void_p(out.ctypes.data))
        return out

    def append(
        self,
        row_ptrs: list[int],
        width: int,
        out_block_crcs: np.ndarray,
        out_block_counts: np.ndarray,
        out_leaf_crcs: np.ndarray,
        out_leaf_counts: np.ndarray,
    ) -> None:
        if self._h is None:
            raise OSError("sink already destroyed")
        assert len(row_ptrs) == self.n
        rc = _lib.sn_sink_append(
            self._h,
            (ctypes.c_void_p * self.n)(*row_ptrs),
            width,
            ctypes.c_void_p(out_block_crcs.ctypes.data),
            ctypes.c_void_p(out_block_counts.ctypes.data),
            ctypes.c_void_p(out_leaf_crcs.ctypes.data),
            ctypes.c_void_p(out_leaf_counts.ctypes.data),
            out_block_crcs.shape[1],
        )
        if rc != 0:
            raise OSError(f"sn_sink_append failed on shard {-rc - 1}")

    def finish(self) -> tuple:
        """-> (tail_block_crc, tail_block_valid, tail_leaf_crc,
        tail_leaf_valid, sizes) arrays; flushes partial-tail CRC state."""
        if self._h is None:
            raise OSError("sink already destroyed")
        n = self.n
        tb = np.zeros(n, np.uint32)
        tbv = np.zeros(n, np.uint8)
        tl = np.zeros(n, np.uint32)
        tlv = np.zeros(n, np.uint8)
        sizes = np.zeros(n, np.uint64)
        _lib.sn_sink_finish(
            self._h,
            ctypes.c_void_p(tb.ctypes.data),
            ctypes.c_void_p(tbv.ctypes.data),
            ctypes.c_void_p(tl.ctypes.data),
            ctypes.c_void_p(tlv.ctypes.data),
            ctypes.c_void_p(sizes.ctypes.data),
        )
        return tb, tbv, tl, tlv, sizes

    def destroy(self) -> None:
        if self._h is not None:
            _lib.sn_sink_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC backstop
        try:
            self.destroy()
        except Exception:
            pass


def crc32c_combine(crc1: int, crc2: int, len2: int) -> int:
    """CRC of A++B from crc(A), crc(B), len(B) — the C twin of
    utils/crc.crc32c_combine (used by the sink's leaf->block fold)."""
    return _lib.sn_crc32c_combine(crc1, crc2, len2)


def gf_mul(a: int, b: int) -> int:
    return _lib.sn_gf_mul(a, b)


def has_avx2() -> bool:
    return bool(_lib.sn_has_avx2())


def scan_dat(path: str):
    """Fast .dat scan: -> (ids u64, offsets u32 [8-byte units],
    body_sizes i32, crc_ok u8) parallel arrays, append order.
    Raises OSError on unreadable/short files."""
    import os

    size = os.path.getsize(path)
    max_entries = max(size // 24 + 2, 16)  # min padded record is 24 bytes (v2 tombstone)
    ids = np.empty(max_entries, dtype=np.uint64)
    offsets = np.empty(max_entries, dtype=np.uint32)
    sizes = np.empty(max_entries, dtype=np.int32)
    crc_ok = np.empty(max_entries, dtype=np.uint8)
    n = _lib.sn_scan_dat(
        path.encode(),
        ids.ctypes.data_as(ctypes.c_void_p),
        offsets.ctypes.data_as(ctypes.c_void_p),
        sizes.ctypes.data_as(ctypes.c_void_p),
        crc_ok.ctypes.data_as(ctypes.c_void_p),
        max_entries,
    )
    if n < 0:
        raise OSError(f"sn_scan_dat({path}) failed: {n}")
    return ids[:n], offsets[:n], sizes[:n], crc_ok[:n].astype(bool)
