"""TLS for every HTTP/gRPC listener, with certificate hot-reload.

Reference: weed/security/tls.go + weed/security/certreload/ — the
reference loads cert/key from security.toml and re-reads them when the
files change so operators can rotate certificates without restarting
servers. Here the same is done with the stdlib ssl module: one
SSLContext per listener whose cert chain is re-loaded (cheap mtime
stat) from the ssl SNI callback, which fires once per handshake.

Self-signed certificate minting (for tests and `scaffold`-style
bootstrap) uses the `cryptography` package.
"""

from __future__ import annotations

import contextlib
import datetime
import ipaddress
import os
import ssl
import threading
from dataclasses import dataclass, field


@dataclass
class TlsConfig:
    """Paths for one side of a TLS endpoint.

    ``ca_file`` set on a server means "require and verify client
    certificates" (mutual TLS, like the reference's
    grpc.*.ca security.toml keys); on a client it is the trust root.
    """

    cert_file: str
    key_file: str
    ca_file: str | None = None
    client_auth: bool = False
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)
    _ctx: ssl.SSLContext | None = field(default=None, repr=False)
    _mtimes: tuple[float, float] = field(default=(0.0, 0.0), repr=False)

    # -- server side ----------------------------------------------------
    def _stat(self) -> tuple[float, float]:
        try:
            return (os.stat(self.cert_file).st_mtime, os.stat(self.key_file).st_mtime)
        except OSError:
            return self._mtimes

    def server_context(self) -> ssl.SSLContext:
        """A context whose cert chain hot-reloads on file change."""
        with self._lock:
            if self._ctx is None:
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                ctx.load_cert_chain(self.cert_file, self.key_file)
                if self.client_auth and self.ca_file:
                    ctx.load_verify_locations(self.ca_file)
                    ctx.verify_mode = ssl.CERT_REQUIRED
                ctx.sni_callback = self._sni_reload
                self._ctx = ctx
                self._mtimes = self._stat()
            return self._ctx

    def _sni_reload(self, sslobj, server_name, ctx) -> None:
        # Per-handshake: two stat() calls; reload only when rotated.
        now = self._stat()
        if now != self._mtimes:
            with self._lock:
                if now != self._mtimes:
                    try:
                        ctx.load_cert_chain(self.cert_file, self.key_file)
                        self._mtimes = now
                    except (OSError, ssl.SSLError):
                        pass  # keep serving the old cert on a bad rotate

    def wrap_server(self, httpd) -> None:
        """TLS-enable a ThreadingHTTPServer.

        The handshake must NOT happen in the accept loop (a client that
        connects and sends nothing would stall every other connection),
        so the listening socket stays plain and each accepted socket is
        wrapped in the per-connection thread (finish_request), under a
        handshake timeout."""
        ctx = self.server_context()
        handler_cls = httpd.RequestHandlerClass

        def finish_request(request, client_address):
            request.settimeout(30.0)
            try:
                tls_sock = ctx.wrap_socket(request, server_side=True)
                tls_sock.settimeout(None)
            except (OSError, ssl.SSLError):
                with contextlib.suppress(OSError):
                    request.close()
                return
            handler_cls(tls_sock, client_address, httpd)

        httpd.finish_request = finish_request

    # -- client side ----------------------------------------------------
    def client_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        if self.ca_file:
            ctx.load_verify_locations(self.ca_file)
        else:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        if self.cert_file and os.path.exists(self.cert_file):
            try:
                ctx.load_cert_chain(self.cert_file, self.key_file)
            except (OSError, ssl.SSLError):
                pass
        return ctx

    def requests_kwargs(self) -> dict:
        """kwargs for requests.* against a server using this CA."""
        kw: dict = {"verify": self.ca_file or True}
        if self.cert_file and os.path.exists(self.cert_file):
            kw["cert"] = (self.cert_file, self.key_file)
        return kw


def generate_self_signed(
    out_dir: str,
    hosts: tuple[str, ...] = ("localhost", "127.0.0.1"),
    days: int = 365,
    name: str = "server",
) -> TlsConfig:
    """Mint a CA plus a server cert signed by it under ``out_dir``.

    Returns a TlsConfig pointing at <name>.crt/<name>.key with ca.crt
    as the trust root. Re-invoking with the same dir reuses the CA so
    rotated leaf certs keep verifying.
    """
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    os.makedirs(out_dir, exist_ok=True)
    ca_crt = os.path.join(out_dir, "ca.crt")
    ca_key_p = os.path.join(out_dir, "ca.key")
    now = datetime.datetime.now(datetime.timezone.utc)

    if os.path.exists(ca_crt) and os.path.exists(ca_key_p):
        with open(ca_key_p, "rb") as f:
            ca_key = serialization.load_pem_private_key(f.read(), None)
        with open(ca_crt, "rb") as f:
            ca_cert = x509.load_pem_x509_certificate(f.read())
    else:
        ca_key = ec.generate_private_key(ec.SECP256R1())
        ca_name = x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, "seaweedfs-tpu test CA")]
        )
        ca_cert = (
            x509.CertificateBuilder()
            .subject_name(ca_name)
            .issuer_name(ca_name)
            .public_key(ca_key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=days))
            .add_extension(x509.BasicConstraints(ca=True, path_length=0), True)
            .sign(ca_key, hashes.SHA256())
        )
        with open(ca_key_p, "wb") as f:
            f.write(
                ca_key.private_bytes(
                    serialization.Encoding.PEM,
                    serialization.PrivateFormat.PKCS8,
                    serialization.NoEncryption(),
                )
            )
        with open(ca_crt, "wb") as f:
            f.write(ca_cert.public_bytes(serialization.Encoding.PEM))

    leaf_key = ec.generate_private_key(ec.SECP256R1())
    sans = []
    for h in hosts:
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            sans.append(x509.DNSName(h))
    leaf = (
        x509.CertificateBuilder()
        .subject_name(
            x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, hosts[0])])
        )
        .issuer_name(ca_cert.subject)
        .public_key(leaf_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(x509.SubjectAlternativeName(sans), False)
        .sign(ca_key, hashes.SHA256())
    )
    crt = os.path.join(out_dir, f"{name}.crt")
    key = os.path.join(out_dir, f"{name}.key")
    tmp_key, tmp_crt = key + ".tmp", crt + ".tmp"
    with open(tmp_key, "wb") as f:
        f.write(
            leaf_key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            )
        )
    with open(tmp_crt, "wb") as f:
        f.write(leaf.public_bytes(serialization.Encoding.PEM))
    # key first, then cert: the reload stat pair changes atomically enough
    os.replace(tmp_key, key)
    os.replace(tmp_crt, crt)
    return TlsConfig(cert_file=crt, key_file=key, ca_file=ca_crt)
