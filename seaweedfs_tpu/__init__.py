"""seaweedfs_tpu — a TPU-native distributed blob/object/file store.

A from-scratch framework with the capabilities of seaweedfs/seaweedfs
(Facebook Haystack-style blob store), re-designed TPU-first:

- The erasure-coding (Reed-Solomon GF(2^8)) pipeline runs as batched
  GF(2) bit-plane matmuls on the TPU MXU (JAX/XLA + Pallas), bit-exact
  with the reference's klauspost/reedsolomon CPU path
  (reference: weed/storage/erasure_coding/ec_context.go:45).
- Multi-chip scaling uses jax.sharding.Mesh + shard_map with XLA
  collectives over ICI, not NCCL/MPI translation.
- The storage/cluster runtime (volume engine, master, filer, shell)
  is Python/asyncio + a C++ native core for the hot CPU paths.

Layer map mirrors SURVEY.md §1:
  storage/   on-disk formats + volume engine        (weed/storage)
  ec/        erasure-coding pipeline                (weed/storage/erasure_coding)
  ops/       GF(256) math: numpy reference, XLA, Pallas kernels
  parallel/  device-mesh sharding of the EC math
  server/    master / volume server / filer         (weed/server, weed/topology)
  client/    master client, assign/upload ops       (weed/wdclient, weed/operation)
  shell/     operator command surface               (weed/shell)
  utils/     config, metrics, logging               (weed/util, weed/stats, weed/glog)
"""

__version__ = "0.1.0"
