"""`python -m seaweedfs_tpu.shell` — ops REPL / one-shot command runner."""

from __future__ import annotations

import argparse
import sys

from .commands import ShellEnv, run_command


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="seaweedfs_tpu.shell")
    p.add_argument("-master", default="localhost:9333")
    p.add_argument("-filer", default="localhost:8888")
    p.add_argument("-c", dest="command", default=None, help="run one command and exit")
    a = p.parse_args(argv)

    env = ShellEnv(a.master, a.filer)
    try:
        if a.command:
            print(run_command(env, a.command))
            return 0
        while True:
            try:
                line = input("> ")
            except EOFError:
                return 0
            if line.strip() in ("exit", "quit"):
                return 0
            try:
                print(run_command(env, line))
            except Exception as e:  # keep the REPL alive
                print(f"error: {e}")
    finally:
        env.close()


if __name__ == "__main__":
    sys.exit(main())
