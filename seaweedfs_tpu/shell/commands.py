"""Shell commands: the ops surface (`weed shell` analog).

Reference: weed/shell/commands.go + command_ec_encode.go:102 (doEcEncode
pipeline: mark readonly -> generate -> mount -> delete source),
command_ec_rebuild.go, command_ec_decode.go, volume.* family.

Each command is a function(env, args) -> str; the registry drives both
the REPL and one-shot `python -m seaweedfs_tpu.shell -c "..."`.
"""

from __future__ import annotations

import argparse
import contextlib
import shlex
import uuid as _uuid

import grpc

from ..client.master_client import (
    LockHeldError,
    MasterClient,
    volume_channel,
)
from ..ec import fleet
from ..pb import cluster_pb2 as pb
from ..pb import rpc
from ..utils import trace
from ..utils.urls import service_url


class ShellEnv:
    def __init__(self, master: str = "localhost:9333", filer: str = "localhost:8888"):
        self.master_addr = master
        self.filer_addr = filer
        self.master = MasterClient(master)
        self.owner = f"shell-{_uuid.uuid4().hex[:8]}"
        # how long mutating commands wait for a busy cluster lock
        self.lock_wait = 10.0
        # set by the explicit `lock` command: held across the session
        self.admin_token = ""
        # set while a mutating command auto-holds the admin lease
        # (makes nested cluster_guard calls re-entrant)
        self._auto_admin_token = ""

    def close(self):
        if self.admin_token:
            self.master.unlock("admin", self.admin_token)
            self.admin_token = ""
        self.master.close()


@contextlib.contextmanager
def cluster_guard(env: ShellEnv, vids=(), ttl: float = 600.0, wait: float | None = None):
    """Exclusive cluster lock for a mutating command (reference
    confirmIsLocked): the global admin lease plus a per-volume lease for
    every touched volume, so two shells — or a shell and the worker
    fleet — cannot race destructive steps on the same volume. The admin
    lease is auto-acquired per command unless the session holds it via
    the `lock` command."""
    import threading as _threading

    if wait is None:
        wait = env.lock_wait
    held = env.admin_token or env._auto_admin_token
    admin_tok = env.master.lock(
        "admin", env.owner, ttl=ttl, token=held, wait=wait
    )
    outer = not held
    if outer:
        env._auto_admin_token = admin_tok
    vol_toks: list[tuple[str, str]] = []
    stop_renew = _threading.Event()

    def _renew_loop():
        # a command outliving its ttl must not silently lose mutual
        # exclusion: renew all held leases at ttl/3 cadence (renewal
        # never shortens a lease server-side)
        while not stop_renew.wait(max(ttl / 3.0, 1.0)):
            try:
                env.master.lock(
                    "admin", env.owner, ttl=ttl, token=admin_tok, wait=0
                )
                for name, tok in vol_toks:
                    env.master.lock(name, env.owner, ttl=ttl, token=tok, wait=0)
            except Exception:  # noqa: BLE001 — lease lost (e.g. failover)
                return

    try:
        for vid in vids:
            name = f"volume/{int(vid)}"
            vol_toks.append(
                (name, env.master.lock(name, env.owner, ttl=ttl, wait=wait))
            )
        _threading.Thread(target=_renew_loop, daemon=True).start()
        yield
    finally:
        stop_renew.set()
        for name, tok in vol_toks:
            env.master.unlock(name, tok)
        if outer:
            env._auto_admin_token = ""
            if not env.admin_token:
                env.master.unlock("admin", admin_tok)


@contextlib.contextmanager
def volume_lease(env: ShellEnv, vid: int, ttl: float = 600.0):
    """Per-volume cluster lease for commands that discover their target
    volumes at runtime (ec.balance, fix.replication, collection.delete):
    the admin lease alone does not exclude the worker fleet, which holds
    only volume/<vid> leases."""
    name = f"volume/{int(vid)}"
    tok = env.master.lock(name, env.owner, ttl=ttl, wait=env.lock_wait)
    try:
        yield
    finally:
        env.master.unlock(name, tok)


COMMANDS: dict[str, tuple] = {}


def command(name: str, help_text: str, mutating: bool = False):
    """`mutating=True` gates the command on the exclusive cluster admin
    lease (reference confirmIsLocked) — two shells cannot interleave
    destructive cluster operations."""

    def deco(fn):
        if mutating:
            import functools

            @functools.wraps(fn)
            def wrapped(env, args):
                # the command's -volumeId targets get per-volume leases
                # too, so worker tasks on those volumes cannot interleave
                vids: list[int] = []
                for i, tok in enumerate(args):
                    if tok == "-volumeId" and i + 1 < len(args):
                        vids = [
                            int(v)
                            for v in str(args[i + 1]).split(",")
                            if v.strip().isdigit()
                        ]
                with cluster_guard(env, vids=vids):
                    return fn(env, args)

            COMMANDS[name] = (wrapped, help_text)
            return fn
        COMMANDS[name] = (fn, help_text)
        return fn

    return deco


def run_command(env: ShellEnv, line: str) -> str:
    parts = shlex.split(line)
    if not parts:
        return ""
    name, args = parts[0], parts[1:]
    if name in ("help", "?"):
        return "\n".join(
            f"{n:28s} {h}" for n, (_, h) in sorted(COMMANDS.items())
        )
    entry = COMMANDS.get(name)
    if entry is None:
        return f"unknown command {name!r} (try `help`)"
    # one request id per shell command: every server an `ec.rebuild`
    # or `ec.scrub` touches logs the same id (utils/request_id.py)
    from ..utils.request_id import ensure as _rid_ensure

    _rid_ensure()
    try:
        return entry[0](env, args)
    except grpc.RpcError as e:
        return f"error: {e.code().name}: {e.details()}"
    except (LookupError, LockHeldError, RuntimeError, OSError) as e:
        return f"error: {e}"


def _locate_volume(env: ShellEnv, vid: int) -> pb.Location:
    locs = env.master.lookup(vid, refresh=True)
    if not locs:
        raise LookupError(f"volume {vid} has no locations")
    return locs[0]


def _volume_stub(loc: pb.Location):
    ch = volume_channel(loc)
    return ch, rpc.volume_stub(ch)


def _volume_holders(topo):
    """{vid: [DataNodeInfo...]}, {vid: (collection, replica_placement)} —
    the shared input for replication checks/repair."""
    holders: dict[int, list] = {}
    meta: dict[int, tuple] = {}
    for n in topo.nodes:
        for v in n.volumes:
            holders.setdefault(v.id, []).append(n)
            meta[v.id] = (v.collection, v.replica_placement)
    return holders, meta


# ----------------------------------------------------------------- cluster


@command("cluster.status", "show nodes, volume/EC counts, chip telemetry, SLOs")
def cluster_status(env: ShellEnv, args) -> str:
    topo = env.master.topology()
    lines = [f"max volume id: {topo.max_volume_id}"]
    for n in topo.nodes:
        lines.append(
            f"  node {n.id} rack={n.rack or '-'} "
            f"volumes={len(n.volumes)} ec={len(n.ec_shards)}"
        )
    # heartbeat-learned chip telemetry + master-side SLO surface ride
    # the master's HTTP status endpoints (best-effort: a master built
    # before PR 9, or an unreachable HTTP port, degrades to the
    # gRPC-only listing above)
    try:
        import requests as _rq

        st = _rq.get(
            f"http://{env.master_addr}/cluster/status", timeout=5
        ).json()
        from ..ec.placement import node_view_for
        from ..ec.rebalance import volume_heat

        for node_id, tele in sorted(st.get("EcTelemetry", {}).items()):
            chips = tele.get("chips", {}) or {}
            flag = " DEGRADED" if tele.get("degraded") else ""
            if tele.get("stale"):
                flag += " STALE"
            # gravity column: the same score placement/rebalance rank
            # with (ec/placement.NodeView.gravity_score), so the
            # operator sees where bytes want to drift
            gv = node_view_for(
                node_id, "", "", 8, 0, [], ec_telemetry=tele
            )
            heat = volume_heat(tele)
            lines.append(
                f"  chips {node_id}: {len(chips)} chip(s), "
                f"breakers_open={tele.get('breakers_open', 0)} "
                f"gravity={gv.gravity_score():.2f} "
                f"age={tele.get('age_s', '-')}s "
                f"heat={sum(heat.values())}B{flag}"
            )
            for chip, c in sorted(chips.items()):
                lines.append(
                    f"    {chip} load={c.get('load', 0)} "
                    f"breaker={c.get('breaker') or '-'}"
                )
            for vid, hb in sorted(
                heat.items(), key=lambda kv: -kv[1]
            )[:5]:
                lines.append(f"    ec {vid} heat={hb}B")
        for mig in st.get("EcMigrations", [])[:5]:
            lines.append(
                f"  migration: ec {mig.get('volume_id')} "
                f"{mig.get('src')} -> {mig.get('dst')} "
                f"shards={mig.get('shards')} heat={mig.get('heat')}B "
                f"gravity {mig.get('src_gravity')} -> "
                f"{mig.get('dst_gravity')}"
            )
        slo = _rq.get(
            f"http://{env.master_addr}/debug/slo", timeout=5
        ).json()
        if slo:
            lines.append("  slo (master, ms):")
            for op, s in sorted(slo.items()):
                lines.append(
                    f"    {op}: n={s['count']} p50={s['p50_ms']} "
                    f"p99={s['p99_ms']}"
                )
    except Exception as e:  # noqa: BLE001 — status must stay best-effort
        lines.append(f"  (telemetry unavailable: {e})")
    return "\n".join(lines)


@command("volume.list", "list volumes and EC shard sets per node")
def volume_list(env: ShellEnv, args) -> str:
    topo = env.master.topology()
    lines = []
    for n in topo.nodes:
        lines.append(f"node {n.id}:")
        for v in sorted(n.volumes, key=lambda v: v.id):
            lines.append(
                f"  volume {v.id} col={v.collection or '-'} size={v.size} "
                f"files={v.file_count} del={v.deleted_count} "
                f"{'RO' if v.read_only else 'RW'} rp={v.replica_placement}"
            )
        for e in sorted(n.ec_shards, key=lambda e: e.id):
            shards = [i for i in range(32) if e.shard_bits & (1 << i)]
            lines.append(
                f"  ec {e.id} col={e.collection or '-'} shards={shards} "
                f"{e.data_shards}+{e.parity_shards} gen={e.generation}"
            )
    return "\n".join(lines) or "no nodes"


@command("volume.grow", "-count N [-collection c] [-replication xyz]")
def volume_grow(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.grow")
    p.add_argument("-count", type=int, default=1)
    p.add_argument("-collection", default="")
    p.add_argument("-replication", default="")
    a = p.parse_args(args)
    vids = env.master.grow(a.count, a.collection, a.replication)
    return f"grew volumes: {vids}"


@command("volume.vacuum", "-volumeId N [-garbageThreshold 0.3]", mutating=True)
def volume_vacuum(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.vacuum")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-garbageThreshold", type=float, default=0.0)
    a = p.parse_args(args)
    out = []
    for loc in env.master.lookup(a.volumeId, refresh=True):
        ch, stub = _volume_stub(loc)
        with ch:
            r = stub.VacuumVolume(
                pb.VacuumRequest(
                    volume_id=a.volumeId, garbage_threshold=a.garbageThreshold
                ),
                timeout=600,
            )
        out.append(f"{loc.url}: reclaimed {r.reclaimed_bytes} (ratio {r.garbage_ratio:.2f})")
    return "\n".join(out)


@command("volume.delete", "-volumeId N", mutating=True)
def volume_delete(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.delete")
    p.add_argument("-volumeId", type=int, required=True)
    a = p.parse_args(args)
    out = []
    for loc in env.master.lookup(a.volumeId, refresh=True):
        ch, stub = _volume_stub(loc)
        with ch:
            r = stub.VolumeDelete(
                pb.VolumeCommandRequest(volume_id=a.volumeId), timeout=60
            )
        out.append(f"{loc.url}: {r.error or 'deleted'}")
    return "\n".join(out)


@command("volume.mark", "-volumeId N -readonly|-writable", mutating=True)
def volume_mark(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.mark")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-readonly", action="store_true")
    p.add_argument("-writable", action="store_true")
    a = p.parse_args(args)
    out = []
    for loc in env.master.lookup(a.volumeId, refresh=True):
        ch, stub = _volume_stub(loc)
        with ch:
            req = pb.VolumeCommandRequest(volume_id=a.volumeId)
            r = (
                stub.VolumeMarkWritable(req, timeout=30)
                if a.writable
                else stub.VolumeMarkReadonly(req, timeout=30)
            )
        out.append(f"{loc.url}: {r.error or 'ok'}")
    return "\n".join(out)


# ---------------------------------------------------------------------- ec


@command(
    "ec.encode",
    "-volumeId N[,N2,...] [-collection c] [-backend cpu|tpu|auto] "
    "[-keepSource] [-maxParallelization P]",
    mutating=True,
)
def ec_encode(env: ShellEnv, args) -> str:
    """Reference doEcEncode (command_ec_encode.go:346): mark replicas
    readonly -> generate shards on one holder -> mount -> delete the
    source volume replicas (unless -keepSource). Multiple volumes encode
    concurrently (the reference's -maxParallelization batches)."""
    p = argparse.ArgumentParser(prog="ec.encode")
    p.add_argument("-volumeId", required=True, help="id or comma-separated ids")
    p.add_argument("-collection", default="")
    p.add_argument("-backend", default="auto")
    p.add_argument("-keepSource", action="store_true")
    p.add_argument("-maxParallelization", type=int, default=4)
    a = p.parse_args(args)
    try:
        vids = [int(v) for v in a.volumeId.split(",") if v.strip()]
    except ValueError:
        return f"error: -volumeId wants an id or comma-separated ids, got {a.volumeId!r}"
    # resolve each volume's collection from the topology: EC artifact
    # paths are collection-prefixed on disk
    topo = env.master.topology()
    vol_collection = {
        v.id: v.collection for n in topo.nodes for v in n.volumes
    }

    def encode_one(vid: int) -> str:
        # one failing volume must not discard the batch's other results:
        # destructive steps (readonly-mark, source delete) already ran
        # for volumes that succeeded
        try:
            return _encode_one(vid)
        except grpc.RpcError as e:
            return f"volume {vid}: error: {e.code().name}: {e.details()}"
        except (LookupError, RuntimeError, OSError) as e:
            return f"volume {vid}: error: {e}"

    def _encode_one(vid: int) -> str:
        collection = a.collection or vol_collection.get(vid, "")
        locs = env.master.lookup(vid, refresh=True)
        if not locs:
            return f"volume {vid}: not found"
        for loc in locs:  # 1. freeze every replica
            ch, stub = _volume_stub(loc)
            with ch:
                stub.VolumeMarkReadonly(
                    pb.VolumeCommandRequest(volume_id=vid), timeout=30
                )
        gen_loc = locs[0]
        ch, stub = _volume_stub(gen_loc)
        with ch:  # 2. generate + 3. mount on the first holder
            r = stub.VolumeEcShardsGenerate(
                pb.EcShardsGenerateRequest(
                    volume_id=vid, collection=collection, backend=a.backend
                ),
                timeout=3600,
            )
            generation = r.generation
            stub.VolumeEcShardsMount(
                pb.EcShardsMountRequest(volume_id=vid, collection=collection),
                timeout=60,
            )
        if not a.keepSource:  # 4. drop source replicas
            for loc in locs:
                ch, stub = _volume_stub(loc)
                with ch:
                    stub.VolumeDelete(
                        pb.VolumeCommandRequest(volume_id=vid), timeout=60
                    )
        return (
            f"volume {vid}: generation {generation} on {gen_loc.url}"
            f"{' (source kept)' if a.keepSource else ''}"
        )

    # admin + per-volume leases come from the mutating-command wrapper
    if len(vids) == 1:
        return "ec.encode " + encode_one(vids[0])
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=max(a.maxParallelization, 1)) as ex:
        results = list(ex.map(encode_one, vids))
    return "ec.encode\n" + "\n".join(results)


@command("ec.check.replication", "verify every EC volume has a full shard set")
def ec_check_replication(env: ShellEnv, args) -> str:
    topo = env.master.topology()
    by_vid: dict[int, tuple[set, int]] = {}
    for n in topo.nodes:
        for e in n.ec_shards:
            sids, total = by_vid.get(e.id, (set(), 0))
            sids = sids | {i for i in range(32) if e.shard_bits & (1 << i)}
            by_vid[e.id] = (sids, e.data_shards + e.parity_shards or 14)
    lines = []
    for vid, (sids, total) in sorted(by_vid.items()):
        missing = sorted(set(range(total)) - sids)
        if missing:
            lines.append(f"ec volume {vid}: MISSING shards {missing} (run ec.rebuild)")
        else:
            lines.append(f"ec volume {vid}: all {total} shards present")
    return "\n".join(lines) or "no EC volumes"


@command("cluster.check", "cluster health summary")
def cluster_check(env: ShellEnv, args) -> str:
    topo = env.master.topology()
    stats = env.master.statistics()
    lines = [
        f"nodes: {stats.node_count}",
        f"volumes: {stats.volume_count} ({stats.file_count} files, "
        f"{stats.used_size:,} bytes)",
        f"ec volumes: {stats.ec_volume_count}",
    ]
    problems = []
    if stats.node_count == 0:
        problems.append("no volume servers registered")
    from ..server.topology import _replica_copies

    holders, meta = _volume_holders(topo)
    for vid, hs in sorted(holders.items()):
        want = _replica_copies(meta[vid][1])
        if len(hs) < want:
            problems.append(
                f"volume {vid} under-replicated: {len(hs)}/{want} copies"
            )
    lines += [f"PROBLEM: {x}" for x in problems] or ["all checks passed"]
    return "\n".join(lines)


@command(
    "ec.rebuild",
    "-volumeId N [-collection c] [-backend cpu|tpu|auto] "
    "[-fromPeers] [-holder host:grpcPort]",
    mutating=True,
)
def ec_rebuild(env: ShellEnv, args) -> str:
    """Local rebuild picks the BIGGEST holder (most local sources).
    -fromPeers drives the cluster self-healing path instead: the
    SMALLEST holder (the subset holder a local rebuild refuses on)
    streams sibling shards from peers, rebuilds on its device, and
    distributes regenerated cluster-lost shards to planned holders.
    -holder pins a specific server either way."""
    p = argparse.ArgumentParser(prog="ec.rebuild")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    p.add_argument("-backend", default="")
    p.add_argument("-fromPeers", action="store_true")
    p.add_argument("-holder", default="", help="grpc host:port to rebuild on")
    a = p.parse_args(args)
    shard_locs = env.master.lookup_ec(a.volumeId, refresh=True)
    if not shard_locs:
        return f"ec volume {a.volumeId} not found"
    by_url, loc_by_url = fleet.holder_maps(shard_locs)
    if a.holder:
        url = next(
            (
                u
                for u, loc in loc_by_url.items()
                if a.holder in (u, fleet.grpc_addr(loc))
            ),
            "",
        )
        if not url:
            return f"no holder {a.holder!r} for ec volume {a.volumeId}"
    else:
        url = fleet.pick_rebuild_holder(by_url, smallest=a.fromPeers)
    ch, stub = _volume_stub(loc_by_url[url])
    with ch:
        r = stub.VolumeEcShardsRebuild(
            pb.EcShardsRebuildRequest(
                volume_id=a.volumeId,
                collection=a.collection,
                backend=a.backend,
                from_peers=a.fromPeers,
            ),
            timeout=3600,
            metadata=trace.grpc_metadata(),
        )
        if not a.fromPeers:
            # the peer-fetch path mounts exactly what it owns/adopts;
            # a blanket mount would also advertise unmounted handoff
            # copies kept after a failed distribute
            stub.VolumeEcShardsMount(
                pb.EcShardsMountRequest(
                    volume_id=a.volumeId, collection=a.collection
                ),
                timeout=60,
                metadata=trace.grpc_metadata(),
            )
    extra = ""
    if a.fromPeers:
        extra = (
            f" (fetched {list(r.fetched_shard_ids)} from peers, "
            f"distributed {list(r.distributed_shard_ids)})"
        )
    if r.repaired_shard_ids:
        # rot was leaf-localized: patched in place under the repair
        # journal instead of a whole-shard rebuild
        extra += f", leaf-repaired {list(r.repaired_shard_ids)} in place"
    return f"rebuilt shards {list(r.rebuilt_shard_ids)} on {url}{extra}"


@command("ec.decode", "-volumeId N [-collection c]", mutating=True)
def ec_decode(env: ShellEnv, args) -> str:
    """Collect all shards onto the node already holding the most, decode
    there, then clean the EC artifacts off every node (reference
    command_ec_decode.go: collectEcShards -> VolumeEcShardsToVolume ->
    delete shards)."""
    p = argparse.ArgumentParser(prog="ec.decode")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    a = p.parse_args(args)
    shard_locs = env.master.lookup_ec(a.volumeId, refresh=True)
    if not shard_locs:
        return f"ec volume {a.volumeId} not found"
    by_url: dict[str, set[int]] = {}
    loc_by_url = {}
    for sid, locs in shard_locs.items():
        for loc in locs:
            by_url.setdefault(loc.url, set()).add(sid)
            loc_by_url[loc.url] = loc
    target_url = max(by_url, key=lambda u: len(by_url[u]))
    target = loc_by_url[target_url]
    have = by_url[target_url]

    ch, stub = _volume_stub(target)
    with ch:
        copied_index = False
        for sid in sorted(shard_locs):
            if sid in have:
                continue
            src = next(
                l for l in shard_locs[sid] if l.url != target_url
            )
            stub.VolumeEcShardsCopy(
                pb.EcShardsCopyRequest(
                    volume_id=a.volumeId,
                    collection=a.collection,
                    shard_ids=[sid],
                    source_url=f"{src.url.split(':')[0]}:{src.grpc_port}",
                    copy_ecx=not copied_index and not have,
                    copy_ecj=not copied_index and not have,
                    copy_vif=not copied_index and not have,
                    copy_ecsum=not copied_index and not have,
                ),
                timeout=3600,
            )
            copied_index = True
        stub.VolumeEcShardsToVolume(
            pb.EcShardsToVolumeRequest(
                volume_id=a.volumeId, collection=a.collection
            ),
            timeout=3600,
        )
    # clean EC artifacts off the other nodes
    all_sids = sorted(shard_locs)
    for url, sids in by_url.items():
        if url == target_url:
            continue
        ch, stub = _volume_stub(loc_by_url[url])
        with ch:
            stub.VolumeEcShardsUnmount(
                pb.EcShardsUnmountRequest(volume_id=a.volumeId, shard_ids=all_sids),
                timeout=60,
            )
            stub.VolumeEcShardsDelete(
                pb.EcShardsDeleteRequest(
                    volume_id=a.volumeId,
                    collection=a.collection,
                    shard_ids=all_sids,
                ),
                timeout=60,
            )
    return f"decoded ec volume {a.volumeId} back to a normal volume on {target_url}"


@command(
    "volume.sync",
    "-volumeId N -target host:grpcPort [-source host:grpcPort] "
    "(incremental replica catch-up via VolumeTailReceiver)",
    mutating=True,
)
def volume_sync(env: ShellEnv, args) -> str:
    """Needle-granular catch-up: the TARGET replica pulls every record
    appended at the source since the target's own last appendAtNs
    (reference volume_grpc_tail.go VolumeTailReceiver + weed backup's
    incremental model). A replica that missed writes while down
    converges without a full re-copy."""
    p = argparse.ArgumentParser(prog="volume.sync")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-target", required=True, help="replica to heal (grpc)")
    p.add_argument("-source", default="", help="replica to pull from (grpc)")
    p.add_argument("-sinceNs", type=int, default=0)
    p.add_argument("-idleTimeout", type=int, default=3)
    a = p.parse_args(args)
    locs = env.master.lookup(a.volumeId, refresh=True)
    if not locs:
        return f"volume {a.volumeId} not found"
    import socket as _socket

    def _resolved(addr: str) -> tuple[str, str]:
        host, _, port = addr.partition(":")
        try:
            return _socket.gethostbyname(host), port
        except OSError:
            return host, port

    src_grpc = a.source
    if not src_grpc:
        # resolve hostnames before comparing: 'localhost' vs
        # '127.0.0.1' must not make the target pull from itself
        for loc in locs:
            cand = f"{loc.url.split(':')[0]}:{loc.grpc_port}"
            if _resolved(cand) != _resolved(a.target):
                src_grpc = cand
                break
        if not src_grpc:
            return f"volume {a.volumeId} has no replica besides the target"
    from ..client.volume_sync import sync_replica

    try:
        n = sync_replica(
            a.target, src_grpc, a.volumeId,
            since_ns=a.sinceNs, idle_timeout_s=a.idleTimeout,
        )
    except (RuntimeError, grpc.RpcError) as e:
        detail = e.details() if isinstance(e, grpc.RpcError) else str(e)
        return f"error: {detail}"
    return (
        f"synced volume {a.volumeId}: {n} records applied "
        f"{src_grpc} -> {a.target}"
    )


@command("volume.move", "-volumeId N -target host:grpcPort (move one volume)", mutating=True)
def volume_move(env: ShellEnv, args) -> str:
    """Copy to target, load there, delete at source (reference
    volume.move: mark-readonly -> copy -> mount -> delete)."""
    p = argparse.ArgumentParser(prog="volume.move")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-target", required=True, help="grpc address host:port")
    p.add_argument(
        "-source",
        default="",
        help="grpc address of the REPLICA to move (default: first found)",
    )
    p.add_argument("-collection", default="")
    a = p.parse_args(args)
    locs = env.master.lookup(a.volumeId, refresh=True)
    if not locs:
        return f"volume {a.volumeId} not found"
    src = locs[0]
    if a.source:
        # replicated volumes: the caller (e.g. volume.balance) names
        # WHICH replica moves; defaulting to locs[0] would drain the
        # wrong node and never converge
        for loc in locs:
            if f"{loc.url.split(':')[0]}:{loc.grpc_port}" == a.source:
                src = loc
                break
        else:
            return f"volume {a.volumeId} has no replica at {a.source}"
    src_grpc = f"{src.url.split(':')[0]}:{src.grpc_port}"
    if src_grpc == a.target:
        return "volume already on target"
    ch, stub = _volume_stub(src)
    with ch:
        stub.VolumeMarkReadonly(
            pb.VolumeCommandRequest(volume_id=a.volumeId), timeout=30
        )
    try:
        with grpc.insecure_channel(a.target) as ch2:
            r = rpc.Stub(ch2, rpc.VOLUME_SERVICE).VolumeCopy(
                pb.EcShardsCopyRequest(
                    volume_id=a.volumeId,
                    collection=a.collection,
                    source_url=src_grpc,
                ),
                timeout=3600,
            )
        if r.error:
            raise RuntimeError(f"copy failed: {r.error}")
    except (grpc.RpcError, RuntimeError) as e:
        # failed move must not strand the source readonly
        ch, stub = _volume_stub(src)
        with ch:
            stub.VolumeMarkWritable(
                pb.VolumeCommandRequest(volume_id=a.volumeId), timeout=30
            )
        detail = e.details() if isinstance(e, grpc.RpcError) else str(e)
        return f"error: {detail} (source volume restored writable)"
    ch, stub = _volume_stub(src)
    with ch:
        stub.VolumeDelete(pb.VolumeCommandRequest(volume_id=a.volumeId), timeout=60)
    return f"moved volume {a.volumeId} {src.url} -> {a.target}"


@command(
    "volume.tier.upload",
    "-volumeId N -dest http://host/bucket/key (move sealed .dat to cold tier)",
    mutating=True,
)
def volume_tier_upload(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.tier.upload")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-dest", required=True, help="S3-style object URL")
    p.add_argument("-keepLocal", action="store_true")
    a = p.parse_args(args)
    loc = _locate_volume(env, a.volumeId)
    ch, stub = _volume_stub(loc)
    with ch:
        stub.VolumeMarkReadonly(
            pb.VolumeCommandRequest(volume_id=a.volumeId), timeout=30
        )
        r = stub.VolumeTierUpload(
            pb.TierRequest(
                volume_id=a.volumeId,
                dest_url=a.dest,
                keep_local=a.keepLocal,
            ),
            timeout=3600,
        )
    if r.error:
        return f"error: {r.error}"
    return (
        f"volume {a.volumeId}: {r.moved_bytes:,} bytes -> {a.dest}"
        f"{' (local copy kept)' if a.keepLocal else ''}"
    )


@command(
    "volume.tier.download",
    "-volumeId N [-deleteRemote] (bring cold .dat back to local disk)",
    mutating=True,
)
def volume_tier_download(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.tier.download")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-deleteRemote", action="store_true")
    a = p.parse_args(args)
    loc = _locate_volume(env, a.volumeId)
    ch, stub = _volume_stub(loc)
    with ch:
        r = stub.VolumeTierDownload(
            pb.TierRequest(
                volume_id=a.volumeId, delete_remote=a.deleteRemote
            ),
            timeout=3600,
        )
    if r.error:
        return f"error: {r.error}"
    return f"volume {a.volumeId}: {r.moved_bytes:,} bytes fetched from cold tier"


@command("volume.fix.replication", "re-replicate under-replicated volumes", mutating=True)
def volume_fix_replication(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.fix.replication")
    p.add_argument("-collection", default="")
    a = p.parse_args(args)
    topo = env.master.topology()
    holders, meta = _volume_holders(topo)
    from ..server.topology import _replica_copies

    fixed = []
    for vid, hs in sorted(holders.items()):
        col, rp = meta[vid]
        want = _replica_copies(rp)
        if len(hs) >= want:
            continue
        candidates = [
            n for n in topo.nodes if all(h.id != n.id for h in hs)
        ]
        src = hs[0]
        src_grpc = f"{src.location.url.split(':')[0]}:{src.location.grpc_port}"
        # freeze writes while the copy streams, restore after — a live
        # append between the .dat and .idx copies would tear the replica
        with volume_lease(env, vid):
            src_ch, src_stub = _volume_stub(src.location)
            with src_ch:
                src_stub.VolumeMarkReadonly(
                    pb.VolumeCommandRequest(volume_id=vid), timeout=30
                )
                try:
                    for n in candidates[: want - len(hs)]:
                        with grpc.insecure_channel(
                            f"{n.location.url.split(':')[0]}:{n.location.grpc_port}"
                        ) as ch:
                            r = rpc.Stub(ch, rpc.VOLUME_SERVICE).VolumeCopy(
                                pb.EcShardsCopyRequest(
                                    volume_id=vid, collection=col, source_url=src_grpc
                                ),
                                timeout=3600,
                            )
                        if not r.error:
                            fixed.append(f"volume {vid} -> {n.id}")
                finally:
                    src_stub.VolumeMarkWritable(
                        pb.VolumeCommandRequest(volume_id=vid), timeout=30
                    )
    return "\n".join(fixed) or "all volumes sufficiently replicated"


@command(
    "ec.balance",
    "spread EC shards evenly across racks and nodes "
    "[-dataGravity drifts shards toward chip-rich low-load hosts]",
    mutating=True,
)
def ec_balance(env: ShellEnv, args) -> str:
    """Rack-aware balance (reference command_ec_common.go:60 EcBalance):
    dedupe shard copies, spread each volume across racks, even within
    racks, then flatten per-rack totals — planned by ec/placement.py,
    executed here as copy+mount / unmount+delete pairs. `-dataGravity`
    appends the gravity stage: bounded moves from chip-poor/loaded
    nodes toward chip-rich low-load ones (heartbeat telemetry), never
    violating the spread/slot invariants."""
    from ..ec.placement import node_view_for, plan_ec_balance

    p = argparse.ArgumentParser(prog="ec.balance")
    p.add_argument("-collection", default="")
    p.add_argument("-dryRun", action="store_true")
    p.add_argument("-dataGravity", action="store_true")
    p.add_argument("-maxGravityMoves", type=int, default=4)
    a = p.parse_args(args)
    topo = env.master.topology()
    nodes = {n.id: n for n in topo.nodes}
    if len(nodes) < 2:
        return "nothing to balance (fewer than 2 nodes)"
    # gravity needs the heartbeat telemetry, which rides the master's
    # HTTP status plane (best-effort: absent telemetry = static plan)
    tele: dict = {}
    if a.dataGravity:
        try:
            import requests as _rq

            tele = _rq.get(
                f"http://{env.master_addr}/cluster/status", timeout=5
            ).json().get("EcTelemetry", {}) or {}
        except Exception:  # noqa: BLE001 — gravity degrades to static
            tele = {}
    vol_collection: dict[int, str] = {}
    views = []
    for n in topo.nodes:
        for e in n.ec_shards:
            if not a.collection or e.collection == a.collection:
                vol_collection[e.id] = e.collection
        views.append(
            node_view_for(
                n.id,
                n.rack,
                n.data_center,
                n.max_volume_count,
                len(n.volumes),
                n.ec_shards,
                a.collection,
                ec_telemetry=tele.get(n.id),
            )
        )
    drops, moves = plan_ec_balance(
        views, data_gravity=a.dataGravity,
        max_gravity_moves=a.maxGravityMoves,
    )
    if a.dryRun:
        return "\n".join(
            [f"drop ec {d.vid}.{d.shard_id:02d} on {d.node}" for d in drops]
            + [
                f"move ec {m.vid}.{m.shard_id:02d}: {m.src} -> {m.dst} ({m.reason})"
                for m in moves
            ]
        ) or "already balanced"

    def _grpc_addr(nid: str) -> str:
        n = nodes[nid]
        return f"{n.location.url.split(':')[0]}:{n.location.grpc_port}"

    out = []
    for d in drops:
        with volume_lease(env, d.vid):
            with grpc.insecure_channel(_grpc_addr(d.node)) as ch:
                stub = rpc.Stub(ch, rpc.VOLUME_SERVICE)
                stub.VolumeEcShardsUnmount(
                    pb.EcShardsUnmountRequest(
                        volume_id=d.vid, shard_ids=[d.shard_id]
                    ),
                    timeout=60,
                )
                stub.VolumeEcShardsDelete(
                    pb.EcShardsDeleteRequest(
                        volume_id=d.vid,
                        collection=vol_collection.get(d.vid, ""),
                        shard_ids=[d.shard_id],
                    ),
                    timeout=60,
                )
        out.append(f"dedupe ec {d.vid}.{d.shard_id:02d} on {d.node}")
    # live per-(node, vid) shard counts: drops and move-sources remove
    # entries (a node whose last shard left also lost its .ecx — the
    # next copy TO it must bring the index files again)
    shard_count: dict[tuple[str, int], int] = {}
    for n in topo.nodes:
        for e in n.ec_shards:
            shard_count[(n.id, e.id)] = bin(e.shard_bits).count("1")
    for d in drops:
        k = (d.node, d.vid)
        shard_count[k] = max(shard_count.get(k, 1) - 1, 0)
    for m in moves:
        col = vol_collection.get(m.vid, "")
        first_on_dst = shard_count.get((m.dst, m.vid), 0) == 0
        with volume_lease(env, m.vid):
            with grpc.insecure_channel(_grpc_addr(m.dst)) as ch:
                stub = rpc.Stub(ch, rpc.VOLUME_SERVICE)
                stub.VolumeEcShardsCopy(
                    pb.EcShardsCopyRequest(
                        volume_id=m.vid,
                        collection=col,
                        shard_ids=[m.shard_id],
                        source_url=_grpc_addr(m.src),
                        copy_ecx=first_on_dst,
                        copy_ecj=first_on_dst,
                        copy_vif=first_on_dst,
                        copy_ecsum=first_on_dst,
                    ),
                    timeout=3600,
                )
                stub.VolumeEcShardsMount(
                    pb.EcShardsMountRequest(volume_id=m.vid, collection=col),
                    timeout=60,
                )
            with grpc.insecure_channel(_grpc_addr(m.src)) as ch:
                stub = rpc.Stub(ch, rpc.VOLUME_SERVICE)
                stub.VolumeEcShardsUnmount(
                    pb.EcShardsUnmountRequest(
                        volume_id=m.vid, shard_ids=[m.shard_id]
                    ),
                    timeout=60,
                )
                stub.VolumeEcShardsDelete(
                    pb.EcShardsDeleteRequest(
                        volume_id=m.vid, collection=col, shard_ids=[m.shard_id]
                    ),
                    timeout=60,
                )
        shard_count[(m.dst, m.vid)] = shard_count.get((m.dst, m.vid), 0) + 1
        ks = (m.src, m.vid)
        shard_count[ks] = max(shard_count.get(ks, 1) - 1, 0)
        out.append(
            f"ec {m.vid}.{m.shard_id:02d}: {m.src} -> {m.dst} ({m.reason})"
        )
    return "\n".join(out) or "already balanced"


@command("volume.scrub", "-volumeId N (CRC-verify all live needles)")
def volume_scrub(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.scrub")
    p.add_argument("-volumeId", type=int, required=True)
    a = p.parse_args(args)
    locs = env.master.lookup(a.volumeId, refresh=True)
    if not locs:
        return f"volume {a.volumeId} not found"
    out = []
    for loc in locs:
        ch, stub = _volume_stub(loc)
        with ch:
            r = stub.ScrubVolume(
                pb.ScrubRequest(volume_id=a.volumeId), timeout=3600,
                metadata=trace.grpc_metadata(),
            )
        if r.error:
            out.append(f"{loc.url}: error: {r.error}")
        else:
            bad = list(r.bad_needles)
            out.append(
                f"{loc.url}: checked {r.checked} needles"
                + (f", CORRUPT: {[hex(b) for b in bad]}" if bad else ", all clean")
            )
    return "\n".join(out)


@command(
    "ec.scrub",
    "-volumeId N [-collection c] [-repair] (verify shards vs .ecsum; "
    "-repair rebuilds corrupt/missing shards on the holder)",
)
def ec_scrub(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="ec.scrub")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    p.add_argument("-repair", action="store_true")
    a = p.parse_args(args)
    shard_locs = env.master.lookup_ec(a.volumeId, refresh=True)
    if not shard_locs:
        return f"ec volume {a.volumeId} not found"
    # k from the topology: a holder with fewer than k verified-good
    # local shards cannot rebuild locally; skip the doomed RPC and point
    # at ec.rebuild (which picks the biggest holder) instead
    data_shards = 0
    for n in env.master.topology().nodes:
        for e in n.ec_shards:
            if e.id == a.volumeId:
                data_shards = e.data_shards
    if not data_shards:
        # topology gap (heartbeat lag): fall back to the default ratio
        # so the guard stays conservative rather than vanishing
        from ..ec.context import DATA_SHARDS

        data_shards = DATA_SHARDS
    holder_sids, loc_by_url = fleet.holder_maps(shard_locs)
    out = []
    fleet_checked = fleet_bad = fleet_missing = fleet_quar = 0
    unrebuildable: list[str] = []
    for url, loc in sorted(loc_by_url.items()):
        ch, stub = _volume_stub(loc)
        with ch:
            r = stub.ScrubEcVolume(
                pb.ScrubRequest(volume_id=a.volumeId, collection=a.collection),
                timeout=3600,
                metadata=trace.grpc_metadata(),
            )
            if r.error:
                out.append(f"{url}: error: {r.error}")
                continue
            # the same per-holder verdict kernel the fleet worker uses
            # (ec/fleet.py): real per-sid missing set difference, with
            # the count-comparison degrade for pre-checked_shards
            # servers, and the < k verified-good unrebuildable call
            facts = fleet.holder_scrub_facts(
                r, holder_sids.get(url, set()), data_shards
            )
            bad = facts["bad"]
            gone = bool(facts["missing"] or facts["legacy_gone"])
            if facts["legacy_gone"]:
                gone_note = (
                    f" ({facts['legacy_gone']} advertised "
                    f"shard files MISSING)"
                )
            else:
                gone_note = (
                    f" (advertised shards {facts['missing']} "
                    f"MISSING locally)"
                )
            quarantined = facts["quarantined"]
            out.append(
                f"{url}: checked {r.checked} shards"
                + (f", BITROT in shards {bad}" if bad else ", all clean")
                + (gone_note if gone else "")
                + (
                    f" (quarantined: {quarantined})" if quarantined else ""
                )
                + (
                    f" ({r.repair_journal_recovered} repair journal(s) "
                    f"recovered)"
                    if r.repair_journal_recovered
                    else ""
                )
            )
            fleet_checked += r.checked
            fleet_bad += len(bad)
            fleet_quar += len(quarantined)
            # legacy holders report losses only as a count — still real
            # shard loss, still in the roll-up the operator alerts on
            fleet_missing += len(facts["missing"]) + facts["legacy_gone"]
            if facts["unrebuildable"]:
                unrebuildable.append(url)
            # gate on the kernel's `hurt` verdict, exactly like the
            # fleet worker: a quarantine-only holder (rot pulled from
            # service, canonical file gone) is repairable too
            if not facts["hurt"] or not a.repair:
                continue
            if facts["good"] < data_shards:
                out.append(
                    f"{url}: repair skipped: {facts['good']} "
                    f"verified-good local shards < {data_shards} needed; "
                    f"use `ec.rebuild -fromPeers` to stream sibling "
                    f"shards from peer holders"
                )
                continue
            # rebuild_ec_files' verify-and-exclude reclassifies the
            # corrupt shards as missing and regenerates them (and any
            # locally-lost mounted shards) from the verified-good
            # remainder (fail-closed on its own)
            try:
                rr = stub.VolumeEcShardsRebuild(
                    pb.EcShardsRebuildRequest(
                        volume_id=a.volumeId, collection=a.collection
                    ),
                    timeout=3600,
                    metadata=trace.grpc_metadata(),
                )
                out.append(
                    f"{url}: rebuilt shards {sorted(rr.rebuilt_shard_ids)}"
                )
            except grpc.RpcError as e:
                out.append(f"{url}: rebuild REFUSED: {e.details()}")
    # fleet roll-up: the one line an operator (or the master's fleet
    # scrub aggregation) alerts on
    out.append(
        f"fleet: {len(loc_by_url)} holders, {fleet_checked} shards checked, "
        f"{fleet_bad} bitrot, {fleet_missing} missing, "
        f"{fleet_quar} quarantined"
        + (
            f"; unrebuildable holders {unrebuildable} -> "
            f"ec.rebuild -fromPeers"
            if unrebuildable
            else ""
        )
    )
    return "\n".join(out)


@command("collection.list", "list collections")
def collection_list(env: ShellEnv, args) -> str:
    return "\n".join(env.master.collections()) or "(none)"


@command("collection.delete", "-collection name (drop all its volumes)", mutating=True)
def collection_delete(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="collection.delete")
    p.add_argument("-collection", required=True)
    a = p.parse_args(args)
    # lease every volume of the collection first so a worker task
    # (ec_encode/vacuum) can't be mid-flight on one while it vanishes
    topo = env.master.topology()
    vids = sorted(
        {
            v.id
            for n in topo.nodes
            for v in n.volumes
            if v.collection == a.collection
        }
        | {
            e.id
            for n in topo.nodes
            for e in n.ec_shards
            if e.collection == a.collection
        }
    )
    with contextlib.ExitStack() as stack:
        for vid in vids:
            stack.enter_context(volume_lease(env, vid))
        deleted = env.master.collection_delete(a.collection)
    return f"deleted collection {a.collection!r}: volumes {deleted}"


# ---------------------------------------------------------------------- fs


def _filer_url(env: ShellEnv, path: str) -> str:
    from urllib.parse import quote

    if not path.startswith("/"):
        path = "/" + path
    return service_url(env.filer_addr, quote(path))


@command("fs.ls", "fs.ls /path (filer listing)")
def fs_ls(env: ShellEnv, args) -> str:
    import requests as rq

    path = args[0] if args else "/"
    r = rq.get(_filer_url(env, path), timeout=30)
    if r.status_code != 200:
        return f"error: {r.text}"
    # the filer marks real directory listings; a stored .json file must
    # not be mistaken for one
    if r.headers.get("X-Filer-Listing") != "true":
        return f"{path}: file ({len(r.content)} bytes)"
    body = r.json()
    return "\n".join(
        f"{'d' if e['IsDirectory'] else '-'} {e['FileSize']:>12} {e['FullPath']}"
        for e in body.get("Entries", [])
    ) or "(empty)"


@command("fs.cat", "fs.cat /path")
def fs_cat(env: ShellEnv, args) -> str:
    import requests as rq

    r = rq.get(_filer_url(env, args[0]), timeout=60)
    if r.status_code != 200:
        return f"error: {r.text}"
    return r.content.decode(errors="replace")


@command("fs.rm", "fs.rm [-r] /path")
def fs_rm(env: ShellEnv, args) -> str:
    import requests as rq

    p = argparse.ArgumentParser(prog="fs.rm")
    p.add_argument("-r", action="store_true")
    p.add_argument("path")
    a = p.parse_args(args)
    r = rq.delete(
        _filer_url(env, a.path) + ("?recursive=true" if a.r else ""), timeout=60
    )
    return "ok" if r.status_code in (200, 204) else f"error: {r.text}"


@command("fs.tree", "fs.tree /path (recursive listing)")
def fs_tree(env: ShellEnv, args) -> str:
    from ..client.filer_client import FilerListingError, list_dir

    root = args[0] if args else "/"
    lines = [root]
    # explicit pre-order work list: correct nesting without Python
    # recursion limits on deep namespaces
    work: list = [("dir", root, 1, True)]
    try:
        while work:
            item = work.pop()
            if item[0] == "line":
                lines.append(item[1])
                continue
            _, path, depth, strict = item
            sub: list = []
            for e in list_dir(env.filer_addr, path, strict=strict):
                name = e["FullPath"].rsplit("/", 1)[-1]
                sub.append(
                    ("line", "  " * depth + name + ("/" if e["IsDirectory"] else ""))
                )
                if e["IsDirectory"]:
                    sub.append(("dir", e["FullPath"], depth + 1, False))
            work.extend(reversed(sub))
    except FilerListingError as e:
        return f"error: {e}"
    return "\n".join(lines)


@command("fs.du", "fs.du /path (recursive size)")
def fs_du(env: ShellEnv, args) -> str:
    from ..client.filer_client import FilerListingError, walk

    root = args[0] if args else "/"
    total = files = dirs = 0
    try:
        for e in walk(env.filer_addr, root, strict=True):
            if e["IsDirectory"]:
                dirs += 1
            else:
                files += 1
                total += e["FileSize"]
    except FilerListingError as e:
        return f"error: {e}"
    return f"{total:,} bytes in {files} files, {dirs} directories under {root}"


@command("volume.fsck", "cross-check filer chunk references against volumes")
def volume_fsck(env: ShellEnv, args) -> str:
    """Referential check (reference volume.fsck direction filer->volume):
    every chunk a filer entry references must be readable on a volume.
    (The reverse direction — unreferenced volume needles — is not
    scanned: raw blob-API uploads are legitimately filer-less.)"""
    from ..client.filer_client import FilerListingError, walk
    from ..storage.file_id import FileId, FileIdError

    p = argparse.ArgumentParser(prog="volume.fsck")
    p.add_argument("-path", default="/")
    a = p.parse_args(args)
    referenced: dict[int, set] = {}
    entries = 0
    skipped = 0
    import requests as rq

    try:
        for e in walk(env.filer_addr, a.path, strict=True):
            if e["IsDirectory"]:
                continue
            entries += 1
            r = rq.get(
                _filer_url(env, e["FullPath"]),
                params={"chunks": "true"},
                timeout=30,
            )
            if r.headers.get("X-Filer-Chunks") != "true":
                skipped += 1  # filer without the chunk-manifest endpoint
                continue
            for fid in r.json().get("chunks", []):
                try:
                    f = FileId.parse(fid)
                except FileIdError:
                    continue
                referenced.setdefault(f.volume_id, set()).add(f.needle_id)
    except FilerListingError as e:
        return f"error: {e}"
    broken = []
    checked = 0
    for vid, nids in sorted(referenced.items()):
        try:
            loc = _locate_volume(env, vid)
        except LookupError:
            broken.extend((vid, n, "volume has no locations") for n in nids)
            continue
        try:
            ch, stub = _volume_stub(loc)
            with ch:
                for nid in nids:
                    checked += 1
                    r2 = stub.ReadNeedle(
                        pb.ReadNeedleRequest(volume_id=vid, needle_id=nid),
                        timeout=30,
                    )
                    if r2.error:
                        broken.append((vid, nid, r2.error))
        except grpc.RpcError as e:
            # one dead server must not discard the rest of the scan
            broken.extend(
                (vid, n, f"holder unreachable: {e.code().name}") for n in nids
            )
    out = [f"fsck: {entries} entries, {checked} chunk references checked"]
    if skipped:
        out.append(f"WARNING: {skipped} entries skipped (no chunk manifest endpoint)")
    if broken:
        out += [f"BROKEN: volume {v} needle {n:x} ({why})" for v, n, why in broken]
    else:
        out.append("no broken chunk references")
    return "\n".join(out)


@command("fs.mkdir", "fs.mkdir /path")
def fs_mkdir(env: ShellEnv, args) -> str:
    import requests as rq

    r = rq.post(_filer_url(env, args[0]) + "?mkdir=true", timeout=30)
    return "ok" if r.status_code == 201 else f"error: {r.text}"


@command("fs.meta.save", "fs.meta.save /path -o meta.jsonl (export filer metadata)")
def fs_meta_save(env: ShellEnv, args) -> str:
    """Walk the filer tree and export entry metadata as NDJSON
    (reference fs.meta.save)."""
    import json as _json

    from ..client.filer_client import FilerListingError, walk

    p = argparse.ArgumentParser(prog="fs.meta.save")
    p.add_argument("path", nargs="?", default="/")
    p.add_argument("-o", required=True)
    a = p.parse_args(args)
    count = 0
    try:
        with open(a.o, "w") as out:
            for e in walk(env.filer_addr, a.path, strict=True):
                out.write(_json.dumps(e, separators=(",", ":")) + "\n")
                count += 1
    except FilerListingError as e:
        return f"error: {e}"
    return f"saved {count} entries -> {a.o}"


@command("fs.meta.load", "fs.meta.load meta.jsonl (recreate dirs; files need data)")
def fs_meta_load(env: ShellEnv, args) -> str:
    """Recreate the directory skeleton from a fs.meta.save export.
    (File content lives in volumes; restoring bytes is filer.sync /
    volume restore territory.)"""
    import json as _json

    import requests as rq

    p = argparse.ArgumentParser(prog="fs.meta.load")
    p.add_argument("file")
    a = p.parse_args(args)
    dirs = files = failed = 0
    with open(a.file) as f:
        for line in f:
            e = _json.loads(line)
            if e["IsDirectory"]:
                r = rq.post(
                    _filer_url(env, e["FullPath"]) + "?mkdir=true", timeout=30
                )
                if r.status_code == 201:
                    dirs += 1
                else:
                    failed += 1
            else:
                files += 1
    out = f"recreated {dirs} directories ({files} file entries listed)"
    if failed:
        out += f"; {failed} FAILED"
    return out


@command("volume.check.disk", "compare replicas of each volume and report divergence")
def volume_check_disk(env: ShellEnv, args) -> str:
    """Cross-replica consistency check (reference volume.check.disk):
    flags replicas whose file counts / sizes disagree."""
    topo = env.master.topology()
    holders: dict[int, list] = {}
    for n in topo.nodes:
        for v in n.volumes:
            holders.setdefault(v.id, []).append((n.id, v))
    lines = []
    for vid, hs in sorted(holders.items()):
        if len(hs) < 2:
            continue
        sizes = {h[1].size for h in hs}
        counts = {h[1].file_count for h in hs}
        dels = {h[1].deleted_count for h in hs}
        if len(sizes) > 1 or len(counts) > 1 or len(dels) > 1:
            detail = "; ".join(
                f"{nid}: size={v.size} files={v.file_count} del={v.deleted_count}"
                for nid, v in hs
            )
            lines.append(f"volume {vid} DIVERGED: {detail}")
        else:
            lines.append(f"volume {vid}: {len(hs)} replicas consistent")
    return "\n".join(lines) or "no replicated volumes"


@command("fs.mv", "fs.mv /src /dst")
def fs_mv(env: ShellEnv, args) -> str:
    import requests as rq
    from urllib.parse import quote

    src, dst = args
    r = rq.post(_filer_url(env, dst) + f"?mv.from={quote(src, safe='')}", timeout=60)
    return "ok" if r.status_code == 200 else f"error: {r.text}"


# -------------------------------------------------------------------- tasks


@command(
    "task.submit",
    "-kind ec_encode|vacuum|balance|ec_balance|s3_lifecycle|iceberg "
    "[-volumeId N] [-backend b] [-param k=v ...]",
)
def task_submit(env: ShellEnv, args) -> str:
    from ..pb import worker_pb2 as wk

    p = argparse.ArgumentParser(prog="task.submit")
    p.add_argument("-kind", required=True)
    # volume-independent kinds (ec_balance, s3_lifecycle) run with 0;
    # every other kind acts on ONE volume and a forgotten -volumeId
    # would submit a doomed volume-0 task that only fails in task.list
    p.add_argument("-volumeId", type=int, default=None)
    p.add_argument("-collection", default="")
    p.add_argument("-backend", default="")
    p.add_argument(
        "-param",
        action="append",
        default=[],
        help="k=v, validated against the kind's descriptor",
    )
    a = p.parse_args(args)
    from ..worker.control import VOLUME_INDEPENDENT_KINDS

    volume_independent = a.kind in VOLUME_INDEPENDENT_KINDS
    if a.volumeId is None and not volume_independent:
        return f"error: -volumeId is required for kind {a.kind}"
    params = {}
    for kv in a.param:
        k, sep, v = kv.partition("=")
        if not sep or not k:
            return f"error: -param wants k=v, got {kv!r}"
        params[k] = v
    req = wk.SubmitTaskRequest(
        kind=a.kind,
        volume_id=a.volumeId or 0,
        collection=a.collection,
        backend=a.backend,
    )
    for k, v in params.items():
        req.params[k] = v
    with grpc.insecure_channel(env.master.grpc_addr) as ch:
        r = rpc.Stub(ch, rpc.WORKER_SERVICE).SubmitTask(req, timeout=30)
    if r.error:
        return f"error: {r.error}"
    return f"task {r.task_id} submitted"


@command("task.list", "show the maintenance task queue")
def task_list(env: ShellEnv, args) -> str:
    from ..pb import worker_pb2 as wk

    with grpc.insecure_channel(env.master.grpc_addr) as ch:
        r = rpc.Stub(ch, rpc.WORKER_SERVICE).ListTasks(
            wk.ListTasksRequest(), timeout=30
        )
    return "\n".join(
        f"{t.task_id} {t.kind} vol={t.volume_id} {t.state}"
        + (f" ({t.progress:.0%})" if t.state == "running" else "")
        + (f" worker={t.worker_id}" if t.worker_id else "")
        + (f" error={t.error}" if t.error else "")
        for t in r.tasks
    ) or "(no tasks)"


# ---------------------------------------------------------------------- mq


@command("mq.topic.list", "[-broker host:port] list topics")
def mq_topic_list(env: ShellEnv, args) -> str:
    from ..mq import MqClient

    p = argparse.ArgumentParser(prog="mq.topic.list")
    p.add_argument("-broker", default="localhost:17777")
    a = p.parse_args(args)
    c = MqClient(a.broker)
    try:
        topics = c.topics()
        return (
            "\n".join(f"{ns}/{name}  partitions={n}" for ns, name, n in topics)
            or "(no topics)"
        )
    finally:
        c.close()


@command("mq.topic.configure", "-topic name [-partitions N] [-broker ...]")
def mq_topic_configure(env: ShellEnv, args) -> str:
    from ..mq import MqClient

    p = argparse.ArgumentParser(prog="mq.topic.configure")
    p.add_argument("-broker", default="localhost:17777")
    p.add_argument("-topic", required=True)
    p.add_argument("-namespace", default="default")
    p.add_argument("-partitions", type=int, default=4)
    a = p.parse_args(args)
    c = MqClient(a.broker)
    try:
        c.configure_topic(a.topic, a.partitions, a.namespace)
        return f"configured {a.namespace}/{a.topic} with {a.partitions} partitions"
    finally:
        c.close()


@command("mq.topic.describe", "-topic name [-broker ...] partition offsets")
def mq_topic_describe(env: ShellEnv, args) -> str:
    from ..mq import MqClient

    p = argparse.ArgumentParser(prog="mq.topic.describe")
    p.add_argument("-broker", default="localhost:17777")
    p.add_argument("-topic", required=True)
    p.add_argument("-namespace", default="default")
    a = p.parse_args(args)
    c = MqClient(a.broker)
    try:
        infos = c.partition_info(a.topic, a.namespace)
        return "\n".join(
            f"partition {pi.partition}: offsets [{pi.earliest_offset}, "
            f"{pi.next_offset}) ({pi.next_offset - pi.earliest_offset} records)"
            for pi in infos
        )
    finally:
        c.close()


# ------------------------------------------------------------------- blobs


@command("upload", "upload a local file; prints fid")
def upload(env: ShellEnv, args) -> str:
    from ..client.operations import Operations

    p = argparse.ArgumentParser(prog="upload")
    p.add_argument("path")
    p.add_argument("-collection", default="")
    p.add_argument("-replication", default="")
    a = p.parse_args(args)
    ops = Operations(env.master_addr)
    try:
        with open(a.path, "rb") as f:
            fid = ops.upload(
                f.read(), name=a.path, collection=a.collection,
                replication=a.replication,
            )
        return fid
    finally:
        ops.close()


@command("download", "download -fid <fid> -o <path>")
def download(env: ShellEnv, args) -> str:
    from ..client.operations import Operations

    p = argparse.ArgumentParser(prog="download")
    p.add_argument("-fid", required=True)
    p.add_argument("-o", required=True)
    a = p.parse_args(args)
    ops = Operations(env.master_addr)
    try:
        data = ops.read(a.fid)
        with open(a.o, "wb") as f:
            f.write(data)
        return f"{len(data)} bytes -> {a.o}"
    finally:
        ops.close()


# -------------------------------------------------------------------- lock


@command("lock", "hold the exclusive cluster admin lease for this session")
def lock_cmd(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="lock")
    p.add_argument("-ttl", type=float, default=600.0)
    a = p.parse_args(args)
    env.admin_token = env.master.lock(
        "admin", env.owner, ttl=a.ttl, token=env.admin_token, wait=5.0
    )
    return f"locked as {env.owner} (ttl {a.ttl:.0f}s; renew with `lock`)"


@command("unlock", "release this session's cluster admin lease")
def unlock_cmd(env: ShellEnv, args) -> str:
    if not env.admin_token:
        return "not holding the admin lease"
    ok = env.master.unlock("admin", env.admin_token)
    env.admin_token = ""
    return "unlocked" if ok else "lease already expired"


@command("lock.status", "show live cluster leases")
def lock_status_cmd(env: ShellEnv, args) -> str:
    rows = env.master.lock_status()
    if not rows:
        return "no live leases"
    return "\n".join(
        f"{name:24s} {owner:24s} {remaining:6.1f}s left"
        for name, owner, remaining in rows
    )


# ------------------------------------------------------- remote storage


def _remote_post(env: "ShellEnv", op: str, body: dict) -> str:
    import json as _json

    import requests as rq

    r = rq.post(
        service_url(env.filer_addr, f"/~remote/{op}"),
        data=_json.dumps(body),
        timeout=300,
    )
    try:
        payload = r.json()
    except ValueError:
        payload = {"error": r.text[:200]}
    if r.status_code != 200:
        return f"error: {payload.get('error', r.status_code)}"
    return ", ".join(f"{k}={v}" for k, v in payload.items())


@command(
    "remote.configure",
    "-name n -endpoint http://host:port [-accessKey k -secretKey s -region r]",
)
def remote_configure(env: ShellEnv, args) -> str:
    """Store an S3-compatible remote's credentials in the filer
    (reference remote.configure)."""
    p = argparse.ArgumentParser(prog="remote.configure")
    p.add_argument("-name", required=True)
    p.add_argument("-endpoint", required=True)
    p.add_argument("-accessKey", default="")
    p.add_argument("-secretKey", default="")
    p.add_argument("-region", default="us-east-1")
    a = p.parse_args(args)
    return _remote_post(
        env,
        "configure",
        {
            "name": a.name,
            "endpoint": a.endpoint,
            "access_key": a.accessKey,
            "secret_key": a.secretKey,
            "region": a.region,
        },
    )


@command(
    "remote.mount",
    "-dir /path -remote name -bucket b [-prefix p] (lazy cloud mount)",
)
def remote_mount(env: ShellEnv, args) -> str:
    """Materialize a bucket listing as a filer directory; file bytes
    stream through on read until remote.cache pins them
    (reference remote.mount + filer_lazy_remote)."""
    p = argparse.ArgumentParser(prog="remote.mount")
    p.add_argument("-dir", required=True)
    p.add_argument("-remote", required=True)
    p.add_argument("-bucket", required=True)
    p.add_argument("-prefix", default="")
    a = p.parse_args(args)
    return _remote_post(
        env,
        "mount",
        {
            "dir": a.dir,
            "remote": a.remote,
            "bucket": a.bucket,
            "prefix": a.prefix,
        },
    )


@command("remote.unmount", "-dir /path (drop the mount view; remote untouched)")
def remote_unmount(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="remote.unmount")
    p.add_argument("-dir", required=True)
    a = p.parse_args(args)
    return _remote_post(env, "unmount", {"dir": a.dir})


@command("remote.cache", "-path /file (pin remote bytes into local chunks)")
def remote_cache(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="remote.cache")
    p.add_argument("-path", required=True)
    a = p.parse_args(args)
    return _remote_post(env, "cache", {"path": a.path})


@command("remote.uncache", "-path /file (drop local copy, keep mapping)")
def remote_uncache(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="remote.uncache")
    p.add_argument("-path", required=True)
    a = p.parse_args(args)
    return _remote_post(env, "uncache", {"path": a.path})


# ------------------------------------------------------------ volume.balance


def _balance_plan(topo, collection: str):
    """Greedy per-disk-type move plan toward equal fullness ratios
    (reference command_volume_balance.go balanceVolumeServers: ratio =
    volumes / max_volume_count per disk type; move from the fullest
    node to the emptiest while the spread shrinks)."""
    nodes = list(topo.nodes)
    disk_types = sorted(
        {(v.disk_type or "hdd") for n in nodes for v in n.volumes} or {"hdd"}
    )
    plan: list[tuple[int, str, object, object]] = []  # vid, col, src, dst
    for dt in disk_types:
        entries = []
        for n in nodes:
            vols = {
                v.id: v
                for v in n.volumes
                if (v.disk_type or "hdd") == dt
                and (not collection or v.collection == collection)
            }
            entries.append(
                {
                    "node": n,
                    "vols": vols,
                    # replica safety: a volume must never move to a node
                    # already holding ANY copy of it (regardless of
                    # collection filter / disk type)
                    "all_vids": {v.id for v in n.volumes},
                    "cap": max(int(n.max_volume_count) or 8, 1),
                }
            )
        if len(entries) < 2:
            continue
        while True:
            entries.sort(key=lambda e: len(e["vols"]) / e["cap"])
            lo, hi = entries[0], entries[-1]
            # does moving one volume from hi to lo reduce the spread?
            if (len(hi["vols"]) - 1) / hi["cap"] < (len(lo["vols"]) + 1) / lo[
                "cap"
            ] - 1e-9:
                break
            cand = next(
                (
                    v
                    for v in hi["vols"].values()
                    if v.id not in lo["all_vids"] and not v.read_only
                ),
                None,
            )
            if cand is None:
                break
            plan.append((cand.id, cand.collection, hi["node"], lo["node"]))
            del hi["vols"][cand.id]
            hi["all_vids"].discard(cand.id)
            lo["vols"][cand.id] = cand
            lo["all_vids"].add(cand.id)
    return plan


@command(
    "volume.balance",
    "[-collection c] [-apply] (plan/execute moves toward equal fullness per disk type)",
    mutating=True,
)
def volume_balance(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.balance")
    p.add_argument("-collection", default="")
    p.add_argument("-apply", action="store_true")
    a = p.parse_args(args)
    topo = env.master.topology()
    plan = _balance_plan(topo, a.collection)
    if not plan:
        return "already balanced"
    lines = [
        f"move volume {vid} ({col or 'default'}): {src.id} -> {dst.id}"
        for vid, col, src, dst in plan
    ]
    if not a.apply:
        return "\n".join(lines) + f"\n{len(plan)} move(s) planned (use -apply)"
    done = []
    for (vid, col, src, dst), line in zip(plan, lines):
        dst_grpc = f"{dst.location.url.split(':')[0]}:{dst.location.grpc_port}"
        src_grpc = f"{src.location.url.split(':')[0]}:{src.location.grpc_port}"
        cmd = (
            f"volume.move -volumeId {vid} -target {dst_grpc}"
            f" -source {src_grpc}"
        )
        if col:
            cmd += f" -collection {col}"
        out = run_command(env, cmd)
        done.append(f"{line}: {out}")
        # success is ONLY the "moved ..." confirmation; other statuses
        # ("volume N not found", "has no replica at") mean the plan is
        # stale — stop rather than keep applying against it
        if not out.startswith("moved"):
            done.append("error: stopping after failed move")
            break
    return "\n".join(done)


# ---------------------------------------------------------------- s3 family


def _filer_grpc(env: ShellEnv):
    host, _, port = env.filer_addr.partition(":")
    ch = grpc.insecure_channel(f"{host}:{int(port or 8888) + 10000}")
    return ch, rpc.filer_stub(ch)


def _s3_conf_load(stub) -> dict:
    from ..pb import filer_pb2 as fpb
    from ..s3.config import S3_IDENTITY_KV

    r = stub.KvGet(fpb.FilerKvGetRequest(key=S3_IDENTITY_KV), timeout=10)
    if not r.found or not r.value:
        return {"identities": []}
    import json as _json

    try:
        return _json.loads(r.value)
    except _json.JSONDecodeError:
        return {"identities": []}


def _s3_conf_save(stub, conf: dict) -> None:
    from ..pb import filer_pb2 as fpb
    from ..s3.config import S3_IDENTITY_KV

    import json as _json

    stub.KvPut(
        fpb.FilerKvPutRequest(
            key=S3_IDENTITY_KV, value=_json.dumps(conf, indent=2).encode()
        ),
        timeout=10,
    )


@command(
    "s3.configure",
    "-user name [-actions A,B] [-access_key K -secret_key S] [-delete] (identity CRUD)",
)
def s3_configure(env: ShellEnv, args) -> str:
    """Reference command_s3_configure.go: maintain the gateway identity
    config (persisted in the filer; every gateway reloads it live)."""
    p = argparse.ArgumentParser(prog="s3.configure")
    p.add_argument("-user", required=True)
    p.add_argument("-actions", default="")
    p.add_argument("-access_key", default="")
    p.add_argument("-secret_key", default="")
    p.add_argument("-delete", action="store_true")
    a = p.parse_args(args)
    ch, stub = _filer_grpc(env)
    with ch:
        conf = _s3_conf_load(stub)
        idents = conf.setdefault("identities", [])
        if a.delete:
            before = len(idents)
            conf["identities"] = [i for i in idents if i.get("name") != a.user]
            _s3_conf_save(stub, conf)
            return f"deleted {before - len(conf['identities'])} credential(s) of {a.user}"
        if bool(a.access_key) != bool(a.secret_key):
            return "error: -access_key and -secret_key go together"
        actions = [s for s in a.actions.split(",") if s]
        existing = [i for i in idents if i.get("name") == a.user]
        if a.access_key:
            entry = {
                "name": a.user,
                "accessKey": a.access_key,
                "secretKey": a.secret_key,
                "actions": actions
                or (existing[0].get("actions", ["Admin"]) if existing else ["Admin"]),
            }
            idents[:] = [
                i for i in idents if i.get("accessKey") != a.access_key
            ] + [entry]
        elif actions:
            if not existing:
                return f"error: user {a.user} has no credentials yet (use s3.accesskey.create)"
            for i in existing:
                i["actions"] = actions
        else:
            return "error: nothing to do (-actions or key pair or -delete)"
        _s3_conf_save(stub, conf)
    return f"configured {a.user}"


@command("s3.user.list", "list configured S3 identities")
def s3_user_list(env: ShellEnv, args) -> str:
    ch, stub = _filer_grpc(env)
    with ch:
        conf = _s3_conf_load(stub)
    rows = [
        f"{i.get('name', '?'):20s} {i.get('accessKey', ''):24s} "
        f"{','.join(i.get('actions', [])) or 'policies:' + str(len(i.get('policies', [])))}"
        for i in conf.get("identities", [])
    ]
    return "\n".join(rows) or "no identities configured (gateway is in open mode)"


@command("s3.user.delete", "-user name (drop all the user's credentials)")
def s3_user_delete(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="s3.user.delete")
    p.add_argument("-user", required=True)
    a = p.parse_args(args)
    return s3_configure(env, ["-user", a.user, "-delete"])


@command("s3.accesskey.create", "-user name [-actions A,B] (generate a key pair)")
def s3_accesskey_create(env: ShellEnv, args) -> str:
    from ..s3.config import mint_key_pair

    p = argparse.ArgumentParser(prog="s3.accesskey.create")
    p.add_argument("-user", required=True)
    p.add_argument("-actions", default="")
    a = p.parse_args(args)
    access_key, secret_key = mint_key_pair()
    out = s3_configure(
        env,
        [
            "-user", a.user,
            "-access_key", access_key,
            "-secret_key", secret_key,
        ]
        + (["-actions", a.actions] if a.actions else []),
    )
    if out.startswith("error"):
        return out
    return f"user={a.user}\naccess_key={access_key}\nsecret_key={secret_key}"


@command("s3.accesskey.delete", "-access_key K")
def s3_accesskey_delete(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="s3.accesskey.delete")
    p.add_argument("-access_key", required=True)
    a = p.parse_args(args)
    ch, stub = _filer_grpc(env)
    with ch:
        conf = _s3_conf_load(stub)
        before = len(conf.get("identities", []))
        conf["identities"] = [
            i for i in conf.get("identities", []) if i.get("accessKey") != a.access_key
        ]
        _s3_conf_save(stub, conf)
    return f"deleted {before - len(conf['identities'])} credential(s)"


@command(
    "s3.policy.put",
    "-user name -policy '<json document>' (attach an IAM policy, replacing actions)",
)
def s3_policy_put(env: ShellEnv, args) -> str:
    import json as _json

    p = argparse.ArgumentParser(prog="s3.policy.put")
    p.add_argument("-user", required=True)
    p.add_argument("-policy", required=True)
    a = p.parse_args(args)
    try:
        doc = _json.loads(a.policy)
    except _json.JSONDecodeError as e:
        return f"error: policy is not JSON: {e}"
    if "Statement" not in doc:
        return "error: policy has no Statement"
    ch, stub = _filer_grpc(env)
    with ch:
        conf = _s3_conf_load(stub)
        hit = [i for i in conf.get("identities", []) if i.get("name") == a.user]
        if not hit:
            return f"error: user {a.user} has no credentials yet"
        for i in hit:
            i["policies"] = [doc]
            i["actions"] = []
        _s3_conf_save(stub, conf)
    return f"policy attached to {a.user} ({len(hit)} credential(s))"


@command("s3.policy.get", "-user name")
def s3_policy_get(env: ShellEnv, args) -> str:
    import json as _json

    p = argparse.ArgumentParser(prog="s3.policy.get")
    p.add_argument("-user", required=True)
    a = p.parse_args(args)
    ch, stub = _filer_grpc(env)
    with ch:
        conf = _s3_conf_load(stub)
    for i in conf.get("identities", []):
        if i.get("name") == a.user and i.get("policies"):
            return _json.dumps(i["policies"], indent=2)
    return f"user {a.user} has no attached policies"


@command("s3.policy.delete", "-user name (detach policies, restoring action-based auth)")
def s3_policy_delete(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="s3.policy.delete")
    p.add_argument("-user", required=True)
    p.add_argument("-actions", default="Admin")
    a = p.parse_args(args)
    ch, stub = _filer_grpc(env)
    with ch:
        conf = _s3_conf_load(stub)
        hit = [i for i in conf.get("identities", []) if i.get("name") == a.user]
        for i in hit:
            i.pop("policies", None)
            i["actions"] = [s for s in a.actions.split(",") if s]
        _s3_conf_save(stub, conf)
    return f"policies detached from {len(hit)} credential(s)"


@command("s3.bucket.list", "list buckets (via the filer)")
def s3_bucket_list(env: ShellEnv, args) -> str:
    from ..pb import filer_pb2 as fpb

    ch, stub = _filer_grpc(env)
    rows = []
    with ch:
        for r in stub.ListEntries(
            fpb.ListEntriesRequest(directory="/buckets", limit=10000),
            timeout=30,
        ):
            if r.entry.is_directory and not r.entry.name.startswith("."):
                rows.append(r.entry.name)
    return "\n".join(sorted(rows)) or "no buckets"


@command("s3.bucket.create", "-name bucket")
def s3_bucket_create(env: ShellEnv, args) -> str:
    from ..pb import filer_pb2 as fpb

    p = argparse.ArgumentParser(prog="s3.bucket.create")
    p.add_argument("-name", required=True)
    a = p.parse_args(args)
    entry = fpb.Entry(name=a.name, is_directory=True)
    entry.attributes.file_mode = 0o40755
    ch, stub = _filer_grpc(env)
    with ch:
        r = stub.LookupDirectoryEntry(
            fpb.LookupEntryRequest(directory="/buckets", name=a.name), timeout=10
        )
        if not r.error:
            return f"bucket {a.name} exists"
        r = stub.CreateEntry(
            fpb.CreateEntryRequest(directory="/buckets", entry=entry), timeout=10
        )
    return r.error or f"created bucket {a.name}"


@command("s3.bucket.delete", "-name bucket [-force] (force = delete objects too)", mutating=True)
def s3_bucket_delete(env: ShellEnv, args) -> str:
    from ..pb import filer_pb2 as fpb

    p = argparse.ArgumentParser(prog="s3.bucket.delete")
    p.add_argument("-name", required=True)
    p.add_argument("-force", action="store_true")
    a = p.parse_args(args)
    ch, stub = _filer_grpc(env)
    with ch:
        if not a.force:
            for r in stub.ListEntries(
                fpb.ListEntriesRequest(directory=f"/buckets/{a.name}", limit=2),
                timeout=10,
            ):
                return f"error: bucket {a.name} not empty (use -force)"
        r = stub.DeleteEntry(
            fpb.DeleteEntryRequest(
                directory="/buckets",
                name=a.name,
                is_recursive=True,
                is_delete_data=True,
            ),
            timeout=60,
        )
    if r.error:
        return f"error: {r.error}"
    with contextlib.suppress(Exception):
        env.master.collection_delete(a.name)
    return f"deleted bucket {a.name}"


@command("s3.clean.uploads", "[-timeAgo hours] purge stale multipart uploads")
def s3_clean_uploads(env: ShellEnv, args) -> str:
    import time as _time

    from ..pb import filer_pb2 as fpb

    p = argparse.ArgumentParser(prog="s3.clean.uploads")
    p.add_argument("-timeAgo", type=float, default=24.0)
    a = p.parse_args(args)
    cutoff = _time.time() - a.timeAgo * 3600
    ch, stub = _filer_grpc(env)
    removed = []
    with ch:
        buckets = [
            r.entry.name
            for r in stub.ListEntries(
                fpb.ListEntriesRequest(directory="/buckets", limit=10000),
                timeout=30,
            )
            if r.entry.is_directory and not r.entry.name.startswith(".")
        ]
        for b in buckets:
            updir = f"/buckets/{b}/.uploads"
            for r in stub.ListEntries(
                fpb.ListEntriesRequest(directory=updir, limit=10000), timeout=30
            ):
                if r.entry.attributes.mtime < cutoff:
                    rr = stub.DeleteEntry(
                        fpb.DeleteEntryRequest(
                            directory=updir,
                            name=r.entry.name,
                            is_recursive=True,
                            is_delete_data=True,
                        ),
                        timeout=60,
                    )
                    if not rr.error:
                        removed.append(f"{b}/{r.entry.name}")
    return "\n".join(removed) or "no stale uploads"


# ------------------------------------------------------------ raft cluster


def _raft_stub(env: ShellEnv, master: str | None = None):
    addr = master or env.master_addr
    host, _, port = addr.partition(":")
    ch = grpc.insecure_channel(f"{host}:{int(port or 9333) + 10000}")
    return ch, rpc.Stub(ch, rpc.RAFT_SERVICE)


@command("cluster.raft.ps", "raft membership + roles of every master")
def cluster_raft_ps(env: ShellEnv, args) -> str:
    ch, stub = _raft_stub(env)
    with ch:
        st = stub.RaftStatus(pb.RaftStatusRequest(), timeout=10)
    rows = [
        f"node {st.node_id}: {st.role} term={st.term} "
        f"commit={st.commit_index} applied={st.applied_index}"
    ]
    rows.append(f"leader: {st.leader or '?'}")
    rows.append(
        "members: " + ", ".join(sorted({st.node_id, *st.peers}))
    )
    return "\n".join(rows)


def _raft_change(env: ShellEnv, op: str, server: str) -> str:
    """Route the change to the LEADER (retrying once on redirect)."""
    target = None
    for _ in range(3):
        ch, stub = _raft_stub(env, target)
        with ch:
            r = stub.RaftChangeMembership(
                pb.RaftChangeRequest(op=op, server=server), timeout=15
            )
        if r.error == "not the leader" and r.leader:
            target = r.leader
            continue
        if r.error:
            return f"error: {r.error}"
        return f"members now: {', '.join(r.members)}"
    return "error: could not find the raft leader"


@command(
    "cluster.raft.add",
    "-server host:port (grow the master raft group by one)",
    mutating=True,
)
def cluster_raft_add(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="cluster.raft.add")
    p.add_argument("-server", required=True)
    a = p.parse_args(args)
    return _raft_change(env, "add", a.server)


@command(
    "cluster.raft.remove",
    "-server host:port (shrink the master raft group by one)",
    mutating=True,
)
def cluster_raft_remove(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="cluster.raft.remove")
    p.add_argument("-server", required=True)
    a = p.parse_args(args)
    return _raft_change(env, "remove", a.server)


# -------------------------------------------------------------- mq schemas


@command(
    "mq.schema.set",
    "-topic name -schema '<json>' [-namespace ns] [-broker host:port]",
)
def mq_schema_set(env: ShellEnv, args) -> str:
    from ..pb import mq_pb2 as mqpb

    p = argparse.ArgumentParser(prog="mq.schema.set")
    p.add_argument("-topic", required=True)
    p.add_argument("-schema", required=True)
    p.add_argument("-namespace", default="default")
    p.add_argument("-broker", default="localhost:17777")
    a = p.parse_args(args)
    with grpc.insecure_channel(a.broker) as ch:
        r = rpc.Stub(ch, rpc.MQ_SERVICE).RegisterSchema(
            mqpb.RegisterSchemaRequest(
                topic=mqpb.Topic(namespace=a.namespace, name=a.topic),
                schema_json=a.schema,
            ),
            timeout=10,
        )
    return f"error: {r.error}" if r.error else f"schema registered for {a.topic}"


@command("mq.schema.get", "-topic name [-namespace ns] [-broker host:port]")
def mq_schema_get(env: ShellEnv, args) -> str:
    from ..pb import mq_pb2 as mqpb

    p = argparse.ArgumentParser(prog="mq.schema.get")
    p.add_argument("-topic", required=True)
    p.add_argument("-namespace", default="default")
    p.add_argument("-broker", default="localhost:17777")
    a = p.parse_args(args)
    with grpc.insecure_channel(a.broker) as ch:
        r = rpc.Stub(ch, rpc.MQ_SERVICE).GetSchema(
            mqpb.GetSchemaRequest(
                topic=mqpb.Topic(namespace=a.namespace, name=a.topic)
            ),
            timeout=10,
        )
    return r.schema_json or f"no schema registered for {a.topic}"


# --------------------------------------------------- r4 ops-surface batch


@command(
    "volume.deleteEmpty",
    "[-collection c] [-force] (drop volumes holding zero live files)",
    mutating=True,
)
def volume_delete_empty(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.deleteEmpty")
    p.add_argument("-collection", default="")
    p.add_argument("-force", action="store_true")
    a = p.parse_args(args)
    topo = env.master.topology()
    plan: list[tuple[int, object]] = []
    for n in topo.nodes:
        for v in n.volumes:
            if v.file_count == 0 and (
                not a.collection or v.collection == a.collection
            ):
                plan.append((v.id, n))
    if not plan:
        return "no empty volumes"
    if not a.force:
        return "\n".join(
            f"would delete empty volume {vid} on {n.id}" for vid, n in plan
        ) + f"\n{len(plan)} deletion(s) planned (use -force)"
    done = []
    for vid, n in plan:
        with volume_lease(env, vid):
            ch, stub = _volume_stub(n.location)
            with ch:
                # freeze writes, then RE-CHECK emptiness on the live
                # volume server (the planning snapshot is heartbeat-
                # stale; a write landing in between must not be
                # destroyed — reference guards this the same way)
                stub.VolumeMarkReadonly(
                    pb.VolumeCommandRequest(volume_id=vid), timeout=30
                )
                st = stub.VolumeServerStatus(
                    pb.VolumeServerStatusRequest(), timeout=30
                )
                live = next(
                    (v for v in st.volumes if v.id == vid), None
                )
                if live is None or live.file_count > 0:
                    stub.VolumeMarkWritable(
                        pb.VolumeCommandRequest(volume_id=vid), timeout=30
                    )
                    done.append(
                        f"skipped volume {vid} on {n.id}: no longer empty"
                    )
                    continue
                stub.VolumeDelete(
                    pb.VolumeCommandRequest(volume_id=vid), timeout=60
                )
        done.append(f"deleted empty volume {vid} on {n.id}")
    return "\n".join(done)


@command("fs.cp", "fs.cp /src /dst (server-side file copy via the filer)")
def fs_cp(env: ShellEnv, args) -> str:
    import requests as rq

    if len(args) != 2:
        return "usage: fs.cp /src /dst"
    src, dst = args
    r = rq.get(_filer_url(env, src), stream=True, timeout=300)
    if r.status_code != 200 or r.headers.get("X-Filer-Listing") == "true":
        return f"error: {src}: not a readable file"
    total = 0

    def chunks():
        nonlocal total
        for c in r.iter_content(1 << 20):  # constant memory on huge files
            total += len(c)
            yield c

    w = rq.post(
        _filer_url(env, dst),
        data=chunks(),
        headers={"Content-Type": r.headers.get("Content-Type", "")},
        timeout=300,
    )
    if w.status_code != 201:
        return f"error: write {dst}: {w.status_code}"
    return f"copied {src} -> {dst} ({total} bytes)"


def _lookup_entry(env: ShellEnv, path: str):
    """-> (entry, None) or (None, error string); one shared
    parse+lookup for the fs.* metadata commands."""
    from ..pb import filer_pb2 as fpb

    directory, _, name = path.rstrip("/").rpartition("/")
    ch, stub = _filer_grpc(env)
    with ch:
        r = stub.LookupDirectoryEntry(
            fpb.LookupEntryRequest(directory=directory or "/", name=name),
            timeout=10,
        )
    if r.error:
        return None, f"error: {r.error}"
    return r.entry, None


@command("fs.stat", "fs.stat /path (full entry metadata)")
def fs_stat(env: ShellEnv, args) -> str:
    if not args:
        return "usage: fs.stat /path"
    path = args[0]
    e, err = _lookup_entry(env, path)
    if err:
        return err
    a = e.attributes
    lines = [
        f"path:      {path}",
        f"type:      {'directory' if e.is_directory else 'file'}",
        f"size:      {a.file_size}",
        f"mode:      {oct(a.file_mode)}",
        f"uid:gid:   {a.uid}:{a.gid}",
        f"mtime:     {a.mtime}",
        f"mime:      {a.mime or '-'}",
        f"chunks:    {len(e.chunks)}",
        f"inline:    {len(e.content)} bytes",
        f"hardlinks: {max(e.hard_link_counter, 1)}",
    ]
    if a.symlink_target:
        lines.append(f"symlink -> {a.symlink_target}")
    if e.extended:
        lines.append("extended:  " + ", ".join(sorted(e.extended)))
    return "\n".join(lines)


@command("fs.verify", "fs.verify /path (read every byte; report size+md5)")
def fs_verify(env: ShellEnv, args) -> str:
    import hashlib

    import requests as rq

    if not args:
        return "usage: fs.verify /path"
    r = rq.get(_filer_url(env, args[0]), stream=True, timeout=600)
    if r.status_code != 200:
        return f"error: {r.status_code}"
    h = hashlib.md5()
    total = 0
    for chunk in r.iter_content(1 << 20):
        h.update(chunk)
        total += len(chunk)
    return f"{args[0]}: {total} bytes readable, md5 {h.hexdigest()}"


@command(
    "cluster.lock.ring",
    "[-filers a,b,...] live leases across the filer lock ring",
)
def cluster_lock_ring(env: ShellEnv, args) -> str:
    from ..filer.lock_ring import DlmClient

    p = argparse.ArgumentParser(prog="cluster.lock.ring")
    p.add_argument("-filers", default="")
    a = p.parse_args(args)
    if a.filers:
        members = [m.strip() for m in a.filers.split(",") if m.strip()]
    else:
        host, _, port = env.filer_addr.partition(":")
        members = [f"{host}:{int(port or 8888) + 10000}"]
    c = DlmClient(members)
    try:
        rows = c.status()
    finally:
        c.close()
    return (
        "\n".join(f"{n:40s} {o:20s} {r:6.1f}s" for n, o, r in rows)
        or "no live leases"
    )


# ------------------------------------------------------------ s3 quotas


def _list_all_entries(stub, directory: str):
    """Full listing with PAGINATION — a flat limit would silently
    undercount directories beyond it."""
    from ..pb import filer_pb2 as fpb

    start = ""
    while True:
        page = list(
            stub.ListEntries(
                fpb.ListEntriesRequest(
                    directory=directory, limit=10000, start_from=start
                ),
                timeout=60,
            )
        )
        for r in page:
            yield r.entry
        if len(page) < 10000:
            return
        start = page[-1].entry.name


def _bucket_usage_bytes(stub, bucket: str) -> int:
    """Recursive size walk of /buckets/<b> over the filer gRPC."""
    total = 0
    stack = [f"/buckets/{bucket}"]
    while stack:
        d = stack.pop()
        for e in _list_all_entries(stub, d):
            if e.is_directory:
                stack.append(f"{d}/{e.name}")
            else:
                total += e.attributes.file_size or (
                    len(e.content) + sum(c.size for c in e.chunks)
                )
    return total


@command(
    "s3.bucket.quota.set",
    "-name bucket -bytes N (0 = remove the quota)",
)
def s3_bucket_quota_set(env: ShellEnv, args) -> str:
    from ..pb import filer_pb2 as fpb

    p = argparse.ArgumentParser(prog="s3.bucket.quota.set")
    p.add_argument("-name", required=True)
    p.add_argument("-bytes", type=int, required=True)
    a = p.parse_args(args)
    ch, stub = _filer_grpc(env)
    with ch:
        key = f"quota/{a.name}".encode()
        if a.bytes > 0:
            stub.KvPut(
                fpb.FilerKvPutRequest(key=key, value=str(a.bytes).encode()),
                timeout=10,
            )
            return f"quota for {a.name}: {a.bytes:,} bytes"
        stub.KvPut(fpb.FilerKvPutRequest(key=key, value=b""), timeout=10)
        stub.KvPut(
            fpb.FilerKvPutRequest(
                key=f"quota-exceeded/{a.name}".encode(), value=b""
            ),
            timeout=10,
        )
        return f"quota removed for {a.name}"


@command("s3.bucket.quota.get", "-name bucket")
def s3_bucket_quota_get(env: ShellEnv, args) -> str:
    from ..pb import filer_pb2 as fpb

    p = argparse.ArgumentParser(prog="s3.bucket.quota.get")
    p.add_argument("-name", required=True)
    a = p.parse_args(args)
    ch, stub = _filer_grpc(env)
    with ch:
        r = stub.KvGet(
            fpb.FilerKvGetRequest(key=f"quota/{a.name}".encode()), timeout=10
        )
        usage = _bucket_usage_bytes(stub, a.name)
    if not r.found or not r.value:
        return f"{a.name}: no quota (usage {usage:,} bytes)"
    quota = int(r.value)
    return (
        f"{a.name}: quota {quota:,} bytes, usage {usage:,} "
        f"({100.0 * usage / quota:.1f}%)"
    )


@command(
    "s3.bucket.quota.enforce",
    "check every quota'd bucket; flag over-quota ones read-only for the gateway",
    mutating=True,
)
def s3_bucket_quota_enforce(env: ShellEnv, args) -> str:
    """Reference command_s3_bucketquota.go: enforcement is a periodic
    sweep (cron/worker), not per-request accounting — the gateway just
    honors the exceeded flag on writes."""
    from ..pb import filer_pb2 as fpb

    ch, stub = _filer_grpc(env)
    out = []
    with ch:
        buckets = [
            e.name
            for e in _list_all_entries(stub, "/buckets")
            if e.is_directory and not e.name.startswith(".")
        ]
        for b in buckets:
            q = stub.KvGet(
                fpb.FilerKvGetRequest(key=f"quota/{b}".encode()), timeout=10
            )
            if not q.found or not q.value:
                continue
            quota = int(q.value)
            usage = _bucket_usage_bytes(stub, b)
            flag_key = f"quota-exceeded/{b}".encode()
            if usage > quota:
                stub.KvPut(
                    fpb.FilerKvPutRequest(key=flag_key, value=b"1"), timeout=10
                )
                out.append(
                    f"{b}: OVER quota ({usage:,} > {quota:,}) — writes blocked"
                )
            else:
                stub.KvPut(
                    fpb.FilerKvPutRequest(key=flag_key, value=b""), timeout=10
                )
                out.append(f"{b}: ok ({usage:,} / {quota:,})")
    return "\n".join(out) or "no buckets carry quotas"


@command("fs.meta.cat", "fs.meta.cat /path (raw entry metadata as JSON)")
def fs_meta_cat(env: ShellEnv, args) -> str:
    import json as _json

    if not args:
        return "usage: fs.meta.cat /path"
    e, err = _lookup_entry(env, args[0])
    if err:
        return err
    a = e.attributes
    doc = {
        "name": e.name,
        "isDirectory": e.is_directory,
        "attributes": {
            "mtime": a.mtime,
            "crtime": a.crtime,
            "fileMode": a.file_mode,
            "uid": a.uid,
            "gid": a.gid,
            "mime": a.mime,
            "ttlSec": a.ttl_sec,
            "userName": a.user_name,
            "groupNames": list(a.group_names),
            "symlinkTarget": a.symlink_target,
            "md5": a.md5.hex(),
            "fileSize": a.file_size,
            "rdev": a.rdev,
            "inode": a.inode,
        },
        "chunks": [
            {
                "fid": c.fid,
                "offset": c.offset,
                "size": c.size,
                "modifiedTsNs": c.modified_ts_ns,
                "etag": c.etag,
                "cipherKey": c.cipher_key.hex(),
                "isCompressed": c.is_compressed,
                "isChunkManifest": c.is_chunk_manifest,
            }
            for c in e.chunks
        ],
        "extended": {k: v.hex() for k, v in e.extended.items()},
        "hardLinkId": e.hard_link_id.hex(),
        "hardLinkCounter": e.hard_link_counter,
        "wormEnforcedAtTsNs": e.worm_enforced_at_ts_ns,
        "inlineContentBytes": len(e.content),
    }
    return _json.dumps(doc, indent=2)


# ---------------------------------------------- round-5 gap closure
# (verdict-directed families: volume.copy/mount/unmount/configure,
# vacuum toggles, tier.move, mq compact/truncate, remote.meta.sync,
# mount/fs.configure, cluster.ps, worker.list, maintenance.config)


@command(
    "volume.copy",
    "-volumeId N -target host:grpcPort [-source host:grpcPort] "
    "(copy a volume; source keeps its replica)",
    mutating=True,
)
def volume_copy(env: ShellEnv, args) -> str:
    """Reference volume.copy: pull .dat/.idx/.vif onto the target and
    mount there; unlike volume.move the source keeps serving."""
    p = argparse.ArgumentParser(prog="volume.copy")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-target", required=True)
    p.add_argument("-source", default="")
    p.add_argument("-collection", default="")
    a = p.parse_args(args)
    src_grpc = a.source
    if not src_grpc:
        loc = _locate_volume(env, a.volumeId)
        src_grpc = f"{loc.url.split(':')[0]}:{loc.grpc_port}"
    with grpc.insecure_channel(a.target) as ch:
        r = rpc.Stub(ch, rpc.VOLUME_SERVICE).VolumeCopy(
            pb.EcShardsCopyRequest(
                volume_id=a.volumeId,
                collection=a.collection,
                source_url=src_grpc,
            ),
            timeout=3600,
        )
    if r.error:
        return f"error: {r.error}"
    return f"copied volume {a.volumeId} {src_grpc} -> {a.target}"


@command(
    "volume.mount",
    "-volumeId N -node host:grpcPort [-collection c] (load volume files)",
    mutating=True,
)
def volume_mount(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.mount")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-node", required=True)
    p.add_argument("-collection", default="")
    a = p.parse_args(args)
    with grpc.insecure_channel(a.node) as ch:
        r = rpc.Stub(ch, rpc.VOLUME_SERVICE).VolumeMount(
            pb.AllocateVolumeRequest(
                volume_id=a.volumeId, collection=a.collection
            ),
            timeout=60,
        )
    return f"error: {r.error}" if r.error else f"mounted volume {a.volumeId} on {a.node}"


@command(
    "volume.unmount",
    "-volumeId N -node host:grpcPort (release a volume, keep its files)",
    mutating=True,
)
def volume_unmount(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.unmount")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-node", required=True)
    a = p.parse_args(args)
    with grpc.insecure_channel(a.node) as ch:
        r = rpc.Stub(ch, rpc.VOLUME_SERVICE).VolumeUnmount(
            pb.VolumeCommandRequest(volume_id=a.volumeId), timeout=60
        )
    return f"error: {r.error}" if r.error else f"unmounted volume {a.volumeId} on {a.node}"


@command(
    "volume.configure.replication",
    "-volumeId N -replication xyz (rewrite replica placement in place)",
    mutating=True,
)
def volume_configure_replication(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.configure.replication")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-replication", required=True)
    a = p.parse_args(args)
    locs = env.master.lookup(a.volumeId, refresh=True)
    if not locs:
        return f"volume {a.volumeId} not found"
    changed = []
    for loc in locs:
        ch, stub = _volume_stub(loc)
        with ch:
            r = stub.VolumeConfigure(
                pb.VolumeConfigureRequest(
                    volume_id=a.volumeId, replication=a.replication
                ),
                timeout=30,
            )
        if r.error:
            return f"error on {loc.url}: {r.error}"
        changed.append(loc.url)
    return (
        f"volume {a.volumeId} replication -> {a.replication} on "
        + ", ".join(changed)
    )


# not `mutating`: it only reads topology itself and DELEGATES to
# volume.move, which takes the admin + per-volume leases — taking them
# here too would deadlock against our own nested invocation
@command(
    "volume.tier.move",
    "-volumeId N -targetDiskType t (move to a node of that disk type)",
)
def volume_tier_move(env: ShellEnv, args) -> str:
    """Reference volume.tier.move: relocate a volume onto a node whose
    disks match the requested type (readonly -> copy -> delete)."""
    p = argparse.ArgumentParser(prog="volume.tier.move")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-targetDiskType", required=True)
    p.add_argument("-collection", default="")
    a = p.parse_args(args)
    ch0, mstub = _master_channel(env)
    with ch0:
        topo = mstub.Topology(pb.TopologyRequest(), timeout=30)
    src = _locate_volume(env, a.volumeId)
    target = None
    for n in topo.nodes:
        has_vid = any(v.id == a.volumeId for v in n.volumes)
        disk_types = {v.disk_type or "hdd" for v in n.volumes}
        node_addr = f"{n.location.url.split(':')[0]}:{n.location.grpc_port}"
        src_addr = f"{src.url.split(':')[0]}:{src.grpc_port}"
        # an EMPTY node's disk type is unknowable from topology: only
        # the default tier may claim it; never silently call an
        # unknown disk an ssd
        matches = a.targetDiskType in disk_types or (
            not disk_types and a.targetDiskType == "hdd"
        )
        if not has_vid and node_addr != src_addr and matches:
            target = node_addr
            break
    if target is None:
        return f"no {a.targetDiskType} node available for volume {a.volumeId}"
    return run_command(
        env,
        f"volume.move -volumeId {a.volumeId} -target {target}"
        + (f" -collection {a.collection}" if a.collection else ""),
    )


@command("volume.vacuum.disable", "-volumeId N (skip in auto vacuum)", mutating=True)
def volume_vacuum_disable(env: ShellEnv, args) -> str:
    return _vacuum_toggle(env, args, disable=True)


@command("volume.vacuum.enable", "-volumeId N (re-enable auto vacuum)", mutating=True)
def volume_vacuum_enable(env: ShellEnv, args) -> str:
    return _vacuum_toggle(env, args, disable=False)


def _vacuum_toggle(env: ShellEnv, args, disable: bool) -> str:
    p = argparse.ArgumentParser(
        prog=f"volume.vacuum.{'disable' if disable else 'enable'}"
    )
    p.add_argument("-volumeId", type=int, required=True)
    a = p.parse_args(args)
    ch, stub = _master_channel(env)
    with ch:
        r = stub.VacuumControl(
            pb.VacuumControlRequest(volume_id=a.volumeId, disable=disable),
            timeout=30,
        )
    if r.error:
        return f"error: {r.error}"
    state = "disabled" if disable else "enabled"
    return f"auto vacuum {state} for volume {a.volumeId}"


def _master_channel(env: ShellEnv, service: str = ""):
    host, _, port = env.master_addr.partition(":")
    ch = grpc.insecure_channel(f"{host}:{int(port or 9333) + 10000}")
    return ch, rpc.Stub(ch, service or rpc.MASTER_SERVICE)


@command("mq.topic.compact", "-topic name [-broker ...] (archive sealed segments now)")
def mq_topic_compact(env: ShellEnv, args) -> str:
    from ..pb import mq_pb2 as mq

    p = argparse.ArgumentParser(prog="mq.topic.compact")
    p.add_argument("-broker", default="localhost:17777")
    p.add_argument("-topic", required=True)
    p.add_argument("-ns", default="default")
    a = p.parse_args(args)
    with grpc.insecure_channel(a.broker) as ch:
        r = rpc.mq_stub(ch).CompactTopic(
            mq.CompactTopicRequest(ns=a.ns, name=a.topic), timeout=600
        )
    if r.error:
        return f"error: {r.error}"
    return f"archived {r.archived_segments} segments of {a.ns}/{a.topic}"


@command(
    "mq.topic.truncate",
    "-topic name [-partition P] [-beforeOffset N] (drop old records)",
)
def mq_topic_truncate(env: ShellEnv, args) -> str:
    from ..pb import mq_pb2 as mq

    p = argparse.ArgumentParser(prog="mq.topic.truncate")
    p.add_argument("-broker", default="localhost:17777")
    p.add_argument("-topic", required=True)
    p.add_argument("-ns", default="default")
    p.add_argument("-partition", type=int, default=-1)
    p.add_argument("-beforeOffset", type=int, default=-1)
    a = p.parse_args(args)
    with grpc.insecure_channel(a.broker) as ch:
        r = rpc.mq_stub(ch).TruncateTopic(
            mq.TruncateTopicRequest(
                ns=a.ns,
                name=a.topic,
                partition=a.partition,
                before_offset=a.beforeOffset,
            ),
            timeout=600,
        )
    if r.error:
        return f"error: {r.error}"
    return (
        f"truncated {r.truncated_partitions} partition(s) of "
        f"{a.ns}/{a.topic}"
    )


@command(
    "remote.mount.buckets",
    "-dir /path -remote name [-prefix p] (mount every remote bucket)",
    mutating=True,
)
def remote_mount_buckets(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="remote.mount.buckets")
    p.add_argument("-dir", required=True)
    p.add_argument("-remote", required=True)
    p.add_argument("-prefix", default="")
    a = p.parse_args(args)
    return _remote_post(
        env,
        "mount.buckets",
        {"dir": a.dir, "remote": a.remote, "prefix": a.prefix},
    )


@command(
    "remote.meta.sync",
    "-dir /path (refresh mounted remote metadata: add/update/remove)",
    mutating=True,
)
def remote_meta_sync(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="remote.meta.sync")
    p.add_argument("-dir", required=True)
    a = p.parse_args(args)
    return _remote_post(env, "meta.sync", {"dir": a.dir})


@command(
    "mount.configure",
    "[-attrTtl seconds] [-readonly true|false] [-show] "
    "(cluster-wide mount options, read by mounts at startup)",
    mutating=True,
)
def mount_configure(env: ShellEnv, args) -> str:
    import json as _json

    from ..pb import filer_pb2 as fpb

    p = argparse.ArgumentParser(prog="mount.configure")
    p.add_argument("-attrTtl", type=float, default=None)
    p.add_argument("-readonly", default=None, choices=["true", "false"])
    p.add_argument("-show", action="store_true")
    a = p.parse_args(args)
    ch, stub = _filer_grpc(env)
    with ch:
        cur = stub.KvGet(fpb.FilerKvGetRequest(key=b"mount.conf"), timeout=10)
        conf = _json.loads(cur.value) if cur.found else {}
        if a.show or (a.attrTtl is None and a.readonly is None):
            return _json.dumps(conf or {"attr_ttl": 1.0, "readonly": False})
        if a.attrTtl is not None:
            conf["attr_ttl"] = a.attrTtl
        if a.readonly is not None:
            conf["readonly"] = a.readonly == "true"
        stub.KvPut(
            fpb.FilerKvPutRequest(
                key=b"mount.conf", value=_json.dumps(conf).encode()
            ),
            timeout=10,
        )
    return f"mount.conf = {_json.dumps(conf)} (applies to newly started mounts)"


@command(
    "fs.configure",
    "[-locationPrefix /p -collection c -replication xyz -ttlSec n] "
    "[-delete] [-show] (per-path storage rules)",
    mutating=True,
)
def fs_configure(env: ShellEnv, args) -> str:
    import json as _json

    from ..pb import filer_pb2 as fpb

    p = argparse.ArgumentParser(prog="fs.configure")
    p.add_argument("-locationPrefix", default="")
    p.add_argument("-collection", default="")
    p.add_argument("-replication", default="")
    p.add_argument("-ttlSec", type=int, default=0)
    p.add_argument("-delete", action="store_true")
    p.add_argument("-show", action="store_true")
    a = p.parse_args(args)
    ch, stub = _filer_grpc(env)
    with ch:
        cur = stub.KvGet(
            fpb.FilerKvGetRequest(key=b"fs.configure"), timeout=10
        )
        conf = _json.loads(cur.value) if cur.found else {"locations": []}
        if a.show or not a.locationPrefix:
            return _json.dumps(conf, indent=2)
        locs = [
            r for r in conf.get("locations", [])
            if r.get("location_prefix") != a.locationPrefix
        ]
        if not a.delete:
            locs.append(
                {
                    "location_prefix": a.locationPrefix,
                    "collection": a.collection,
                    "replication": a.replication,
                    "ttl_sec": a.ttlSec,
                }
            )
        conf["locations"] = locs
        stub.KvPut(
            fpb.FilerKvPutRequest(
                key=b"fs.configure", value=_json.dumps(conf).encode()
            ),
            timeout=10,
        )
    verb = "deleted rule for" if a.delete else "configured"
    return f"{verb} {a.locationPrefix} ({len(locs)} rule(s) total)"


@command("cluster.ps", "list cluster processes (masters, volume servers, workers)")
def cluster_ps(env: ShellEnv, args) -> str:
    from ..pb import worker_pb2 as wk

    lines = []
    ch, _stub = _master_channel(env)
    with ch:
        try:
            rs = rpc.Stub(ch, rpc.RAFT_SERVICE).RaftStatus(
                pb.RaftStatusRequest(), timeout=10
            )
            lines.append(f"master {rs.node_id} role={rs.role} term={rs.term}")
            for peer in rs.peers:
                lines.append(f"master {peer} (peer)")
        except grpc.RpcError:
            lines.append(f"master {env.master_addr}")
        topo = rpc.Stub(ch, rpc.MASTER_SERVICE).Topology(
            pb.TopologyRequest(), timeout=30
        )
        for n in topo.nodes:
            lines.append(
                f"volumeServer {n.location.url} grpc={n.location.grpc_port} "
                f"volumes={len(n.volumes)} ec={len(n.ec_shards)} "
                f"dc={n.data_center or 'default'} rack={n.rack or 'default'}"
            )
        try:
            ws = rpc.Stub(ch, rpc.WORKER_SERVICE).ListWorkers(
                wk.ListWorkersRequest(), timeout=10
            )
            for w in ws.workers:
                lines.append(
                    f"worker {w.worker_id} caps={','.join(w.capabilities)} "
                    f"active={w.active}"
                )
        except grpc.RpcError:
            pass
    return "\n".join(lines)


@command("worker.list", "list registered maintenance workers")
def worker_list(env: ShellEnv, args) -> str:
    from ..pb import worker_pb2 as wk

    ch, _ = _master_channel(env)
    with ch:
        r = rpc.Stub(ch, rpc.WORKER_SERVICE).ListWorkers(
            wk.ListWorkersRequest(), timeout=10
        )
    if not r.workers:
        return "no workers connected"
    return "\n".join(
        f"{w.worker_id} caps={','.join(w.capabilities)} "
        f"active={w.active}/{w.max_concurrent} backend={w.backend}"
        for w in r.workers
    )


@command(
    "maintenance.config",
    "[-set key=value ...] show or tune the maintenance policy live",
    mutating=True,
)
def maintenance_config(env: ShellEnv, args) -> str:
    import json as _json

    from ..pb import worker_pb2 as wk

    p = argparse.ArgumentParser(prog="maintenance.config")
    p.add_argument("-set", action="append", default=[])
    a = p.parse_args(args)
    ch, _ = _master_channel(env)
    with ch:
        stub = rpc.Stub(ch, rpc.WORKER_SERVICE)
        if a.set:
            req = wk.MaintenanceConfig()
            for kv in a.set:
                key, _, val = kv.partition("=")
                if key == "lifecycle_filer":
                    req.lifecycle_filer = val
                else:
                    try:
                        setattr(req, key, float(val))
                    except (AttributeError, ValueError):
                        return f"unknown or invalid knob {kv!r}"
            r = stub.SetMaintenanceConfig(req, timeout=10)
            if r.error:
                return f"error: {r.error}"
        cfg = stub.GetMaintenanceConfig(
            wk.GetMaintenanceConfigRequest(), timeout=10
        )
    return _json.dumps(
        {
            "ec_auto_fullness": cfg.ec_auto_fullness,
            "ec_quiet_seconds": cfg.ec_quiet_seconds,
            "garbage_threshold": cfg.garbage_threshold,
            "vacuum_interval_seconds": cfg.vacuum_interval_seconds,
            "balance_spread": cfg.balance_spread,
            "lifecycle_interval_seconds": cfg.lifecycle_interval_seconds,
            "lifecycle_filer": cfg.lifecycle_filer,
            "ec_balance_interval_seconds": cfg.ec_balance_interval_seconds,
            "ec_scrub_interval_seconds": cfg.ec_scrub_interval_seconds,
            "ec_rebalance_interval_seconds": (
                cfg.ec_rebalance_interval_seconds
            ),
        }
    )


@command("mq.topic.delete", "-topic name [-broker ...] (drop a topic and its data)")
def mq_topic_delete(env: ShellEnv, args) -> str:
    from ..pb import mq_pb2 as mq

    p = argparse.ArgumentParser(prog="mq.topic.delete")
    p.add_argument("-broker", default="localhost:17777")
    p.add_argument("-topic", required=True)
    p.add_argument("-ns", default="default")
    a = p.parse_args(args)
    with grpc.insecure_channel(a.broker) as ch:
        r = rpc.mq_stub(ch).DeleteTopic(
            mq.DeleteTopicRequest(ns=a.ns, name=a.topic), timeout=120
        )
    return f"error: {r.error}" if r.error else f"deleted topic {a.ns}/{a.topic}"
