"""Shell commands: the ops surface (`weed shell` analog).

Reference: weed/shell/commands.go + command_ec_encode.go:102 (doEcEncode
pipeline: mark readonly -> generate -> mount -> delete source),
command_ec_rebuild.go, command_ec_decode.go, volume.* family.

Each command is a function(env, args) -> str; the registry drives both
the REPL and one-shot `python -m seaweedfs_tpu.shell -c "..."`.
"""

from __future__ import annotations

import argparse
import shlex

import grpc

from ..client.master_client import MasterClient, volume_channel
from ..pb import cluster_pb2 as pb
from ..pb import rpc


class ShellEnv:
    def __init__(self, master: str = "localhost:9333"):
        self.master_addr = master
        self.master = MasterClient(master)

    def close(self):
        self.master.close()


COMMANDS: dict[str, tuple] = {}


def command(name: str, help_text: str):
    def deco(fn):
        COMMANDS[name] = (fn, help_text)
        return fn

    return deco


def run_command(env: ShellEnv, line: str) -> str:
    parts = shlex.split(line)
    if not parts:
        return ""
    name, args = parts[0], parts[1:]
    if name in ("help", "?"):
        return "\n".join(
            f"{n:28s} {h}" for n, (_, h) in sorted(COMMANDS.items())
        )
    entry = COMMANDS.get(name)
    if entry is None:
        return f"unknown command {name!r} (try `help`)"
    try:
        return entry[0](env, args)
    except grpc.RpcError as e:
        return f"error: {e.code().name}: {e.details()}"
    except (LookupError, RuntimeError, OSError) as e:
        return f"error: {e}"


def _locate_volume(env: ShellEnv, vid: int) -> pb.Location:
    locs = env.master.lookup(vid, refresh=True)
    if not locs:
        raise LookupError(f"volume {vid} has no locations")
    return locs[0]


def _volume_stub(loc: pb.Location):
    ch = volume_channel(loc)
    return ch, rpc.volume_stub(ch)


# ----------------------------------------------------------------- cluster


@command("cluster.status", "show nodes and volume/EC counts")
def cluster_status(env: ShellEnv, args) -> str:
    topo = env.master.topology()
    lines = [f"max volume id: {topo.max_volume_id}"]
    for n in topo.nodes:
        lines.append(
            f"  node {n.id} rack={n.rack or '-'} "
            f"volumes={len(n.volumes)} ec={len(n.ec_shards)}"
        )
    return "\n".join(lines)


@command("volume.list", "list volumes and EC shard sets per node")
def volume_list(env: ShellEnv, args) -> str:
    topo = env.master.topology()
    lines = []
    for n in topo.nodes:
        lines.append(f"node {n.id}:")
        for v in sorted(n.volumes, key=lambda v: v.id):
            lines.append(
                f"  volume {v.id} col={v.collection or '-'} size={v.size} "
                f"files={v.file_count} del={v.deleted_count} "
                f"{'RO' if v.read_only else 'RW'} rp={v.replica_placement}"
            )
        for e in sorted(n.ec_shards, key=lambda e: e.id):
            shards = [i for i in range(32) if e.shard_bits & (1 << i)]
            lines.append(
                f"  ec {e.id} col={e.collection or '-'} shards={shards} "
                f"{e.data_shards}+{e.parity_shards} gen={e.generation}"
            )
    return "\n".join(lines) or "no nodes"


@command("volume.grow", "-count N [-collection c] [-replication xyz]")
def volume_grow(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.grow")
    p.add_argument("-count", type=int, default=1)
    p.add_argument("-collection", default="")
    p.add_argument("-replication", default="")
    a = p.parse_args(args)
    vids = env.master.grow(a.count, a.collection, a.replication)
    return f"grew volumes: {vids}"


@command("volume.vacuum", "-volumeId N [-garbageThreshold 0.3]")
def volume_vacuum(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.vacuum")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-garbageThreshold", type=float, default=0.0)
    a = p.parse_args(args)
    out = []
    for loc in env.master.lookup(a.volumeId, refresh=True):
        ch, stub = _volume_stub(loc)
        with ch:
            r = stub.VacuumVolume(
                pb.VacuumRequest(
                    volume_id=a.volumeId, garbage_threshold=a.garbageThreshold
                ),
                timeout=600,
            )
        out.append(f"{loc.url}: reclaimed {r.reclaimed_bytes} (ratio {r.garbage_ratio:.2f})")
    return "\n".join(out)


@command("volume.delete", "-volumeId N")
def volume_delete(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.delete")
    p.add_argument("-volumeId", type=int, required=True)
    a = p.parse_args(args)
    out = []
    for loc in env.master.lookup(a.volumeId, refresh=True):
        ch, stub = _volume_stub(loc)
        with ch:
            r = stub.VolumeDelete(
                pb.VolumeCommandRequest(volume_id=a.volumeId), timeout=60
            )
        out.append(f"{loc.url}: {r.error or 'deleted'}")
    return "\n".join(out)


@command("volume.mark", "-volumeId N -readonly|-writable")
def volume_mark(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="volume.mark")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-readonly", action="store_true")
    p.add_argument("-writable", action="store_true")
    a = p.parse_args(args)
    out = []
    for loc in env.master.lookup(a.volumeId, refresh=True):
        ch, stub = _volume_stub(loc)
        with ch:
            req = pb.VolumeCommandRequest(volume_id=a.volumeId)
            r = (
                stub.VolumeMarkWritable(req, timeout=30)
                if a.writable
                else stub.VolumeMarkReadonly(req, timeout=30)
            )
        out.append(f"{loc.url}: {r.error or 'ok'}")
    return "\n".join(out)


# ---------------------------------------------------------------------- ec


@command("ec.encode", "-volumeId N [-collection c] [-backend cpu|tpu|auto] [-keepSource]")
def ec_encode(env: ShellEnv, args) -> str:
    """Reference doEcEncode (command_ec_encode.go:346): mark replicas
    readonly -> generate shards on one holder -> mount -> delete the
    source volume replicas (unless -keepSource)."""
    p = argparse.ArgumentParser(prog="ec.encode")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    p.add_argument("-backend", default="auto")
    p.add_argument("-keepSource", action="store_true")
    a = p.parse_args(args)

    locs = env.master.lookup(a.volumeId, refresh=True)
    if not locs:
        return f"volume {a.volumeId} not found"
    # 1. mark every replica readonly
    for loc in locs:
        ch, stub = _volume_stub(loc)
        with ch:
            stub.VolumeMarkReadonly(
                pb.VolumeCommandRequest(volume_id=a.volumeId), timeout=30
            )
    # 2. generate on the first holder
    gen_loc = locs[0]
    ch, stub = _volume_stub(gen_loc)
    with ch:
        r = stub.VolumeEcShardsGenerate(
            pb.EcShardsGenerateRequest(
                volume_id=a.volumeId,
                collection=a.collection,
                backend=a.backend,
            ),
            timeout=3600,
        )
        generation = r.generation
        # 3. mount all shards there
        stub.VolumeEcShardsMount(
            pb.EcShardsMountRequest(
                volume_id=a.volumeId, collection=a.collection
            ),
            timeout=60,
        )
    # 4. delete source volume replicas
    if not a.keepSource:
        for loc in locs:
            ch, stub = _volume_stub(loc)
            with ch:
                stub.VolumeDelete(
                    pb.VolumeCommandRequest(volume_id=a.volumeId), timeout=60
                )
    return (
        f"ec.encode volume {a.volumeId}: generation {generation} on "
        f"{gen_loc.url}{' (source kept)' if a.keepSource else ''}"
    )


@command("ec.rebuild", "-volumeId N [-collection c] [-backend cpu|tpu|auto]")
def ec_rebuild(env: ShellEnv, args) -> str:
    p = argparse.ArgumentParser(prog="ec.rebuild")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    p.add_argument("-backend", default="")
    a = p.parse_args(args)
    shard_locs = env.master.lookup_ec(a.volumeId)
    if not shard_locs:
        return f"ec volume {a.volumeId} not found"
    # rebuild on the node holding the most shards
    by_url: dict[str, list[int]] = {}
    loc_by_url = {}
    for sid, locs in shard_locs.items():
        for loc in locs:
            by_url.setdefault(loc.url, []).append(sid)
            loc_by_url[loc.url] = loc
    url = max(by_url, key=lambda u: len(by_url[u]))
    ch, stub = _volume_stub(loc_by_url[url])
    with ch:
        r = stub.VolumeEcShardsRebuild(
            pb.EcShardsRebuildRequest(
                volume_id=a.volumeId, collection=a.collection, backend=a.backend
            ),
            timeout=3600,
        )
        stub.VolumeEcShardsMount(
            pb.EcShardsMountRequest(volume_id=a.volumeId, collection=a.collection),
            timeout=60,
        )
    return f"rebuilt shards {list(r.rebuilt_shard_ids)} on {url}"


@command("ec.decode", "-volumeId N [-collection c]")
def ec_decode(env: ShellEnv, args) -> str:
    """Collect all shards onto the node already holding the most, decode
    there, then clean the EC artifacts off every node (reference
    command_ec_decode.go: collectEcShards -> VolumeEcShardsToVolume ->
    delete shards)."""
    p = argparse.ArgumentParser(prog="ec.decode")
    p.add_argument("-volumeId", type=int, required=True)
    p.add_argument("-collection", default="")
    a = p.parse_args(args)
    shard_locs = env.master.lookup_ec(a.volumeId, refresh=True)
    if not shard_locs:
        return f"ec volume {a.volumeId} not found"
    by_url: dict[str, set[int]] = {}
    loc_by_url = {}
    for sid, locs in shard_locs.items():
        for loc in locs:
            by_url.setdefault(loc.url, set()).add(sid)
            loc_by_url[loc.url] = loc
    target_url = max(by_url, key=lambda u: len(by_url[u]))
    target = loc_by_url[target_url]
    have = by_url[target_url]

    ch, stub = _volume_stub(target)
    with ch:
        copied_index = False
        for sid in sorted(shard_locs):
            if sid in have:
                continue
            src = next(
                l for l in shard_locs[sid] if l.url != target_url
            )
            stub.VolumeEcShardsCopy(
                pb.EcShardsCopyRequest(
                    volume_id=a.volumeId,
                    collection=a.collection,
                    shard_ids=[sid],
                    source_url=f"{src.url.split(':')[0]}:{src.grpc_port}",
                    copy_ecx=not copied_index and not have,
                    copy_ecj=not copied_index and not have,
                    copy_vif=not copied_index and not have,
                    copy_ecsum=not copied_index and not have,
                ),
                timeout=3600,
            )
            copied_index = True
        stub.VolumeEcShardsToVolume(
            pb.EcShardsToVolumeRequest(
                volume_id=a.volumeId, collection=a.collection
            ),
            timeout=3600,
        )
    # clean EC artifacts off the other nodes
    all_sids = sorted(shard_locs)
    for url, sids in by_url.items():
        if url == target_url:
            continue
        ch, stub = _volume_stub(loc_by_url[url])
        with ch:
            stub.VolumeEcShardsUnmount(
                pb.EcShardsUnmountRequest(volume_id=a.volumeId, shard_ids=all_sids),
                timeout=60,
            )
            stub.VolumeEcShardsDelete(
                pb.EcShardsDeleteRequest(
                    volume_id=a.volumeId,
                    collection=a.collection,
                    shard_ids=all_sids,
                ),
                timeout=60,
            )
    return f"decoded ec volume {a.volumeId} back to a normal volume on {target_url}"


# ------------------------------------------------------------------- blobs


@command("upload", "upload a local file; prints fid")
def upload(env: ShellEnv, args) -> str:
    from ..client.operations import Operations

    p = argparse.ArgumentParser(prog="upload")
    p.add_argument("path")
    p.add_argument("-collection", default="")
    p.add_argument("-replication", default="")
    a = p.parse_args(args)
    ops = Operations(env.master_addr)
    try:
        with open(a.path, "rb") as f:
            fid = ops.upload(
                f.read(), name=a.path, collection=a.collection,
                replication=a.replication,
            )
        return fid
    finally:
        ops.close()


@command("download", "download -fid <fid> -o <path>")
def download(env: ShellEnv, args) -> str:
    from ..client.operations import Operations

    p = argparse.ArgumentParser(prog="download")
    p.add_argument("-fid", required=True)
    p.add_argument("-o", required=True)
    a = p.parse_args(args)
    ops = Operations(env.master_addr)
    try:
        data = ops.read(a.fid)
        with open(a.o, "wb") as f:
            f.write(data)
        return f"{len(data)} bytes -> {a.o}"
    finally:
        ops.close()
