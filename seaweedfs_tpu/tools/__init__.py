"""Offline volume tools (weed fix/export/compact equivalents)."""
