"""Offline volume tools (reference `weed fix` / `export` / `compact`):

  python -m seaweedfs_tpu.tools fix     -dir D -volumeId N   rebuild .idx from .dat
  python -m seaweedfs_tpu.tools export  -dir D -volumeId N -o out.tar
  python -m seaweedfs_tpu.tools compact -dir D -volumeId N   offline vacuum
  python -m seaweedfs_tpu.tools scan    -dir D -volumeId N   print needles
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys
import tarfile


from ..storage.types import NeedleValue
from ..storage.volume import Volume
from ..storage.volume_scan import scan_volume_file


def _base(a) -> str:
    return Volume.base_file_name(a.dir, a.collection, a.volumeId)


def _is_tombstone_record(dat_fd: int, stored_off: int, body_size: int) -> bool:
    """Delete marker test: empty body (legacy tombstones / reference
    fix.go:86 semantics) OR an empty-data body whose flags byte carries
    FLAG_IS_TOMBSTONE (0x40, this framework's explicit marker). The
    flags byte sits at header(16) + data_size(4) + data(len) when the
    body exists."""
    if body_size == 0:
        return True
    if body_size > 64:  # real payloads: skip the pread
        return False
    import struct as _struct

    from ..storage.needle import FLAG_IS_TOMBSTONE

    off = stored_off * 8 + 16
    head = os.pread(dat_fd, min(body_size, 64), off)
    if len(head) < 5:
        return False
    (data_len,) = _struct.unpack_from(">I", head, 0)
    if data_len != 0 or len(head) < 5 + data_len:
        return False
    return bool(head[4] & FLAG_IS_TOMBSTONE)


def cmd_fix(a) -> int:
    """Rebuild .idx by replaying the .dat (reference fix.go:86: size>0
    puts, tombstone appends are delete markers)."""
    base = _base(a)
    live: dict[int, NeedleValue] = {}
    records = 0
    scan = None
    try:  # native mmap scanner when available
        from ..utils import native

        ids, offs, sizes, ok = native.scan_dat(base + ".dat")
        scan = (
            (int(a), int(b), int(c), bool(d))
            for a, b, c, d in zip(ids, offs, sizes, ok)
        )
    except Exception:  # .so missing AND unbuildable included
        pass
    if scan is None:
        _, items = scan_volume_file(base + ".dat")
        scan = (
            (i.needle.needle_id, i.offset // 8, i.body_size, i.crc_ok)
            for i in items
        )
    dat_fd = os.open(base + ".dat", os.O_RDONLY)
    try:
        for nid, stored_off, body_size, crc_ok in scan:
            if not crc_ok:
                print(f"skip needle {nid:x} at {stored_off * 8}: bad crc")
                continue
            records += 1
            if _is_tombstone_record(dat_fd, stored_off, body_size):
                live.pop(nid, None)  # delete marker
            else:
                live[nid] = NeedleValue(nid, stored_off, body_size)
    finally:
        os.close(dat_fd)
    # .idx is a replayable journal; a minimal rebuild carries only the
    # surviving entries, ascending
    with open(base + ".idx.tmp", "wb") as f:
        for nid in sorted(live):
            f.write(live[nid].to_bytes())
        f.flush()
        os.fsync(f.fileno())
    os.replace(base + ".idx.tmp", base + ".idx")
    print(f"rebuilt {base}.idx from {records} records ({len(live)} live entries)")
    return 0


def cmd_export(a) -> int:
    base = _base(a)
    live: dict[int, tuple] = {}
    _, items = scan_volume_file(base + ".dat")
    for item in items:
        if item.crc_ok and not (
            item.body_size == 0 or item.needle.is_tombstone
        ):
            live[item.needle.needle_id] = item
        else:
            live.pop(item.needle.needle_id, None)
    with tarfile.open(a.o, "w") as tar:
        for nid, item in sorted(live.items()):
            n = item.needle
            name = n.name.decode(errors="replace") if n.name else f"{nid:x}"
            info = tarfile.TarInfo(name=name)
            info.size = len(n.data)
            info.mtime = n.last_modified
            tar.addfile(info, io.BytesIO(n.data))
    print(f"exported {len(live)} files -> {a.o}")
    return 0


def cmd_compact(a) -> int:
    v = Volume(a.dir, a.volumeId, collection=a.collection, create=False)
    reclaimed = v.vacuum()
    v.close()
    print(f"compacted volume {a.volumeId}: reclaimed {reclaimed} bytes")
    return 0


def _remote_reader(source: str, vid: int, collection: str):
    """CopyFile-backed reader against a live volume server
    ('host:grpcPort') for remote incremental backup."""
    import grpc

    from ..pb import cluster_pb2 as pb
    from ..pb import rpc

    channel = grpc.insecure_channel(source)
    stub = rpc.volume_stub(channel)

    def stream(ext: str, start: int = 0, stop: int = 0):
        for c in stub.CopyFile(
            pb.CopyFileRequest(
                volume_id=vid,
                collection=collection,
                ext=ext,
                start_offset=start,
                stop_offset=stop,
            ),
            timeout=3600,
        ):
            yield c.data

    def read(ext: str, start: int = 0, stop: int = 0) -> bytes:
        return b"".join(stream(ext, start, stop))

    return read, stream, channel


def cmd_backup(a) -> int:
    """Incremental volume backup (reference `weed backup`): .dat is
    append-only, so each run copies only the new tail plus the current
    .idx; the backup directory is itself a loadable volume directory.
    With -from host:grpcPort the source is a LIVE volume server
    (VolumeTailSender analog) instead of local files."""

    from ..storage.super_block import SUPER_BLOCK_SIZE, SuperBlock

    src_base = _base(a)
    os.makedirs(a.o, exist_ok=True)
    name = os.path.basename(src_base)
    dst_base = os.path.join(a.o, name)
    state_path = dst_base + ".backup.state"
    last = 0
    last_rev = -1
    if os.path.exists(state_path):
        try:
            st = json.load(open(state_path))
            last = st["size"]
            last_rev = st.get("revision", -1)
        except (ValueError, KeyError, OSError):
            last = 0
    remote = getattr(a, "source", "")
    channel = None
    if remote:
        import grpc as _grpc

        from ..ec.decoder import record_actual_size
        from ..storage.types import NEEDLE_MAP_ENTRY_SIZE, NeedleValue, actual_offset

        read_remote, stream_remote, channel = _remote_reader(
            remote, a.volumeId, a.collection
        )
        try:
            header = read_remote(".dat", 0, SUPER_BLOCK_SIZE)
        except _grpc.RpcError as e:
            print(f"volume {a.volumeId} not readable on {remote}: {e.code().name}")
            channel.close()
            return 1
        sb = SuperBlock.from_bytes(header)
        revision = sb.compaction_revision
        # snapshot the .idx FIRST and bound the .dat to the extent its
        # entries cover: a write racing the backup must never leave idx
        # entries pointing past the copied data
        idx = read_remote(".idx")
        src_size = SUPER_BLOCK_SIZE
        for off in range(0, len(idx) - len(idx) % NEEDLE_MAP_ENTRY_SIZE,
                         NEEDLE_MAP_ENTRY_SIZE):
            nv = NeedleValue.from_bytes(idx[off : off + NEEDLE_MAP_ENTRY_SIZE])
            if nv.is_deleted:
                continue
            src_size = max(
                src_size,
                actual_offset(nv.offset)
                + record_actual_size(nv.size, sb.version),
            )
    else:
        src_size = os.path.getsize(src_base + ".dat")
        with open(src_base + ".dat", "rb") as f:
            revision = SuperBlock.from_bytes(
                f.read(SUPER_BLOCK_SIZE)
            ).compaction_revision
    if last_rev != -1 and revision != last_rev:
        # compaction shifted every offset — size alone can't detect it
        # when post-vacuum writes regrow the file past the old size
        print(
            f"compaction revision changed ({last_rev} -> {revision}); "
            "taking a fresh full backup"
        )
        last = 0
    elif src_size < last:
        print("source shrank; taking a fresh full backup")
        last = 0
    if not os.path.exists(dst_base + ".dat"):
        last = 0  # stale state without a backup file: full copy
    if last > src_size:
        last = 0  # idx-bounded extent moved backwards: full copy
    mode = "r+b" if last > 0 else "wb"
    try:
        with open(dst_base + ".dat", mode) as dst:
            dst.seek(last)
            copied = 0
            if remote:
                # streamed: a large volume must not be held in RAM
                for chunk in stream_remote(".dat", last, src_size):
                    dst.write(chunk)
                    copied += len(chunk)
            else:
                with open(src_base + ".dat", "rb") as src:
                    src.seek(last)
                    while True:
                        chunk = src.read(1 << 20)
                        if not chunk:
                            break
                        dst.write(chunk)
                        copied += len(chunk)
            dst.truncate(src_size)
            dst.flush()
            os.fsync(dst.fileno())
        # .idx is small and replayable: copy whole (remote: the snapshot
        # taken BEFORE the dat copy, so entries never outrun the data)
        if not remote:
            with open(src_base + ".idx", "rb") as f:
                idx = f.read()
        with open(dst_base + ".idx", "wb") as f:
            f.write(idx)
            f.flush()
            os.fsync(f.fileno())
    finally:
        if channel is not None:
            channel.close()
    with open(state_path, "w") as f:
        json.dump({"size": src_size, "revision": revision}, f)
    print(f"backed up volume {a.volumeId}: +{copied} bytes (total {src_size})")
    return 0


def cmd_scan(a) -> int:
    base = _base(a)
    sb, items = scan_volume_file(base + ".dat")
    print(f"superblock: version={sb.version} rp={sb.replica_placement} rev={sb.compaction_revision}")
    for item in items:
        n = item.needle
        kind = "DEL" if (n.is_tombstone or item.body_size == 0) else "PUT"
        flag = "" if item.crc_ok else " CRC-BAD"
        print(
            f"{kind} offset={item.offset} id={n.needle_id:x} cookie={n.cookie:08x} "
            f"size={len(n.data)} name={n.name.decode(errors='replace')!r}{flag}"
        )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="seaweedfs_tpu.tools")
    sub = p.add_subparsers(dest="cmd", required=True)
    for name, fn in (
        ("fix", cmd_fix),
        ("export", cmd_export),
        ("compact", cmd_compact),
        ("scan", cmd_scan),
        ("backup", cmd_backup),
    ):
        sp = sub.add_parser(name)
        sp.add_argument("-dir", required=True)
        sp.add_argument("-volumeId", type=int, required=True)
        sp.add_argument("-collection", default="")
        if name in ("export", "backup"):
            sp.add_argument("-o", required=True)
        if name == "backup":
            sp.add_argument(
                "-from",
                dest="source",
                default="",
                help="live volume server host:grpcPort (remote tail backup)",
            )
        sp.set_defaults(fn=fn)
    a = p.parse_args(argv)
    return a.fn(a)


if __name__ == "__main__":
    sys.exit(main())
