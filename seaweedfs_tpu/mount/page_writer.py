"""Interval-merged dirty-page buffer for the FUSE mount.

Reference: weed/mount/page_writer.go + dirty_pages_chunked.go — open
files buffer written byte ranges as merged intervals; when the dirty
set crosses a bound, sealed intervals are uploaded as chunks (placed
via the filer's AssignVolume) instead of growing resident memory, so a
write larger than RAM completes with flat RSS.
"""

from __future__ import annotations

from typing import Optional


class PageBuffer:
    """Sorted, non-overlapping, merged dirty intervals.

    Not thread-safe — callers hold the handle lock.
    """

    def __init__(self):
        # list[(offset, bytearray)] sorted by offset; adjacent or
        # overlapping writes merge into one interval
        self._iv: list[tuple[int, bytearray]] = []

    @property
    def total(self) -> int:
        return sum(len(b) for _, b in self._iv)

    @property
    def extent(self) -> int:
        """One past the last dirty byte (0 when clean)."""
        if not self._iv:
            return 0
        off, buf = self._iv[-1]
        return off + len(buf)

    def write(self, offset: int, data: bytes) -> None:
        if not data:
            return
        # fast path: sequential append to the last interval
        if self._iv:
            last_off, last_buf = self._iv[-1]
            if offset == last_off + len(last_buf):
                last_buf.extend(data)
                return
        merged = bytearray(data)
        m_start = offset
        keep: list[tuple[int, bytearray]] = []
        for off, buf in self._iv:
            end = off + len(buf)
            if end < m_start or off > m_start + len(merged):
                keep.append((off, buf))  # strictly disjoint, non-adjacent
                continue
            # overlapping or adjacent: fold into merged
            new_start = min(m_start, off)
            new_end = max(m_start + len(merged), end)
            out = bytearray(new_end - new_start)
            out[off - new_start : off - new_start + len(buf)] = buf
            # the NEW data wins on overlap: copy it last
            out[m_start - new_start : m_start - new_start + len(merged)] = merged
            merged, m_start = out, new_start
        keep.append((m_start, merged))
        keep.sort(key=lambda t: t[0])
        self._iv = keep

    def read(self, offset: int, size: int) -> Optional[bytes]:
        """The range's bytes if FULLY covered by one interval, else
        None (caller falls back to a committed read)."""
        for off, buf in self._iv:
            if off <= offset and offset + size <= off + len(buf):
                lo = offset - off
                return bytes(buf[lo : lo + size])
            if off > offset:
                break
        return None

    def covers_any(self, offset: int, size: int) -> bool:
        stop = offset + size
        return any(
            off < stop and off + len(buf) > offset for off, buf in self._iv
        )

    def truncate(self, length: int) -> None:
        out = []
        for off, buf in self._iv:
            if off >= length:
                continue
            if off + len(buf) > length:
                buf = buf[: length - off]
            if buf:
                out.append((off, buf))
        self._iv = out

    def drain(self) -> list[tuple[int, bytes]]:
        """All intervals, clearing the buffer."""
        out = [(off, bytes(buf)) for off, buf in self._iv]
        self._iv = []
        return out

    def peek(self) -> list[tuple[int, bytes]]:
        """All intervals without clearing (spill discards each one only
        after its upload succeeds)."""
        return [(off, bytes(buf)) for off, buf in self._iv]

    def discard(self, offset: int) -> None:
        """Drop the interval starting at `offset` (post-upload)."""
        self._iv = [t for t in self._iv if t[0] != offset]
