"""Mount-to-mount chunk cache sharing (reference weed/mount/peer_hrw.go
+ pb/mount_peer.proto).

Every participating mount runs a tiny HTTP sidecar serving its local
chunk cache, announces itself in the filer KV (``mount.peers``), and
routes each chunk fid to its HRW owner: the peer with the highest
``blake2(fid, peer_id)``. A read asks the owner's cache BEFORE the
volume server, so a chunk hot across N mounts is fetched from the
volume tier once instead of N times. Fids are immutable, so cached
bytes can never go stale — only evicted.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import requests

from ..pb import filer_pb2 as fpb
from ..utils.chunk_cache import ChunkCache
from ..utils.retry import Backoff, RetryPolicy

PEERS_KEY = b"mount.peers"
ANNOUNCE_INTERVAL = 5.0
PEER_TTL = 30.0
PEER_TIMEOUT = 2.0  # a slow peer must not stall reads; fall through

# Announce-loop backoff while the filer is down: walk up from the
# normal cadence instead of hammering a restarting filer every 5 s,
# but never past the peer TTL — recovery must re-announce before other
# mounts would have to expire (and re-learn) this one anyway. Jitter
# is applied ON TOP of max_delay, so the cap is derated to keep the
# worst-case jittered delay within the TTL.
ANNOUNCE_POLICY = RetryPolicy(
    max_attempts=4,
    base_delay=ANNOUNCE_INTERVAL,
    max_delay=PEER_TTL / 1.2,
    jitter=0.2,
)


def hrw_owner(fid: str, peer_ids: list[str]) -> str:
    """Highest-random-weight owner of a fid among peer ids."""
    return max(
        peer_ids,
        key=lambda p: hashlib.blake2b(
            f"{fid}|{p}".encode(), digest_size=8
        ).digest(),
    )


class PeerChunkCache:
    """Cache + sidecar server + announce loop for one mount."""

    def __init__(
        self,
        filer_stub_fn,
        ip: str = "127.0.0.1",
        capacity_bytes: int = 64 * 1024 * 1024,
    ):
        self._stub = filer_stub_fn
        self.cache = ChunkCache(capacity_bytes)
        self.peer_id = f"mount-{uuid.uuid4().hex[:10]}"
        self.stats = {"peer_hits": 0, "peer_misses": 0, "served": 0}
        self._http = requests.Session()
        self._stop = threading.Event()
        self._peers: dict[str, str] = {}  # peer_id -> addr
        self._peers_ts = 0.0

        cache = self.cache
        stats = self.stats

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                if not self.path.startswith("/chunk/"):
                    self.send_response(404)
                    self.end_headers()
                    return
                data = cache.get(self.path[len("/chunk/") :])
                if data is None:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                stats["served"] += 1
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):  # quiet
                pass

        # Bind `ip` when possible (loopback default stays
        # loopback-only: this sidecar is UNAUTHENTICATED); fall back to
        # the wildcard only for a NAT/cloud announce address that is
        # reachable by peers yet not locally bindable.
        try:
            self._server = ThreadingHTTPServer((ip, 0), _Handler)
        except OSError:
            self._server = ThreadingHTTPServer(("0.0.0.0", 0), _Handler)
        self.addr = f"{ip}:{self._server.server_port}"
        threading.Thread(
            target=self._server.serve_forever, daemon=True
        ).start()
        try:
            self._announce()
        except Exception:  # noqa: BLE001 — filer may not be up yet; the
            pass  # announce loop keeps retrying
        threading.Thread(target=self._announce_loop, daemon=True).start()

    # ---------------------------------------------------------- announce

    def _announce_loop(self) -> None:
        backoff = Backoff(ANNOUNCE_POLICY)
        delay = ANNOUNCE_INTERVAL
        while not self._stop.wait(delay):
            try:
                self._announce()
            except Exception:  # noqa: BLE001 — filer may be restarting
                delay = backoff.next_delay()
            else:
                backoff.reset()
                delay = ANNOUNCE_INTERVAL

    def _announce(self) -> None:
        stub = self._stub()
        r = stub.KvGet(fpb.FilerKvGetRequest(key=PEERS_KEY), timeout=5)
        try:
            peers = json.loads(r.value) if r.found else {}
        except ValueError:
            peers = {}
        now = time.time()
        peers = {
            pid: rec
            for pid, rec in peers.items()
            if now - rec.get("ts", 0) < PEER_TTL
        }
        peers[self.peer_id] = {"addr": self.addr, "ts": now}
        stub.KvPut(
            fpb.FilerKvPutRequest(
                key=PEERS_KEY, value=json.dumps(peers).encode()
            ),
            timeout=5,
        )
        self._peers = {pid: rec["addr"] for pid, rec in peers.items()}
        self._peers_ts = now

    def peers(self) -> dict[str, str]:
        if time.time() - self._peers_ts > ANNOUNCE_INTERVAL * 2:
            try:
                self._announce()
            except Exception:  # noqa: BLE001
                pass
        return self._peers

    # ------------------------------------------------------------- fetch

    def get_chunk(self, fid: str, volume_fetch) -> bytes | None:
        """Chunk bytes via local cache -> HRW owner's cache -> the
        volume tier (`volume_fetch(fid) -> bytes|None`). Every fetched
        chunk lands in the local cache (and therefore becomes servable
        to peers)."""
        data = self.cache.get(fid)
        if data is not None:
            return data
        peers = self.peers()
        owner = (
            hrw_owner(fid, sorted(peers)) if peers else self.peer_id
        )
        if owner != self.peer_id:
            addr = peers.get(owner)
            if addr:
                try:
                    r = self._http.get(
                        f"http://{addr}/chunk/{fid}", timeout=PEER_TIMEOUT
                    )
                    if r.status_code == 200:
                        self.stats["peer_hits"] += 1
                        self.cache.put(fid, r.content)
                        return r.content
                    self.stats["peer_misses"] += 1
                except requests.RequestException:
                    self.stats["peer_misses"] += 1
        data = volume_fetch(fid)
        if data is not None:
            self.cache.put(fid, data)
        return data

    def close(self) -> None:
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()
