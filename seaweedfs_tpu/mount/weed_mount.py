"""FUSE mount over the filer (reference `weed mount`, weed/mount 25k).

POSIX subset: getattr/readdir/create/open/read/write/release/truncate/
unlink/mkdir/rmdir/rename/statfs/access/utimens. Writes go through a
chunked dirty-page writer (reference page_writer.go /
dirty_pages_chunked.go): byte ranges buffer as merged intervals and
spill to volume-server chunks (placed via the filer's AssignVolume
gRPC) once they cross FLUSH_BYTES, so a write larger than RAM
completes with flat RSS; the entry (base chunks + new chunks) commits
over the filer gRPC service on flush/release. Attr/dir lookups go
through a short TTL cache like the reference's meta_cache.
"""

from __future__ import annotations

import ctypes
import errno
import stat as stat_mod
import threading
import time

import requests

from ..client.filer_client import filer_url, list_dir
from ..pb import filer_pb2 as fpb
from ..pb import rpc
from . import fuse_ctypes as fc
from .page_writer import PageBuffer
from ..utils.urls import service_url

ATTR_TTL = 1.0
FLUSH_BYTES = 8 * 1024 * 1024  # dirty bytes that trigger a chunk spill
CHUNK_SIZE = 4 * 1024 * 1024


class _Handle:
    __slots__ = (
        "path",
        "pages",
        "chunks",
        "size",
        "base",
        "trunc",
        "dirty",
        "refs",
        "lock",
    )

    def __init__(self, path: str, size: int, base: bool):
        self.path = path
        self.pages = PageBuffer()
        self.chunks: list = []  # uploaded, not yet committed
        self.size = size  # logical file size incl. dirty writes
        self.base = base  # a committed entry exists on the filer
        self.trunc = None  # lowest truncation point since last commit
        self.dirty = not base
        self.refs = 1
        self.lock = threading.Lock()


class FilerMount:
    def __init__(self, filer: str, filer_grpc: str = ""):
        self.filer = filer
        host, _, port = filer.partition(":")
        # default matches the server CLI: filer gRPC = HTTP port + 10000
        self.filer_grpc = filer_grpc or f"{host}:{int(port or 8888) + 10000}"
        self._http = requests.Session()
        self._grpc_lock = threading.Lock()
        self._channel = None
        self._stub = None
        self._handles: dict[int, _Handle] = {}
        # open handle per path: getattr/readdir must see created-but-
        # unflushed files (the filer only learns about them on commit)
        self._by_path: dict[str, _Handle] = {}
        self._next_fh = 1
        self._lock = threading.Lock()
        self._attr_cache: dict[str, tuple[float, dict | None]] = {}

    def _filer_stub(self):
        with self._grpc_lock:
            if self._stub is None:
                import grpc as _grpc

                self._channel = _grpc.insecure_channel(self.filer_grpc)
                self._stub = rpc.filer_stub(self._channel)
            return self._stub

    # ------------------------------------------------------------- filer io

    def _url(self, path: str) -> str:
        return filer_url(self.filer, path)

    def _lookup(self, path: str) -> dict | None:
        """-> {isDir, size, mtime}, None (absent), or raises OSError on
        transient filer errors (must NOT be cached as a bogus file)."""
        now = time.time()
        hit = self._attr_cache.get(path)
        if hit and now - hit[0] < ATTR_TTL:
            return hit[1]
        if path == "/":
            out = {"isDir": True, "size": 0, "mtime": int(now)}
        else:
            r = self._http.head(self._url(path), timeout=10)
            if r.status_code == 404:
                out = None
            elif r.status_code != 200:
                raise OSError(errno.EIO, f"filer HEAD {path}: {r.status_code}")
            elif r.headers.get("X-Filer-Listing") == "true":
                out = {"isDir": True, "size": 0, "mtime": int(now)}
            else:
                mtime = int(now)
                lm = r.headers.get("Last-Modified")
                if lm:
                    try:
                        from email.utils import parsedate_to_datetime

                        mtime = int(parsedate_to_datetime(lm).timestamp())
                    except (ValueError, TypeError):
                        pass
                out = {
                    "isDir": False,
                    "size": int(r.headers.get("Content-Length", "0") or 0),
                    "mtime": mtime,
                }
        self._attr_cache[path] = (now, out)
        return out

    def _invalidate(self, path: str) -> None:
        self._attr_cache.pop(path, None)
        parent = path.rsplit("/", 1)[0] or "/"
        self._attr_cache.pop(parent, None)

    def _read_all(self, path: str) -> bytearray | None:
        r = self._http.get(self._url(path), timeout=300)
        if r.status_code != 200:
            return None
        return bytearray(r.content)

    def _write_all(self, path: str, data: bytes) -> bool:
        r = self._http.post(
            self._url(path),
            data=bytes(data),
            headers={"Content-Type": "application/octet-stream"},
            timeout=300,
        )
        self._invalidate(path)
        return r.status_code == 201

    # ----------------------------------------------------------- callbacks

    def getattr(self, path: str, st) -> int:
        h = self._by_path.get(path)
        if h is not None:
            with h.lock:
                info = {
                    "isDir": False,
                    "size": h.size,
                    "mtime": int(time.time()),
                }
        else:
            info = self._lookup(path)
        if info is None:
            return -errno.ENOENT
        ctypes.memset(ctypes.byref(st.contents), 0, ctypes.sizeof(fc.Stat))
        s = st.contents
        if info["isDir"]:
            s.st_mode = stat_mod.S_IFDIR | 0o755
            s.st_nlink = 2
        else:
            s.st_mode = stat_mod.S_IFREG | 0o644
            s.st_nlink = 1
            s.st_size = info["size"]
        s.st_mtim.tv_sec = info["mtime"]
        s.st_ctim.tv_sec = info["mtime"]
        s.st_blksize = 4096
        s.st_blocks = (s.st_size + 511) // 512
        return 0

    def readdir(self, path: str, buf, filler) -> int:
        info = self._lookup(path)
        if info is None:
            return -errno.ENOENT
        if not info["isDir"]:
            return -errno.ENOTDIR
        filler(buf, b".", None, 0)
        filler(buf, b"..", None, 0)
        seen = set()
        try:
            for e in list_dir(self.filer, path, session=self._http):
                name = e["FullPath"].rsplit("/", 1)[-1]
                seen.add(name)
                filler(buf, name.encode(), None, 0)
        except requests.RequestException:
            return -errno.EIO
        prefix = path.rstrip("/") + "/"
        for p in list(self._by_path):
            if p.startswith(prefix) and "/" not in p[len(prefix):]:
                name = p[len(prefix):]
                if name not in seen:
                    filler(buf, name.encode(), None, 0)
        return 0

    def _new_fh(self, h: _Handle) -> int:
        with self._lock:
            fh = self._next_fh
            self._next_fh += 1
            self._handles[fh] = h
            self._by_path[h.path] = h
            return fh

    def open(self, path: str, fi) -> int:
        # second open of a live handle shares it (refcounted): the
        # dirty state is per-path, not per-descriptor
        with self._lock:
            existing = self._by_path.get(path)
            if existing is not None:
                existing.refs += 1
        if existing is not None:
            fi.contents.fh = self._new_fh(existing)
            return 0
        info = self._lookup(path)
        if info is None:
            return -errno.ENOENT
        if info["isDir"]:
            return -errno.EISDIR
        fi.contents.fh = self._new_fh(_Handle(path, info["size"], base=True))
        return 0

    def create(self, path: str, mode: int, fi) -> int:
        fi.contents.fh = self._new_fh(_Handle(path, 0, base=False))
        self._invalidate(path)
        return 0

    # ------------------------------------------------------- page writer

    def _upload_chunk(self, piece: bytes, offset: int, ts: int) -> fpb.FileChunk:
        """Place one chunk via the filer's AssignVolume and POST it to
        the volume server (reference dirty_pages_chunked.go
        saveChunkedFileIntervalToStorage)."""
        a = self._filer_stub().AssignVolume(
            fpb.AssignVolumeRequest(count=1), timeout=30
        )
        if a.error:
            raise OSError(errno.EIO, f"assign: {a.error}")
        headers = {"Authorization": f"Bearer {a.jwt}"} if a.jwt else {}
        r = self._http.post(
            service_url(a.url, f"/{a.fid}"),
            files={"file": ("chunk", piece, "application/octet-stream")},
            headers=headers,
            timeout=300,
        )
        if r.status_code >= 400:
            raise OSError(errno.EIO, f"chunk upload: {r.status_code}")
        return fpb.FileChunk(
            fid=a.fid, offset=offset, size=len(piece), modified_ts_ns=ts
        )

    def _upload_interval(self, h: _Handle, offset: int, data: bytes) -> None:
        ts = time.time_ns()
        for i in range(0, len(data), CHUNK_SIZE):
            h.chunks.append(
                self._upload_chunk(data[i : i + CHUNK_SIZE], offset + i, ts)
            )

    def _spill_locked(self, h: _Handle) -> None:
        # discard an interval only AFTER its upload succeeds: a failed
        # spill must leave the un-uploaded dirty bytes in the buffer,
        # not silently drop them (zero-gap corruption on later commit)
        for off, data in h.pages.peek():
            self._upload_interval(h, off, data)
            h.pages.discard(off)

    def _commit_locked(self, h: _Handle) -> None:
        """Publish the entry: base chunks + spilled chunks + attrs
        (reference weedfs_file_sync.go doFlush)."""
        if not h.dirty and not h.chunks and h.pages.total == 0:
            return
        self._spill_locked(h)
        stub = self._filer_stub()
        directory, _, name = h.path.rpartition("/")
        directory = directory or "/"
        entry = fpb.Entry(name=name)
        if h.base:
            r = stub.LookupDirectoryEntry(
                fpb.LookupEntryRequest(directory=directory, name=name),
                timeout=30,
            )
            if not r.error:
                base = r.entry
                if h.trunc is not None:
                    # truncation must clamp the BASE state: file_size
                    # alone can't hide interior stale bytes (a shrink
                    # followed by writes past the cut would otherwise
                    # resurface old chunk data where POSIX wants zeros)
                    base.content = base.content[: h.trunc]
                    kept = []
                    for c in base.chunks:
                        if c.offset >= h.trunc:
                            continue
                        if c.offset + c.size > h.trunc:
                            c.size = h.trunc - c.offset
                        kept.append(c)
                    del base.chunks[:]
                    base.chunks.extend(kept)
                if base.content and not h.chunks:
                    # tiny committed file: apply truncation to the
                    # inline bytes — read_entry serves content verbatim,
                    # so a stale-length content would defeat truncate
                    content = base.content[: h.size]
                    if h.size > len(content):
                        if h.size <= 512:
                            content += b"\x00" * (h.size - len(content))
                        else:
                            # grown past inline territory: chunk it and
                            # let file_size zero-fill the tail
                            entry.chunks.append(
                                self._upload_chunk(base.content, 0, ts=0)
                            )
                            content = b""
                    entry.content = content
                elif base.content:
                    # inline content must become a chunk before new
                    # chunks can overlay it; ts=0 so every spilled
                    # dirty chunk (newer) wins the LWW overlay
                    entry.chunks.append(
                        self._upload_chunk(base.content, 0, ts=0)
                    )
                entry.chunks.extend(base.chunks)
                entry.attributes.CopyFrom(base.attributes)
        entry.chunks.extend(h.chunks)
        entry.attributes.file_size = h.size
        entry.attributes.mtime = int(time.time())
        if not entry.attributes.file_mode:
            entry.attributes.file_mode = stat_mod.S_IFREG | 0o644
        r = stub.CreateEntry(
            fpb.CreateEntryRequest(directory=directory, entry=entry),
            timeout=60,
        )
        if r.error:
            raise OSError(errno.EIO, f"commit {h.path}: {r.error}")
        h.chunks = []
        h.base = True
        h.trunc = None
        h.dirty = False
        self._invalidate(h.path)

    # ----------------------------------------------------------- file io

    def read(self, path: str, buf, size: int, offset: int, fi) -> int:
        h = self._handles.get(fi.contents.fh)
        if h is None:
            return -errno.EBADF
        with h.lock:
            if offset >= h.size:
                return 0
            size = min(size, h.size - offset)
            piece = h.pages.read(offset, size)
            if piece is None:
                if h.chunks or h.pages.covers_any(offset, size):
                    # the range spans uncommitted state: publish first,
                    # then read through the filer (rare for the
                    # sequential-write workloads the page writer serves)
                    self._commit_locked(h)
                piece = self._read_range(path, offset, size)
                if piece is None:
                    return -errno.EIO
                if len(piece) < size:
                    # sparse hole / ftruncate-grown tail: zeros, the
                    # same bytes the committed entry would serve
                    piece += b"\x00" * (size - len(piece))
        ctypes.memmove(buf, piece, len(piece))
        return len(piece)

    def _read_range(self, path: str, offset: int, size: int) -> bytes | None:
        """Committed bytes for [offset, offset+size); short when the
        committed file ends early (caller zero-fills); None only on a
        real IO error — a hole in a never-committed file reads as
        zeros, matching the old whole-file-buffer behavior."""
        r = self._http.get(
            self._url(path),
            headers={"Range": f"bytes={offset}-{offset + size - 1}"},
            timeout=300,
        )
        if r.status_code in (404, 416):
            return b""
        if r.status_code not in (200, 206):
            return None
        data = r.content
        if r.status_code == 200:
            data = data[offset : offset + size]
        return data

    def write(self, path: str, buf, size: int, offset: int, fi) -> int:
        h = self._handles.get(fi.contents.fh)
        if h is None:
            return -errno.EBADF
        data = ctypes.string_at(buf, size)
        with h.lock:
            h.pages.write(offset, data)
            h.size = max(h.size, offset + size)
            h.dirty = True
            if h.pages.total >= FLUSH_BYTES:
                # bounded memory: spill sealed intervals as chunks
                try:
                    self._spill_locked(h)
                except OSError:
                    return -errno.EIO
        return size

    def _flush_handle(self, h: _Handle) -> int:
        with h.lock:
            try:
                self._commit_locked(h)
            except OSError:
                return -errno.EIO
        return 0

    def flush(self, path: str, fi) -> int:
        h = self._handles.get(fi.contents.fh)
        return self._flush_handle(h) if h else 0

    def release(self, path: str, fi) -> int:
        h = self._handles.pop(fi.contents.fh, None)
        if h is not None:
            rc = self._flush_handle(h)
            with self._lock:
                h.refs -= 1
                if h.refs <= 0 and self._by_path.get(h.path) is h:
                    del self._by_path[h.path]
            return rc if rc else 0
        return 0

    def fsync(self, path: str, datasync: int, fi) -> int:
        h = self._handles.get(fi.contents.fh)
        return self._flush_handle(h) if h else 0

    def truncate(self, path: str, length: int) -> int:
        h = self._by_path.get(path)
        if h is not None:
            return self._ftruncate_handle(h, length)
        data = self._read_all(path)
        if data is None:
            return -errno.ENOENT
        if len(data) > length:
            data = data[:length]
        else:
            data.extend(b"\x00" * (length - len(data)))
        return 0 if self._write_all(path, data) else -errno.EIO

    def _ftruncate_handle(self, h: _Handle, length: int) -> int:
        with h.lock:
            h.pages.truncate(length)
            h.chunks = [c for c in h.chunks if c.offset < length]
            for c in h.chunks:
                if c.offset + c.size > length:
                    c.size = length - c.offset
            if length < h.size:
                # remember the lowest cut: commit clamps the BASE
                # entry's chunks/content to it (stale interior bytes
                # must never resurface after a shrink)
                h.trunc = length if h.trunc is None else min(h.trunc, length)
            h.size = length
            h.dirty = True
        return 0

    def ftruncate(self, path: str, length: int, fi) -> int:
        h = self._handles.get(fi.contents.fh)
        if h is None:
            return self.truncate(path, length)
        return self._ftruncate_handle(h, length)

    def unlink(self, path: str) -> int:
        r = self._http.delete(self._url(path), timeout=60)
        self._invalidate(path)
        # an open handle must not resurrect the path on release
        with self._lock:
            h = self._by_path.pop(path, None)
        if h is not None:
            with h.lock:
                h.dirty = False
                h.pages = PageBuffer()
                h.chunks = []
                h.base = False
        return 0 if r.status_code in (200, 204) else -errno.EIO

    def mkdir(self, path: str, mode: int) -> int:
        r = self._http.post(self._url(path) + "?mkdir=true", timeout=30)
        self._invalidate(path)
        return 0 if r.status_code == 201 else -errno.EIO

    def rmdir(self, path: str) -> int:
        r = self._http.delete(self._url(path), timeout=60)
        self._invalidate(path)
        if r.status_code == 409:
            return -errno.ENOTEMPTY
        return 0 if r.status_code in (200, 204) else -errno.EIO

    def rename(self, old: str, new: str) -> int:
        import urllib.parse

        r = self._http.post(
            self._url(new) + f"?mv.from={urllib.parse.quote(old, safe='')}",
            timeout=60,
        )
        self._invalidate(old)
        self._invalidate(new)
        # retarget any open handle so a later flush lands on the new
        # name instead of resurrecting the old one
        with self._lock:
            h = self._by_path.pop(old, None)
            if h is not None:
                h.path = new
                self._by_path[new] = h
        if r.status_code == 200:
            return 0
        if r.status_code == 404 and h is not None:
            # created-but-unflushed file: the filer has never seen it;
            # the in-memory retarget IS the rename (flush publishes /new)
            return 0
        if r.status_code == 404:
            return -errno.ENOENT
        return -errno.EIO

    def statfs(self, path: str, sv) -> int:
        ctypes.memset(ctypes.byref(sv.contents), 0, ctypes.sizeof(fc.StatVfs))
        s = sv.contents
        s.f_bsize = s.f_frsize = 4096
        s.f_blocks = s.f_bfree = s.f_bavail = 1 << 30
        s.f_files = s.f_ffree = 1 << 20
        s.f_namemax = 255
        return 0


def build_operations(mount: FilerMount) -> fc.FuseOperations:
    """Wrap FilerMount methods as C callbacks (exceptions -> -EIO)."""

    def wrap(cb_type, fn):
        def guard(*args):
            try:
                return fn(*args)
            except Exception:
                return -errno.EIO

        return cb_type(guard)

    ops = fc.FuseOperations()
    ops.getattr = wrap(fc.GetattrT, lambda p, st: mount.getattr(p.decode(), st))
    ops.readdir = wrap(
        fc.ReaddirT,
        lambda p, buf, filler, off, fi: mount.readdir(p.decode(), buf, filler),
    )
    ops.open = wrap(fc.OpenT, lambda p, fi: mount.open(p.decode(), fi))
    ops.create = wrap(
        fc.CreateT, lambda p, mode, fi: mount.create(p.decode(), mode, fi)
    )
    ops.read = wrap(
        fc.ReadT,
        lambda p, buf, size, off, fi: mount.read(p.decode(), buf, size, off, fi),
    )
    ops.write = wrap(
        fc.WriteT,
        lambda p, buf, size, off, fi: mount.write(p.decode(), buf, size, off, fi),
    )
    ops.flush = wrap(fc.OpenT, lambda p, fi: mount.flush(p.decode(), fi))
    ops.release = wrap(fc.OpenT, lambda p, fi: mount.release(p.decode(), fi))
    ops.fsync = wrap(
        fc.FsyncT, lambda p, ds, fi: mount.fsync(p.decode(), ds, fi)
    )
    ops.truncate = wrap(
        fc.TruncateT, lambda p, length: mount.truncate(p.decode(), length)
    )
    ops.ftruncate = wrap(
        fc.FtruncateT,
        lambda p, length, fi: mount.ftruncate(p.decode(), length, fi),
    )
    ops.unlink = wrap(fc.PathT, lambda p: mount.unlink(p.decode()))
    ops.mkdir = wrap(fc.MkdirT, lambda p, mode: mount.mkdir(p.decode(), mode))
    ops.rmdir = wrap(fc.PathT, lambda p: mount.rmdir(p.decode()))
    ops.rename = wrap(
        fc.TwoPathT, lambda a, b: mount.rename(a.decode(), b.decode())
    )
    ops.statfs = wrap(fc.StatfsT, lambda p, sv: mount.statfs(p.decode(), sv))
    ops.access = wrap(fc.AccessT, lambda p, mask: 0)
    ops.utimens = wrap(fc.UtimensT, lambda p, ts: 0)
    ops.chmod = wrap(fc.ChmodT, lambda p, m: 0)
    ops.chown = wrap(fc.ChownT, lambda p, u, g: 0)
    return ops


def run_mount(filer: str, mountpoint: str, filer_grpc: str = "") -> int:
    mount = FilerMount(filer, filer_grpc=filer_grpc)
    ops = build_operations(mount)
    return fc.fuse_main(mountpoint, ops, foreground=True)
