"""FUSE mount over the filer (reference `weed mount`, weed/mount 25k).

POSIX subset: getattr/readdir/create/open/read/write/release/truncate/
unlink/mkdir/rmdir/rename/statfs/access/utimens. Writes go through a
chunked dirty-page writer (reference page_writer.go /
dirty_pages_chunked.go): byte ranges buffer as merged intervals and
spill to volume-server chunks (placed via the filer's AssignVolume
gRPC) once they cross FLUSH_BYTES, so a write larger than RAM
completes with flat RSS; the entry (base chunks + new chunks) commits
over the filer gRPC service on flush/release. Attr/dir lookups go
through a short TTL cache like the reference's meta_cache.
"""

from __future__ import annotations

import ctypes
import errno
import stat as stat_mod
import threading
import time

import requests

from ..client.filer_client import filer_url, list_dir
from ..pb import filer_pb2 as fpb
from ..pb import rpc
from . import fuse_ctypes as fc
from .page_writer import PageBuffer
from ..utils.urls import service_url

ATTR_TTL = 1.0
FLUSH_BYTES = 8 * 1024 * 1024  # dirty bytes that trigger a chunk spill
CHUNK_SIZE = 4 * 1024 * 1024
XATTR_PREFIX = "xattr-"  # extended-attr namespace in entry.extended


class _Handle:
    __slots__ = (
        "path",
        "pages",
        "chunks",
        "size",
        "base",
        "trunc",
        "dirty",
        "refs",
        "lock",
        "mode",
    )

    def __init__(self, path: str, size: int, base: bool, mode: int = 0o644):
        self.path = path
        self.pages = PageBuffer()
        self.chunks: list = []  # uploaded, not yet committed
        self.size = size  # logical file size incl. dirty writes
        self.base = base  # a committed entry exists on the filer
        self.trunc = None  # lowest truncation point since last commit
        self.dirty = not base
        self.refs = 1
        self.lock = threading.Lock()
        self.mode = mode  # create()-requested permission bits


class FilerMount:
    def __init__(
        self,
        filer: str,
        filer_grpc: str = "",
        peer_cache: bool = False,
        peer_ip: str = "127.0.0.1",
    ):
        self.filer = filer
        host, _, port = filer.partition(":")
        # default matches the server CLI: filer gRPC = HTTP port + 10000
        self.filer_grpc = filer_grpc or f"{host}:{int(port or 8888) + 10000}"
        self._http = requests.Session()
        self._grpc_lock = threading.Lock()
        self._channel = None
        self._stub = None
        self._handles: dict[int, _Handle] = {}
        # open handle per path: getattr/readdir must see created-but-
        # unflushed files (the filer only learns about them on commit)
        self._by_path: dict[str, _Handle] = {}
        self._next_fh = 1
        self._lock = threading.Lock()
        self._attr_cache: dict[str, tuple[float, dict | None]] = {}
        # mount.configure (filer KV "mount.conf"): live-tunable attr
        # cache TTL and a cluster-enforced readonly flag
        self.attr_ttl = ATTR_TTL
        self.readonly = False
        try:
            import json as _json

            r = self._filer_stub().KvGet(
                fpb.FilerKvGetRequest(key=b"mount.conf"), timeout=5
            )
            if r.found:
                conf = _json.loads(r.value)
                self.attr_ttl = float(conf.get("attr_ttl", ATTR_TTL))
                self.readonly = bool(conf.get("readonly", False))
        except Exception:  # noqa: BLE001 — filer may not be up yet
            pass
        # P2P chunk-cache sharing between mounts (reference
        # weed/mount/peer_hrw.go): each chunk fid routes to its HRW
        # owner's cache before the volume tier
        self.peer = None
        self._vid_urls: dict[int, tuple[float, str]] = {}
        if peer_cache:
            from .peer_cache import PeerChunkCache

            # peer_ip is both the sidecar bind address and what gets
            # ANNOUNCED: cross-host sharing needs the host's reachable
            # address here (-peerIp), not loopback
            self.peer = PeerChunkCache(self._filer_stub, ip=peer_ip)

    def _filer_stub(self):
        with self._grpc_lock:
            if self._stub is None:
                import grpc as _grpc

                self._channel = _grpc.insecure_channel(self.filer_grpc)
                self._stub = rpc.filer_stub(self._channel)
            return self._stub

    # ------------------------------------------------------------- filer io

    def _url(self, path: str) -> str:
        return filer_url(self.filer, path)

    def _lookup(self, path: str) -> dict | None:
        """-> {isDir, size, mtime, mode, uid, gid, symlink, nlink},
        None (absent), or raises OSError on transient filer errors
        (must NOT be cached as a bogus file). Rides the filer gRPC
        LookupDirectoryEntry so the FULL attribute set (mode/uid/gid/
        symlink/hardlink count) is visible — the HTTP HEAD this
        replaced could only see size+mtime, which is why chmod/chown
        used to be silent lies."""
        now = time.time()
        hit = self._attr_cache.get(path)
        if hit and now - hit[0] < self.attr_ttl:
            return hit[1]
        if path == "/":
            out = {"isDir": True, "size": 0, "mtime": int(now)}
        else:
            r = self._grpc_lookup(path)
            if r.error:
                out = None
            else:
                a = r.entry.attributes
                size = a.file_size
                if not size:
                    size = len(r.entry.content) + sum(
                        c.size for c in r.entry.chunks
                    )
                out = {
                    "isDir": r.entry.is_directory,
                    "size": size,
                    "mtime": a.mtime or int(now),
                    "mode": a.file_mode,
                    "uid": a.uid,
                    "gid": a.gid,
                    "symlink": a.symlink_target,
                    "nlink": max(r.entry.hard_link_counter, 1),
                    "hlid": bytes(r.entry.hard_link_id),
                    "xattrs": {
                        k[len(XATTR_PREFIX) :]: bytes(v)
                        for k, v in r.entry.extended.items()
                        if k.startswith(XATTR_PREFIX)
                    },
                }
        self._attr_cache[path] = (now, out)
        return out

    def _grpc_lookup(self, path: str):
        """One LookupDirectoryEntry round-trip (shared by attr/xattr/
        metadata paths so the directory-split + error mapping cannot
        drift between copies)."""
        directory, _, name = path.rpartition("/")
        try:
            return self._filer_stub().LookupDirectoryEntry(
                fpb.LookupEntryRequest(directory=directory or "/", name=name),
                timeout=10,
            )
        except Exception as e:  # noqa: BLE001 — grpc transport errors
            raise OSError(errno.EIO, f"filer lookup {path}: {e}") from None

    def _flush_open_handle(self, path: str) -> None:
        """A created-but-unflushed file exists only as an open handle
        (the filer learns about it at commit): metadata operations on
        the path must publish it first or they ENOENT."""
        h = self._by_path.get(path)
        if h is not None:
            with h.lock:
                self._commit_locked(h)
            self._invalidate(path)

    def _mutate_attrs(self, path: str, fn) -> int:
        """Read-modify-write an entry's metadata over gRPC; `fn(entry)`
        mutates the proto in place (may return an errno to abort).

        fsetattr-style sequences (cp -p: write, futimens, close) would
        ENOENT on a created-but-unflushed file without the flush."""
        self._flush_open_handle(path)
        directory, _, name = path.rpartition("/")
        directory = directory or "/"
        stub = self._filer_stub()
        r = self._grpc_lookup(path)
        if r.error:
            return -errno.ENOENT
        entry = r.entry
        rc = fn(entry)
        if rc:
            return rc
        r2 = stub.UpdateEntry(
            fpb.UpdateEntryRequest(directory=directory, entry=entry),
            timeout=30,
        )
        if r2.error:
            return -errno.EIO
        self._invalidate(path)
        return 0

    def _invalidate(self, path: str) -> None:
        self._attr_cache.pop(path, None)
        parent = path.rsplit("/", 1)[0] or "/"
        self._attr_cache.pop(parent, None)

    def _read_all(self, path: str) -> bytearray | None:
        r = self._http.get(self._url(path), timeout=300)
        if r.status_code != 200:
            return None
        return bytearray(r.content)

    def _write_all(self, path: str, data: bytes) -> bool:
        r = self._http.post(
            self._url(path),
            data=bytes(data),
            headers={"Content-Type": "application/octet-stream"},
            timeout=300,
        )
        self._invalidate(path)
        return r.status_code == 201

    # ----------------------------------------------------------- callbacks

    def getattr(self, path: str, st) -> int:
        h = self._by_path.get(path)
        if h is not None:
            # Open handle: size/mtime come from the live handle, but
            # persisted metadata (mode/uid/gid/nlink) must not degrade
            # to hardcoded defaults while the file is merely open.
            with h.lock:
                size, hmode, has_base = h.size, h.mode, h.base
            info = None
            if has_base:
                try:
                    info = self._lookup(path)
                except OSError:
                    info = None
            if info is None:
                # carry the type bit so a legal 000-permission create
                # is distinguishable from "no stored mode"
                info = {"isDir": False, "mode": stat_mod.S_IFREG | hmode}
            info = {**info, "size": size, "mtime": int(time.time())}
        else:
            info = self._lookup(path)
        if info is None:
            return -errno.ENOENT
        ctypes.memset(ctypes.byref(st.contents), 0, ctypes.sizeof(fc.Stat))
        s = st.contents
        mode = info.get("mode", 0)
        # mode==0 means "never stored" (proto3 default) — apply type
        # defaults; a STORED mode keeps its exact permission bits, so a
        # legal chmod 000 isn't silently reported as the default.
        perm = mode & 0o7777
        has_mode = mode != 0
        if info.get("symlink"):
            s.st_mode = stat_mod.S_IFLNK | (perm if has_mode else 0o777)
            s.st_nlink = 1
            s.st_size = len(info["symlink"])
        elif info["isDir"]:
            s.st_mode = stat_mod.S_IFDIR | (perm if has_mode else 0o755)
            s.st_nlink = 2
        else:
            s.st_mode = stat_mod.S_IFREG | (perm if has_mode else 0o644)
            s.st_nlink = info.get("nlink", 1)
            s.st_size = info["size"]
        s.st_uid = info.get("uid", 0)
        s.st_gid = info.get("gid", 0)
        s.st_mtim.tv_sec = info["mtime"]
        s.st_ctim.tv_sec = info["mtime"]
        s.st_blksize = 4096
        s.st_blocks = (s.st_size + 511) // 512
        s.st_ino = self._ino_for(path, info)
        return 0

    @staticmethod
    def _ino_for(path: str, info: dict) -> int:
        """Stable inode number (the fs runs with -o use_ino). Hardlinked
        names share their hard_link_id-derived ino; everything else
        hashes its path."""
        import hashlib

        hlid = info.get("hlid") or b""
        key = b"hl:" + hlid if hlid else b"p:" + path.encode()
        # 63 bits: never 0 (0 means "unknown" to the kernel)
        return (
            int.from_bytes(hashlib.blake2b(key, digest_size=8).digest(), "big")
            & 0x7FFFFFFFFFFFFFFF
        ) or 1

    def readdir(self, path: str, buf, filler) -> int:
        info = self._lookup(path)
        if info is None:
            return -errno.ENOENT
        if not info["isDir"]:
            return -errno.ENOTDIR
        filler(buf, b".", None, 0)
        filler(buf, b"..", None, 0)
        seen = set()
        try:
            for e in list_dir(self.filer, path, session=self._http):
                name = e["FullPath"].rsplit("/", 1)[-1]
                seen.add(name)
                filler(buf, name.encode(), None, 0)
        except requests.RequestException:
            return -errno.EIO
        prefix = path.rstrip("/") + "/"
        for p in list(self._by_path):
            if p.startswith(prefix) and "/" not in p[len(prefix):]:
                name = p[len(prefix):]
                if name not in seen:
                    filler(buf, name.encode(), None, 0)
        return 0

    def _new_fh(self, h: _Handle) -> int:
        with self._lock:
            fh = self._next_fh
            self._next_fh += 1
            self._handles[fh] = h
            self._by_path[h.path] = h
            return fh

    def open(self, path: str, fi) -> int:
        if self.readonly and (fi.contents.flags & 0x3):  # O_WRONLY|O_RDWR
            return -errno.EROFS
        # second open of a live handle shares it (refcounted): the
        # dirty state is per-path, not per-descriptor
        with self._lock:
            existing = self._by_path.get(path)
            if existing is not None:
                existing.refs += 1
        if existing is not None:
            fi.contents.fh = self._new_fh(existing)
            return 0
        info = self._lookup(path)
        if info is None:
            return -errno.ENOENT
        if info["isDir"]:
            return -errno.EISDIR
        fi.contents.fh = self._new_fh(_Handle(path, info["size"], base=True))
        return 0

    def create(self, path: str, mode: int, fi) -> int:
        if self.readonly:
            return -errno.EROFS
        if self._name_too_long(path):
            return -errno.ENAMETOOLONG
        # mode 0 is a legal create permission; no `or 0o644` coercion
        fi.contents.fh = self._new_fh(
            _Handle(path, 0, base=False, mode=mode & 0o7777)
        )
        self._invalidate(path)
        return 0

    # ------------------------------------------------------- page writer

    def _upload_chunk(self, piece: bytes, offset: int, ts: int) -> fpb.FileChunk:
        """Place one chunk via the filer's AssignVolume and POST it to
        the volume server (reference dirty_pages_chunked.go
        saveChunkedFileIntervalToStorage)."""
        a = self._filer_stub().AssignVolume(
            fpb.AssignVolumeRequest(count=1), timeout=30
        )
        if a.error:
            raise OSError(errno.EIO, f"assign: {a.error}")
        headers = {"Authorization": f"Bearer {a.jwt}"} if a.jwt else {}
        r = self._http.post(
            service_url(a.url, f"/{a.fid}"),
            files={"file": ("chunk", piece, "application/octet-stream")},
            headers=headers,
            timeout=300,
        )
        if r.status_code >= 400:
            raise OSError(errno.EIO, f"chunk upload: {r.status_code}")
        return fpb.FileChunk(
            fid=a.fid, offset=offset, size=len(piece), modified_ts_ns=ts
        )

    def _upload_interval(self, h: _Handle, offset: int, data: bytes) -> None:
        ts = time.time_ns()
        for i in range(0, len(data), CHUNK_SIZE):
            h.chunks.append(
                self._upload_chunk(data[i : i + CHUNK_SIZE], offset + i, ts)
            )

    def _spill_locked(self, h: _Handle) -> None:
        # discard an interval only AFTER its upload succeeds: a failed
        # spill must leave the un-uploaded dirty bytes in the buffer,
        # not silently drop them (zero-gap corruption on later commit)
        for off, data in h.pages.peek():
            self._upload_interval(h, off, data)
            h.pages.discard(off)

    def _commit_locked(self, h: _Handle) -> None:
        """Publish the entry: base chunks + spilled chunks + attrs
        (reference weedfs_file_sync.go doFlush)."""
        if not h.dirty and not h.chunks and h.pages.total == 0:
            return
        self._spill_locked(h)
        stub = self._filer_stub()
        directory, _, name = h.path.rpartition("/")
        directory = directory or "/"
        entry = fpb.Entry(name=name)
        if h.base:
            r = stub.LookupDirectoryEntry(
                fpb.LookupEntryRequest(directory=directory, name=name),
                timeout=30,
            )
            if not r.error:
                base = r.entry
                if h.trunc is not None:
                    # truncation must clamp the BASE state: file_size
                    # alone can't hide interior stale bytes (a shrink
                    # followed by writes past the cut would otherwise
                    # resurface old chunk data where POSIX wants zeros)
                    base.content = base.content[: h.trunc]
                    kept = []
                    for c in base.chunks:
                        if c.offset >= h.trunc:
                            continue
                        if c.offset + c.size > h.trunc:
                            c.size = h.trunc - c.offset
                        kept.append(c)
                    del base.chunks[:]
                    base.chunks.extend(kept)
                if base.content and not h.chunks:
                    # tiny committed file: apply truncation to the
                    # inline bytes — read_entry serves content verbatim,
                    # so a stale-length content would defeat truncate
                    content = base.content[: h.size]
                    if h.size > len(content):
                        if h.size <= 512:
                            content += b"\x00" * (h.size - len(content))
                        else:
                            # grown past inline territory: chunk it and
                            # let file_size zero-fill the tail
                            entry.chunks.append(
                                self._upload_chunk(base.content, 0, ts=0)
                            )
                            content = b""
                    entry.content = content
                elif base.content:
                    # inline content must become a chunk before new
                    # chunks can overlay it; ts=0 so every spilled
                    # dirty chunk (newer) wins the LWW overlay
                    entry.chunks.append(
                        self._upload_chunk(base.content, 0, ts=0)
                    )
                entry.chunks.extend(base.chunks)
                entry.attributes.CopyFrom(base.attributes)
        entry.chunks.extend(h.chunks)
        entry.attributes.file_size = h.size
        entry.attributes.mtime = int(time.time())
        if not entry.attributes.file_mode:
            entry.attributes.file_mode = stat_mod.S_IFREG | h.mode
        r = stub.CreateEntry(
            fpb.CreateEntryRequest(directory=directory, entry=entry),
            timeout=60,
        )
        if r.error:
            raise OSError(errno.EIO, f"commit {h.path}: {r.error}")
        h.chunks = []
        h.base = True
        h.trunc = None
        h.dirty = False
        self._invalidate(h.path)

    # ----------------------------------------------------------- file io

    def read(self, path: str, buf, size: int, offset: int, fi) -> int:
        h = self._handles.get(fi.contents.fh)
        if h is None:
            return -errno.EBADF
        with h.lock:
            if offset >= h.size:
                return 0
            size = min(size, h.size - offset)
            piece = h.pages.read(offset, size)
            if piece is None:
                if h.chunks or h.pages.covers_any(offset, size):
                    # the range spans uncommitted state: publish first,
                    # then read through the filer (rare for the
                    # sequential-write workloads the page writer serves)
                    self._commit_locked(h)
                piece = self._read_range(path, offset, size)
                if piece is None:
                    return -errno.EIO
                if len(piece) < size:
                    # sparse hole / ftruncate-grown tail: zeros, the
                    # same bytes the committed entry would serve
                    piece += b"\x00" * (size - len(piece))
        ctypes.memmove(buf, piece, len(piece))
        return len(piece)

    def _read_range(self, path: str, offset: int, size: int) -> bytes | None:
        """Committed bytes for [offset, offset+size); short when the
        committed file ends early (caller zero-fills); None only on a
        real IO error — a hole in a never-committed file reads as
        zeros, matching the old whole-file-buffer behavior."""
        if self.peer is not None:
            piece = self._read_range_p2p(path, offset, size)
            if piece is not None:
                return piece
        r = self._http.get(
            self._url(path),
            headers={"Range": f"bytes={offset}-{offset + size - 1}"},
            timeout=300,
        )
        if r.status_code in (404, 416):
            return b""
        if r.status_code not in (200, 206):
            return None
        data = r.content
        if r.status_code == 200:
            data = data[offset : offset + size]
        return data

    def write(self, path: str, buf, size: int, offset: int, fi) -> int:
        h = self._handles.get(fi.contents.fh)
        if h is None:
            return -errno.EBADF
        data = ctypes.string_at(buf, size)
        with h.lock:
            h.pages.write(offset, data)
            h.size = max(h.size, offset + size)
            h.dirty = True
            if h.pages.total >= FLUSH_BYTES:
                # bounded memory: spill sealed intervals as chunks
                try:
                    self._spill_locked(h)
                except OSError:
                    return -errno.EIO
        return size

    def _flush_handle(self, h: _Handle) -> int:
        with h.lock:
            try:
                self._commit_locked(h)
            except OSError:
                return -errno.EIO
        return 0

    def flush(self, path: str, fi) -> int:
        h = self._handles.get(fi.contents.fh)
        return self._flush_handle(h) if h else 0

    def release(self, path: str, fi) -> int:
        h = self._handles.pop(fi.contents.fh, None)
        if h is not None:
            rc = self._flush_handle(h)
            with self._lock:
                h.refs -= 1
                if h.refs <= 0 and self._by_path.get(h.path) is h:
                    del self._by_path[h.path]
            return rc if rc else 0
        return 0

    def fsync(self, path: str, datasync: int, fi) -> int:
        h = self._handles.get(fi.contents.fh)
        return self._flush_handle(h) if h else 0

    def truncate(self, path: str, length: int) -> int:
        if self.readonly:
            return -errno.EROFS
        h = self._by_path.get(path)
        if h is not None:
            return self._ftruncate_handle(h, length)
        data = self._read_all(path)
        if data is None:
            return -errno.ENOENT
        if len(data) > length:
            data = data[:length]
        else:
            data.extend(b"\x00" * (length - len(data)))
        return 0 if self._write_all(path, data) else -errno.EIO

    def _ftruncate_handle(self, h: _Handle, length: int) -> int:
        with h.lock:
            h.pages.truncate(length)
            h.chunks = [c for c in h.chunks if c.offset < length]
            for c in h.chunks:
                if c.offset + c.size > length:
                    c.size = length - c.offset
            if length < h.size:
                # remember the lowest cut: commit clamps the BASE
                # entry's chunks/content to it (stale interior bytes
                # must never resurface after a shrink)
                h.trunc = length if h.trunc is None else min(h.trunc, length)
            h.size = length
            h.dirty = True
        return 0

    def ftruncate(self, path: str, length: int, fi) -> int:
        h = self._handles.get(fi.contents.fh)
        if h is None:
            return self.truncate(path, length)
        return self._ftruncate_handle(h, length)

    def unlink(self, path: str) -> int:
        if self.readonly:
            return -errno.EROFS
        r = self._http.delete(self._url(path), timeout=60)
        self._invalidate(path)
        # an open handle must not resurrect the path on release
        with self._lock:
            h = self._by_path.pop(path, None)
        if h is not None:
            with h.lock:
                h.dirty = False
                h.pages = PageBuffer()
                h.chunks = []
                h.base = False
        return 0 if r.status_code in (200, 204) else -errno.EIO

    def mkdir(self, path: str, mode: int) -> int:
        if self.readonly:
            return -errno.EROFS
        if self._name_too_long(path):
            return -errno.ENAMETOOLONG
        # gRPC CreateEntry (not the HTTP ?mkdir) so the requested mode
        # bits persist. CreateEntry upserts, so existence must be
        # checked first (fresh lookup, not the 1s attr cache, whose
        # stale negative would let mkdir clobber a sibling mount's
        # directory metadata).
        if not self._grpc_lookup(path).error:
            return -errno.EEXIST
        directory, _, name = path.rpartition("/")
        entry = fpb.Entry(name=name, is_directory=True)
        entry.attributes.file_mode = stat_mod.S_IFDIR | (mode & 0o7777)
        entry.attributes.mtime = int(time.time())
        r = self._filer_stub().CreateEntry(
            fpb.CreateEntryRequest(directory=directory or "/", entry=entry),
            timeout=30,
        )
        self._invalidate(path)
        return -errno.EIO if r.error else 0

    def rmdir(self, path: str) -> int:
        if self.readonly:
            return -errno.EROFS
        r = self._http.delete(self._url(path), timeout=60)
        self._invalidate(path)
        if r.status_code == 409:
            return -errno.ENOTEMPTY
        return 0 if r.status_code in (200, 204) else -errno.EIO

    def _name_too_long(self, path: str) -> bool:
        """POSIX NAME_MAX (255 bytes per component): the kernel does
        not enforce f_namemax for FUSE, the fs must."""
        return any(
            len(c.encode()) > 255 for c in path.split("/") if c
        )

    def rename(self, old: str, new: str) -> int:
        import urllib.parse

        if self.readonly:
            return -errno.EROFS
        if self._name_too_long(new):
            return -errno.ENAMETOOLONG
        # POSIX target-exists semantics the filer's generic error can't
        # express: file->dir EISDIR, dir->file ENOTDIR, dir->nonempty
        # ENOTEMPTY, dir->EMPTY dir replaces. FRESH lookups (not the 1s
        # attr cache): existence decisions on a stale cache give wrong
        # verdicts when a peer client mutates the tree.
        self._flush_open_handle(old)

        def fresh_isdir(path: str):
            r = self._grpc_lookup(path)
            return None if r.error else r.entry.is_directory

        oi, ni = fresh_isdir(old), fresh_isdir(new)
        if oi is None and self._by_path.get(old) is None:
            return -errno.ENOENT
        replaced_dir = False
        if ni is not None and oi is not None:
            if ni and not oi:
                return -errno.EISDIR
            if not ni and oi:
                return -errno.ENOTDIR
            if ni and oi:
                try:
                    empty = not any(
                        True
                        for _ in list_dir(
                            self.filer, new, session=self._http
                        )
                    )
                except requests.RequestException:
                    return -errno.EIO
                if not empty:
                    return -errno.ENOTEMPTY
                rc = self.rmdir(new)
                if rc != 0:
                    return rc
                replaced_dir = True
        r = self._http.post(
            self._url(new) + f"?mv.from={urllib.parse.quote(old, safe='')}",
            timeout=60,
        )
        self._invalidate(old)
        self._invalidate(new)
        # retarget any open handle so a later flush lands on the new
        # name instead of resurrecting the old one
        with self._lock:
            h = self._by_path.pop(old, None)
            if h is not None:
                h.path = new
                self._by_path[new] = h
        if r.status_code == 200:
            return 0
        if r.status_code == 404 and h is not None:
            # created-but-unflushed file: the filer has never seen it;
            # the in-memory retarget IS the rename (flush publishes /new)
            return 0
        if replaced_dir:
            # the move failed AFTER we removed the empty destination:
            # best-effort restore so rename degrades to "nothing
            # happened" instead of destroying the target (full
            # atomicity needs a filer-side replace, not client steps)
            self.mkdir(new, 0o755)
        if r.status_code == 404:
            return -errno.ENOENT
        return -errno.EIO

    def _read_range_p2p(self, path: str, offset: int, size: int) -> bytes | None:
        """Chunk-granular read: local cache -> HRW peer cache -> DIRECT
        volume-server GET (fids resolved via the filer's LookupVolume).
        Returns None to fall back to the filer HTTP path (inline
        content, manifests, compressed/ciphered chunks, any error)."""
        from ..filer.chunks import read_chunk_views, total_size

        try:
            r = self._grpc_lookup(path)
        except OSError:
            return None
        if r.error:
            return None
        e = r.entry
        if not e.chunks or any(
            c.is_chunk_manifest or c.is_compressed or c.cipher_key
            for c in e.chunks
        ):
            return None
        fsize = e.attributes.file_size or total_size(list(e.chunks))
        end = min(offset + size, fsize)
        if end <= offset:
            return b""
        out = bytearray(end - offset)
        for v in read_chunk_views(list(e.chunks), offset, end - offset):
            data = self.peer.get_chunk(v.fid, self._volume_fetch)
            if data is None or len(data) < v.offset_in_chunk + v.size:
                # short chunk body (metadata/data skew): fall back to
                # the filer path — slice-assigning short bytes would
                # SHRINK the buffer and shift every later view
                return None
            out[v.logical_offset - offset : v.logical_offset - offset + v.size] = (
                data[v.offset_in_chunk : v.offset_in_chunk + v.size]
            )
        return bytes(out)

    def _volume_fetch(self, fid: str) -> bytes | None:
        """Raw chunk bytes straight from a volume server."""
        try:
            vid = int(fid.split(",")[0])
        except ValueError:
            return None
        url = self._vid_url(vid)
        if not url:
            return None
        try:
            r = self._http.get(f"http://{url}/{fid}", timeout=30)
        except requests.RequestException:
            return None
        if self.peer is not None:
            self.peer.stats["volume_fetches"] = (
                self.peer.stats.get("volume_fetches", 0) + 1
            )
        return r.content if r.status_code == 200 else None

    def _vid_url(self, vid: int) -> str:
        hit = self._vid_urls.get(vid)
        if hit and time.time() - hit[0] < 60:
            return hit[1]
        from ..pb import cluster_pb2 as cpb

        try:
            resp = self._filer_stub().LookupVolume(
                cpb.LookupVolumeRequest(volume_ids=[vid]), timeout=10
            )
        except Exception:  # noqa: BLE001 — transport
            return hit[1] if hit else ""
        url = ""
        for vl in resp.volume_locations:
            if vl.volume_id == vid and vl.locations:
                url = vl.locations[0].url
        if url:
            self._vid_urls[vid] = (time.time(), url)
        return url

    def statfs(self, path: str, sv) -> int:
        ctypes.memset(ctypes.byref(sv.contents), 0, ctypes.sizeof(fc.StatVfs))
        s = sv.contents
        s.f_bsize = s.f_frsize = 4096
        s.f_blocks = s.f_bfree = s.f_bavail = 1 << 30
        s.f_files = s.f_ffree = 1 << 20
        s.f_namemax = 255
        return 0

    # ------------------------------------------- POSIX metadata (persisted)

    def chmod(self, path: str, mode: int) -> int:
        if self.readonly:
            return -errno.EROFS
        """Persisted to the filer entry (reference weedfs_attr.go
        Setattr) — the pre-r4 silent no-op lied to callers."""

        def apply(e):
            e.attributes.file_mode = (e.attributes.file_mode & ~0o7777) | (
                mode & 0o7777
            )

        return self._mutate_attrs(path, apply)

    def chown(self, path: str, uid: int, gid: int) -> int:
        if self.readonly:
            return -errno.EROFS
        def apply(e):
            if uid != 0xFFFFFFFF:  # -1 = leave unchanged
                e.attributes.uid = uid
            if gid != 0xFFFFFFFF:
                e.attributes.gid = gid

        return self._mutate_attrs(path, apply)

    _UTIME_NOW = (1 << 30) - 1
    _UTIME_OMIT = (1 << 30) - 2

    def utimens(self, path: str, ts) -> int:
        if self.readonly:
            return -errno.EROFS
        """ts = timespec[2] (atime, mtime); atime is not tracked (the
        reference's filer model has no atime either)."""
        if not ts:
            mtime = int(time.time())
        else:
            spec = ts[1]
            if spec.tv_nsec == self._UTIME_OMIT:
                return 0
            if spec.tv_nsec == self._UTIME_NOW:
                mtime = int(time.time())
            else:
                mtime = spec.tv_sec

        def apply(e):
            e.attributes.mtime = mtime

        return self._mutate_attrs(path, apply)

    # ------------------------------------------------------------- xattrs

    def setxattr(self, path: str, name: str, value: bytes, flags: int) -> int:
        if self.readonly:
            return -errno.EROFS
        if name.startswith(("system.", "security.")):
            # No POSIX-ACL/capability support: accepting
            # system.posix_acl_access as an opaque blob would make
            # tools like `cp -p` believe permissions were applied
            # (libacl only falls back to chmod on EOPNOTSUPP).
            return -errno.EOPNOTSUPP
        key = XATTR_PREFIX + name

        def apply(e):
            exists = key in e.extended
            if flags & 0x1 and exists:  # XATTR_CREATE
                return -errno.EEXIST
            if flags & 0x2 and not exists:  # XATTR_REPLACE
                return -errno.ENODATA
            e.extended[key] = value

        return self._mutate_attrs(path, apply)

    def getxattr(self, path: str, name: str, buf, size: int) -> int:
        if name.startswith(("system.", "security.")):
            # "security.capability" is probed by the kernel on EVERY
            # write(2) (file_remove_privs); answering it from the filer
            # would turn each write into a metadata round-trip.
            return -errno.EOPNOTSUPP
        xattrs = self._xattr_map(path)
        if xattrs is None:
            return -errno.ENOENT
        val = xattrs.get(name)
        if val is None:
            return -errno.ENODATA
        if size == 0:
            return len(val)
        if size < len(val):
            return -errno.ERANGE
        ctypes.memmove(buf, val, len(val))
        return len(val)

    def listxattr(self, path: str, buf, size: int) -> int:
        xattrs = self._xattr_map(path)
        if xattrs is None:
            return -errno.ENOENT
        blob = b"".join(n.encode() + b"\x00" for n in sorted(xattrs))
        if size == 0:
            return len(blob)
        if size < len(blob):
            return -errno.ERANGE
        ctypes.memmove(buf, blob, len(blob))
        return len(blob)

    def removexattr(self, path: str, name: str) -> int:
        if self.readonly:
            return -errno.EROFS
        key = XATTR_PREFIX + name

        def apply(e):
            if key not in e.extended:
                return -errno.ENODATA
            del e.extended[key]

        return self._mutate_attrs(path, apply)

    def _xattr_map(self, path: str) -> dict | None:
        """Object's xattrs via the (cached) attr lookup. A READ must
        never force-commit an open dirty handle (xattr probes arrive
        mid-stream); a created-but-uncommitted file simply has no
        xattrs yet."""
        h = self._by_path.get(path)
        if h is not None and not h.base:
            return {}
        info = self._lookup(path)
        if info is None:
            return None
        return info.get("xattrs", {})

    # -------------------------------------------------- symlink / hardlink

    def symlink(self, target: str, linkpath: str) -> int:
        if self.readonly:
            return -errno.EROFS
        if self._name_too_long(linkpath):
            return -errno.ENAMETOOLONG
        # CreateEntry upserts: without this check a symlink over an
        # existing entry would silently clobber it (orphaning chunks)
        if not self._grpc_lookup(linkpath).error:
            return -errno.EEXIST
        directory, _, name = linkpath.rpartition("/")
        entry = fpb.Entry(name=name)
        entry.attributes.symlink_target = target
        entry.attributes.file_mode = stat_mod.S_IFLNK | 0o777
        entry.attributes.mtime = int(time.time())
        r = self._filer_stub().CreateEntry(
            fpb.CreateEntryRequest(directory=directory or "/", entry=entry),
            timeout=30,
        )
        self._invalidate(linkpath)
        return -errno.EIO if r.error else 0

    def readlink(self, path: str, buf, size: int) -> int:
        info = self._lookup(path)
        if info is None:
            return -errno.ENOENT
        target = (info.get("symlink") or "").encode()
        if not target:
            return -errno.EINVAL
        n = min(len(target), size - 1)
        ctypes.memmove(buf, target, n)
        buf[n] = b"\x00"
        return 0

    def link(self, src: str, dst: str) -> int:
        if self.readonly:
            return -errno.EROFS
        if self._name_too_long(dst):
            return -errno.ENAMETOOLONG
        self._flush_open_handle(src)
        r = self._filer_stub().HardLink(
            fpb.HardLinkRequest(src_path=src, dst_path=dst), timeout=30
        )
        self._invalidate(src)
        self._invalidate(dst)
        if r.error:
            if "not found" in r.error:
                return -errno.ENOENT
            if "exists" in r.error:
                return -errno.EEXIST
            return -errno.EIO
        return 0

    # -------------------------------------------------------- POSIX locks

    # fcntl constants (x86_64)
    _F_RDLCK, _F_WRLCK, _F_UNLCK = 0, 1, 2
    _F_GETLK, _F_SETLK, _F_SETLKW = 5, 6, 7
    _SETLKW_RETRY_S = 5.0  # bounded: the FUSE loop is single-threaded

    def lock(self, path: str, fi, cmd: int, flp) -> int:
        """fcntl byte-range locks routed to the filer lock service
        (LockRange RPC, reference filer_grpc_server_posix_lock.go) so
        locks coordinate ACROSS mounts of the same filer. F_SETLKW
        polls with a bounded deadline instead of blocking the
        single-threaded FUSE loop forever (documented divergence)."""
        fl = ctypes.cast(flp, ctypes.POINTER(fc.Flock)).contents
        owner = f"mnt-{id(self):x}-{fi.contents.lock_owner:x}"
        start = max(fl.l_start, 0)
        end = 0 if fl.l_len == 0 else start + fl.l_len
        stub = self._filer_stub()

        def call(op: int, exclusive: bool):
            return stub.LockRange(
                fpb.LockRangeRequest(
                    path=path,
                    owner=owner,
                    start=start,
                    end=end,
                    exclusive=exclusive,
                    op=op,
                ),
                timeout=10,
            )

        if cmd == self._F_GETLK:
            r = call(2, fl.l_type == self._F_WRLCK)
            if r.granted:
                fl.l_type = self._F_UNLCK
            else:
                # The lock service reports only the conflicting owner,
                # not its exact range/type: report the probed range as
                # write-locked (conservative; pid unknowable across
                # mounts).
                fl.l_type = self._F_WRLCK
                fl.l_whence = 0  # SEEK_SET
                fl.l_pid = 0
            return 0
        if cmd in (self._F_SETLK, self._F_SETLKW):
            if fl.l_type == self._F_UNLCK:
                r = call(1, False)
                return -errno.EIO if r.error else 0
            exclusive = fl.l_type == self._F_WRLCK
            deadline = time.time() + (
                self._SETLKW_RETRY_S if cmd == self._F_SETLKW else 0
            )
            while True:
                r = call(0, exclusive)
                if r.granted:
                    return 0
                if time.time() >= deadline:
                    return -errno.EAGAIN
                time.sleep(0.05)
        return -errno.EINVAL


def build_operations(mount: FilerMount) -> fc.FuseOperations:
    """Wrap FilerMount methods as C callbacks (exceptions -> -EIO)."""

    def wrap(cb_type, fn):
        def guard(*args):
            try:
                return fn(*args)
            except Exception:
                return -errno.EIO

        return cb_type(guard)

    ops = fc.FuseOperations()
    ops.getattr = wrap(fc.GetattrT, lambda p, st: mount.getattr(p.decode(), st))
    ops.readdir = wrap(
        fc.ReaddirT,
        lambda p, buf, filler, off, fi: mount.readdir(p.decode(), buf, filler),
    )
    ops.open = wrap(fc.OpenT, lambda p, fi: mount.open(p.decode(), fi))
    ops.create = wrap(
        fc.CreateT, lambda p, mode, fi: mount.create(p.decode(), mode, fi)
    )
    ops.read = wrap(
        fc.ReadT,
        lambda p, buf, size, off, fi: mount.read(p.decode(), buf, size, off, fi),
    )
    ops.write = wrap(
        fc.WriteT,
        lambda p, buf, size, off, fi: mount.write(p.decode(), buf, size, off, fi),
    )
    ops.flush = wrap(fc.OpenT, lambda p, fi: mount.flush(p.decode(), fi))
    ops.release = wrap(fc.OpenT, lambda p, fi: mount.release(p.decode(), fi))
    ops.fsync = wrap(
        fc.FsyncT, lambda p, ds, fi: mount.fsync(p.decode(), ds, fi)
    )
    ops.truncate = wrap(
        fc.TruncateT, lambda p, length: mount.truncate(p.decode(), length)
    )
    ops.ftruncate = wrap(
        fc.FtruncateT,
        lambda p, length, fi: mount.ftruncate(p.decode(), length, fi),
    )
    ops.unlink = wrap(fc.PathT, lambda p: mount.unlink(p.decode()))
    ops.mkdir = wrap(fc.MkdirT, lambda p, mode: mount.mkdir(p.decode(), mode))
    ops.rmdir = wrap(fc.PathT, lambda p: mount.rmdir(p.decode()))
    ops.rename = wrap(
        fc.TwoPathT, lambda a, b: mount.rename(a.decode(), b.decode())
    )
    ops.statfs = wrap(fc.StatfsT, lambda p, sv: mount.statfs(p.decode(), sv))
    ops.access = wrap(fc.AccessT, lambda p, mask: 0)
    ops.utimens = wrap(
        fc.UtimensT, lambda p, ts: mount.utimens(p.decode(), ts)
    )
    ops.chmod = wrap(fc.ChmodT, lambda p, m: mount.chmod(p.decode(), m))
    ops.chown = wrap(
        fc.ChownT, lambda p, u, g: mount.chown(p.decode(), u, g)
    )
    ops.setxattr = wrap(
        fc.SetxattrT,
        lambda p, n, v, sz, fl: mount.setxattr(
            p.decode(), n.decode(), ctypes.string_at(v, sz), fl
        ),
    )
    ops.getxattr = wrap(
        fc.GetxattrT,
        lambda p, n, buf, sz: mount.getxattr(p.decode(), n.decode(), buf, sz),
    )
    ops.listxattr = wrap(
        fc.ListxattrT,
        lambda p, buf, sz: mount.listxattr(p.decode(), buf, sz),
    )
    ops.removexattr = wrap(
        fc.TwoPathT,
        lambda p, n: mount.removexattr(p.decode(), n.decode()),
    )
    ops.symlink = wrap(
        fc.TwoPathT, lambda t, lp: mount.symlink(t.decode(), lp.decode())
    )
    ops.readlink = wrap(
        fc.ReadlinkT,
        lambda p, buf, sz: mount.readlink(p.decode(), buf, sz),
    )
    ops.link = wrap(
        fc.TwoPathT, lambda a, b: mount.link(a.decode(), b.decode())
    )
    ops.lock = wrap(
        fc.LockT,
        lambda p, fi, cmd, flp: mount.lock(p.decode(), fi, cmd, flp),
    )
    return ops


def run_mount(
    filer: str,
    mountpoint: str,
    filer_grpc: str = "",
    peer_cache: bool = False,
    peer_ip: str = "127.0.0.1",
) -> int:
    mount = FilerMount(
        filer, filer_grpc=filer_grpc, peer_cache=peer_cache, peer_ip=peer_ip
    )
    ops = build_operations(mount)
    return fc.fuse_main(mountpoint, ops, foreground=True)
