"""FUSE mount over the filer (reference `weed mount`, weed/mount 25k).

POSIX subset: getattr/readdir/create/open/read/write/release/truncate/
unlink/mkdir/rmdir/rename/statfs/access/utimens. Open files buffer
whole-file content (read-modify-write), flushed to the filer on
release — the chunked dirty-page writer arrives in a later round.
Attr/dir lookups go through a short TTL cache like the reference's
meta_cache.
"""

from __future__ import annotations

import ctypes
import errno
import stat as stat_mod
import threading
import time

import requests

from ..client.filer_client import filer_url, list_dir
from . import fuse_ctypes as fc

ATTR_TTL = 1.0


class _Handle:
    __slots__ = ("path", "data", "dirty", "lock")

    def __init__(self, path: str, data: bytearray, dirty: bool = False):
        self.path = path
        self.data = data
        self.dirty = dirty
        self.lock = threading.Lock()


class FilerMount:
    def __init__(self, filer: str):
        self.filer = filer
        self._http = requests.Session()
        self._handles: dict[int, _Handle] = {}
        # open handle per path: getattr/readdir must see created-but-
        # unflushed files (the filer only learns about them on release)
        self._by_path: dict[str, _Handle] = {}
        self._next_fh = 1
        self._lock = threading.Lock()
        self._attr_cache: dict[str, tuple[float, dict | None]] = {}

    # ------------------------------------------------------------- filer io

    def _url(self, path: str) -> str:
        return filer_url(self.filer, path)

    def _lookup(self, path: str) -> dict | None:
        """-> {isDir, size, mtime}, None (absent), or raises OSError on
        transient filer errors (must NOT be cached as a bogus file)."""
        now = time.time()
        hit = self._attr_cache.get(path)
        if hit and now - hit[0] < ATTR_TTL:
            return hit[1]
        if path == "/":
            out = {"isDir": True, "size": 0, "mtime": int(now)}
        else:
            r = self._http.head(self._url(path), timeout=10)
            if r.status_code == 404:
                out = None
            elif r.status_code != 200:
                raise OSError(errno.EIO, f"filer HEAD {path}: {r.status_code}")
            elif r.headers.get("X-Filer-Listing") == "true":
                out = {"isDir": True, "size": 0, "mtime": int(now)}
            else:
                mtime = int(now)
                lm = r.headers.get("Last-Modified")
                if lm:
                    try:
                        from email.utils import parsedate_to_datetime

                        mtime = int(parsedate_to_datetime(lm).timestamp())
                    except (ValueError, TypeError):
                        pass
                out = {
                    "isDir": False,
                    "size": int(r.headers.get("Content-Length", "0") or 0),
                    "mtime": mtime,
                }
        self._attr_cache[path] = (now, out)
        return out

    def _invalidate(self, path: str) -> None:
        self._attr_cache.pop(path, None)
        parent = path.rsplit("/", 1)[0] or "/"
        self._attr_cache.pop(parent, None)

    def _read_all(self, path: str) -> bytearray | None:
        r = self._http.get(self._url(path), timeout=300)
        if r.status_code != 200:
            return None
        return bytearray(r.content)

    def _write_all(self, path: str, data: bytes) -> bool:
        r = self._http.post(
            self._url(path),
            data=bytes(data),
            headers={"Content-Type": "application/octet-stream"},
            timeout=300,
        )
        self._invalidate(path)
        return r.status_code == 201

    # ----------------------------------------------------------- callbacks

    def getattr(self, path: str, st) -> int:
        h = self._by_path.get(path)
        if h is not None:
            with h.lock:
                info = {
                    "isDir": False,
                    "size": len(h.data),
                    "mtime": int(time.time()),
                }
        else:
            info = self._lookup(path)
        if info is None:
            return -errno.ENOENT
        ctypes.memset(ctypes.byref(st.contents), 0, ctypes.sizeof(fc.Stat))
        s = st.contents
        if info["isDir"]:
            s.st_mode = stat_mod.S_IFDIR | 0o755
            s.st_nlink = 2
        else:
            s.st_mode = stat_mod.S_IFREG | 0o644
            s.st_nlink = 1
            s.st_size = info["size"]
        s.st_mtim.tv_sec = info["mtime"]
        s.st_ctim.tv_sec = info["mtime"]
        s.st_blksize = 4096
        s.st_blocks = (s.st_size + 511) // 512
        return 0

    def readdir(self, path: str, buf, filler) -> int:
        info = self._lookup(path)
        if info is None:
            return -errno.ENOENT
        if not info["isDir"]:
            return -errno.ENOTDIR
        filler(buf, b".", None, 0)
        filler(buf, b"..", None, 0)
        seen = set()
        try:
            for e in list_dir(self.filer, path, session=self._http):
                name = e["FullPath"].rsplit("/", 1)[-1]
                seen.add(name)
                filler(buf, name.encode(), None, 0)
        except requests.RequestException:
            return -errno.EIO
        prefix = path.rstrip("/") + "/"
        for p in list(self._by_path):
            if p.startswith(prefix) and "/" not in p[len(prefix):]:
                name = p[len(prefix):]
                if name not in seen:
                    filler(buf, name.encode(), None, 0)
        return 0

    def _new_handle(self, path: str, data: bytearray, dirty: bool) -> int:
        with self._lock:
            fh = self._next_fh
            self._next_fh += 1
            h = _Handle(path, data, dirty)
            self._handles[fh] = h
            self._by_path[path] = h
            return fh

    def open(self, path: str, fi) -> int:
        # an open dirty handle holds newer content than the filer
        existing = self._by_path.get(path)
        if existing is not None:
            with existing.lock:
                data = bytearray(existing.data)
            fi.contents.fh = self._new_handle(path, data, dirty=False)
            return 0
        info = self._lookup(path)
        if info is None:
            return -errno.ENOENT
        if info["isDir"]:
            return -errno.EISDIR
        data = self._read_all(path)
        if data is None:
            return -errno.EIO
        fi.contents.fh = self._new_handle(path, data, dirty=False)
        return 0

    def create(self, path: str, mode: int, fi) -> int:
        fi.contents.fh = self._new_handle(path, bytearray(), dirty=True)
        self._invalidate(path)
        return 0

    def read(self, path: str, buf, size: int, offset: int, fi) -> int:
        h = self._handles.get(fi.contents.fh)
        if h is None:
            return -errno.EBADF
        with h.lock:
            chunk = bytes(h.data[offset : offset + size])
        ctypes.memmove(buf, chunk, len(chunk))
        return len(chunk)

    def write(self, path: str, buf, size: int, offset: int, fi) -> int:
        h = self._handles.get(fi.contents.fh)
        if h is None:
            return -errno.EBADF
        data = ctypes.string_at(buf, size)
        with h.lock:
            if len(h.data) < offset:
                h.data.extend(b"\x00" * (offset - len(h.data)))
            h.data[offset : offset + size] = data
            h.dirty = True
        return size

    def _flush_handle(self, h: _Handle) -> int:
        with h.lock:
            if not h.dirty:
                return 0
            ok = self._write_all(h.path, h.data)
            if ok:
                h.dirty = False
                return 0
            return -errno.EIO

    def flush(self, path: str, fi) -> int:
        h = self._handles.get(fi.contents.fh)
        return self._flush_handle(h) if h else 0

    def release(self, path: str, fi) -> int:
        h = self._handles.pop(fi.contents.fh, None)
        if h is not None:
            self._flush_handle(h)
            with self._lock:
                if self._by_path.get(h.path) is h:
                    del self._by_path[h.path]
        return 0

    def fsync(self, path: str, datasync: int, fi) -> int:
        h = self._handles.get(fi.contents.fh)
        return self._flush_handle(h) if h else 0

    def truncate(self, path: str, length: int) -> int:
        data = self._read_all(path)
        if data is None:
            return -errno.ENOENT
        if len(data) > length:
            data = data[:length]
        else:
            data.extend(b"\x00" * (length - len(data)))
        return 0 if self._write_all(path, data) else -errno.EIO

    def ftruncate(self, path: str, length: int, fi) -> int:
        h = self._handles.get(fi.contents.fh)
        if h is None:
            return self.truncate(path, length)
        with h.lock:
            if len(h.data) > length:
                del h.data[length:]
            else:
                h.data.extend(b"\x00" * (length - len(h.data)))
            h.dirty = True
        return 0

    def unlink(self, path: str) -> int:
        r = self._http.delete(self._url(path), timeout=60)
        self._invalidate(path)
        # an open handle must not resurrect the path on release
        with self._lock:
            h = self._by_path.pop(path, None)
            if h is not None:
                h.dirty = False
        return 0 if r.status_code in (200, 204) else -errno.EIO

    def mkdir(self, path: str, mode: int) -> int:
        r = self._http.post(self._url(path) + "?mkdir=true", timeout=30)
        self._invalidate(path)
        return 0 if r.status_code == 201 else -errno.EIO

    def rmdir(self, path: str) -> int:
        r = self._http.delete(self._url(path), timeout=60)
        self._invalidate(path)
        if r.status_code == 409:
            return -errno.ENOTEMPTY
        return 0 if r.status_code in (200, 204) else -errno.EIO

    def rename(self, old: str, new: str) -> int:
        import urllib.parse

        r = self._http.post(
            self._url(new) + f"?mv.from={urllib.parse.quote(old, safe='')}",
            timeout=60,
        )
        self._invalidate(old)
        self._invalidate(new)
        # retarget any open handle so a later flush lands on the new
        # name instead of resurrecting the old one
        with self._lock:
            h = self._by_path.pop(old, None)
            if h is not None:
                h.path = new
                self._by_path[new] = h
        if r.status_code == 200:
            return 0
        if r.status_code == 404 and h is not None:
            # created-but-unflushed file: the filer has never seen it;
            # the in-memory retarget IS the rename (flush publishes /new)
            return 0
        if r.status_code == 404:
            return -errno.ENOENT
        return -errno.EIO

    def statfs(self, path: str, sv) -> int:
        ctypes.memset(ctypes.byref(sv.contents), 0, ctypes.sizeof(fc.StatVfs))
        s = sv.contents
        s.f_bsize = s.f_frsize = 4096
        s.f_blocks = s.f_bfree = s.f_bavail = 1 << 30
        s.f_files = s.f_ffree = 1 << 20
        s.f_namemax = 255
        return 0


def build_operations(mount: FilerMount) -> fc.FuseOperations:
    """Wrap FilerMount methods as C callbacks (exceptions -> -EIO)."""

    def wrap(cb_type, fn):
        def guard(*args):
            try:
                return fn(*args)
            except Exception:
                return -errno.EIO

        return cb_type(guard)

    ops = fc.FuseOperations()
    ops.getattr = wrap(fc.GetattrT, lambda p, st: mount.getattr(p.decode(), st))
    ops.readdir = wrap(
        fc.ReaddirT,
        lambda p, buf, filler, off, fi: mount.readdir(p.decode(), buf, filler),
    )
    ops.open = wrap(fc.OpenT, lambda p, fi: mount.open(p.decode(), fi))
    ops.create = wrap(
        fc.CreateT, lambda p, mode, fi: mount.create(p.decode(), mode, fi)
    )
    ops.read = wrap(
        fc.ReadT,
        lambda p, buf, size, off, fi: mount.read(p.decode(), buf, size, off, fi),
    )
    ops.write = wrap(
        fc.WriteT,
        lambda p, buf, size, off, fi: mount.write(p.decode(), buf, size, off, fi),
    )
    ops.flush = wrap(fc.OpenT, lambda p, fi: mount.flush(p.decode(), fi))
    ops.release = wrap(fc.OpenT, lambda p, fi: mount.release(p.decode(), fi))
    ops.fsync = wrap(
        fc.FsyncT, lambda p, ds, fi: mount.fsync(p.decode(), ds, fi)
    )
    ops.truncate = wrap(
        fc.TruncateT, lambda p, length: mount.truncate(p.decode(), length)
    )
    ops.ftruncate = wrap(
        fc.FtruncateT,
        lambda p, length, fi: mount.ftruncate(p.decode(), length, fi),
    )
    ops.unlink = wrap(fc.PathT, lambda p: mount.unlink(p.decode()))
    ops.mkdir = wrap(fc.MkdirT, lambda p, mode: mount.mkdir(p.decode(), mode))
    ops.rmdir = wrap(fc.PathT, lambda p: mount.rmdir(p.decode()))
    ops.rename = wrap(
        fc.TwoPathT, lambda a, b: mount.rename(a.decode(), b.decode())
    )
    ops.statfs = wrap(fc.StatfsT, lambda p, sv: mount.statfs(p.decode(), sv))
    ops.access = wrap(fc.AccessT, lambda p, mask: 0)
    ops.utimens = wrap(fc.UtimensT, lambda p, ts: 0)
    ops.chmod = wrap(fc.ChmodT, lambda p, m: 0)
    ops.chown = wrap(fc.ChownT, lambda p, u, g: 0)
    return ops


def run_mount(filer: str, mountpoint: str) -> int:
    mount = FilerMount(filer)
    ops = build_operations(mount)
    return fc.fuse_main(mountpoint, ops, foreground=True)
