"""FUSE mount gateway (layer 6): the filer namespace as a local filesystem."""
