"""ctypes binding to libfuse 2.9 (high-level API).

The image ships libfuse.so.2 but no Python binding, so this declares the
FUSE 2.9 ABI surface directly: struct stat (x86_64 glibc layout),
fuse_file_info, fuse_operations, and fuse_main_real. Only the operation
slots the mount uses are populated; the rest stay NULL.
"""

from __future__ import annotations

import ctypes
import ctypes.util

_libfuse_path = (
    ctypes.util.find_library("fuse") or "/usr/lib/x86_64-linux-gnu/libfuse.so.2"
)
libfuse = ctypes.CDLL(_libfuse_path)


class Timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


class Stat(ctypes.Structure):
    """x86_64 glibc struct stat."""

    _fields_ = [
        ("st_dev", ctypes.c_uint64),
        ("st_ino", ctypes.c_uint64),
        ("st_nlink", ctypes.c_uint64),
        ("st_mode", ctypes.c_uint32),
        ("st_uid", ctypes.c_uint32),
        ("st_gid", ctypes.c_uint32),
        ("__pad0", ctypes.c_uint32),
        ("st_rdev", ctypes.c_uint64),
        ("st_size", ctypes.c_int64),
        ("st_blksize", ctypes.c_int64),
        ("st_blocks", ctypes.c_int64),
        ("st_atim", Timespec),
        ("st_mtim", Timespec),
        ("st_ctim", Timespec),
        ("__reserved", ctypes.c_int64 * 3),
    ]


class StatVfs(ctypes.Structure):
    _fields_ = [
        ("f_bsize", ctypes.c_ulong),
        ("f_frsize", ctypes.c_ulong),
        ("f_blocks", ctypes.c_uint64),
        ("f_bfree", ctypes.c_uint64),
        ("f_bavail", ctypes.c_uint64),
        ("f_files", ctypes.c_uint64),
        ("f_ffree", ctypes.c_uint64),
        ("f_favail", ctypes.c_uint64),
        ("f_fsid", ctypes.c_ulong),
        ("f_flag", ctypes.c_ulong),
        ("f_namemax", ctypes.c_ulong),
        ("__spare", ctypes.c_int * 6),
    ]


class FuseFileInfo(ctypes.Structure):
    _fields_ = [
        ("flags", ctypes.c_int),
        ("fh_old", ctypes.c_ulong),
        ("writepage", ctypes.c_int),
        ("flags_bits", ctypes.c_uint),
        ("fh", ctypes.c_uint64),
        ("lock_owner", ctypes.c_uint64),
    ]


# callback types
GetattrT = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(Stat)
)
ReadlinkT = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_char), ctypes.c_size_t
)


class Flock(ctypes.Structure):
    """x86_64 glibc struct flock (for the .lock callback)."""

    _fields_ = [
        ("l_type", ctypes.c_short),
        ("l_whence", ctypes.c_short),
        ("l_start", ctypes.c_int64),
        ("l_len", ctypes.c_int64),
        ("l_pid", ctypes.c_int32),
    ]
MknodT = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64
)
MkdirT = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32)
PathT = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p)
TwoPathT = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p)
ChmodT = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32)
ChownT = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32
)
TruncateT = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_int64)
UtimeT = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_void_p)
OpenT = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(FuseFileInfo)
)
ReadT = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_char),
    ctypes.c_size_t,
    ctypes.c_int64,
    ctypes.POINTER(FuseFileInfo),
)
WriteT = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_char),
    ctypes.c_size_t,
    ctypes.c_int64,
    ctypes.POINTER(FuseFileInfo),
)
StatfsT = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(StatVfs)
)
FsyncT = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(FuseFileInfo)
)
# xattr value/output buffers are raw byte regions (values may contain
# NULs; output buffers are written into) — POINTER(c_char), never
# c_char_p which both truncates at NUL and is read-only.
SetxattrT = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_char_p,
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_char),
    ctypes.c_size_t,
    ctypes.c_int,
)
GetxattrT = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_char_p,
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_char),
    ctypes.c_size_t,
)
ListxattrT = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_char_p,
    ctypes.POINTER(ctypes.c_char),
    ctypes.c_size_t,
)
FillDirT = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_void_p,
    ctypes.c_char_p,
    ctypes.POINTER(Stat),
    ctypes.c_int64,
)
ReaddirT = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_char_p,
    ctypes.c_void_p,
    FillDirT,
    ctypes.c_int64,
    ctypes.POINTER(FuseFileInfo),
)
InitT = ctypes.CFUNCTYPE(ctypes.c_void_p, ctypes.c_void_p)
DestroyT = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
AccessT = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_char_p, ctypes.c_int)
CreateT = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_char_p,
    ctypes.c_uint32,
    ctypes.POINTER(FuseFileInfo),
)
FtruncateT = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_char_p,
    ctypes.c_int64,
    ctypes.POINTER(FuseFileInfo),
)
FgetattrT = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_char_p,
    ctypes.POINTER(Stat),
    ctypes.POINTER(FuseFileInfo),
)
LockT = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_char_p,
    ctypes.POINTER(FuseFileInfo),
    ctypes.c_int,
    ctypes.c_void_p,
)
UtimensT = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(Timespec)
)
BmapT = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint64)
)


class FuseOperations(ctypes.Structure):
    """struct fuse_operations, FUSE 2.9 ABI order."""

    _fields_ = [
        ("getattr", GetattrT),
        ("readlink", ReadlinkT),
        ("getdir", ctypes.c_void_p),  # deprecated
        ("mknod", MknodT),
        ("mkdir", MkdirT),
        ("unlink", PathT),
        ("rmdir", PathT),
        ("symlink", TwoPathT),
        ("rename", TwoPathT),
        ("link", TwoPathT),
        ("chmod", ChmodT),
        ("chown", ChownT),
        ("truncate", TruncateT),
        ("utime", UtimeT),
        ("open", OpenT),
        ("read", ReadT),
        ("write", WriteT),
        ("statfs", StatfsT),
        ("flush", OpenT),
        ("release", OpenT),
        ("fsync", FsyncT),
        ("setxattr", SetxattrT),
        ("getxattr", GetxattrT),
        ("listxattr", ListxattrT),
        ("removexattr", TwoPathT),
        ("opendir", OpenT),
        ("readdir", ReaddirT),
        ("releasedir", OpenT),
        ("fsyncdir", FsyncT),
        ("init", InitT),
        ("destroy", DestroyT),
        ("access", AccessT),
        ("create", CreateT),
        ("ftruncate", FtruncateT),
        ("fgetattr", FgetattrT),
        ("lock", LockT),
        ("utimens", UtimensT),
        ("bmap", BmapT),
        ("flags_word", ctypes.c_uint),  # nullpath_ok etc. bitfield
        ("ioctl", ctypes.c_void_p),
        ("poll", ctypes.c_void_p),
        ("write_buf", ctypes.c_void_p),
        ("read_buf", ctypes.c_void_p),
        ("flock", ctypes.c_void_p),
        ("fallocate", ctypes.c_void_p),
    ]


libfuse.fuse_main_real.restype = ctypes.c_int
libfuse.fuse_main_real.argtypes = [
    ctypes.c_int,
    ctypes.POINTER(ctypes.c_char_p),
    ctypes.POINTER(FuseOperations),
    ctypes.c_size_t,
    ctypes.c_void_p,
]


def fuse_main(mountpoint: str, ops: FuseOperations, foreground: bool = True) -> int:
    """Run the libfuse main loop (single-threaded: Python callbacks)."""
    # use_ino: the fs supplies st_ino itself so hardlinked names report
    # ONE inode number (pjdfstest link semantics); without it the
    # kernel invents a distinct ino per path node.
    args = [b"seaweedfs_tpu", mountpoint.encode(), b"-s", b"-o", b"use_ino"]
    if foreground:
        args.append(b"-f")
    argv = (ctypes.c_char_p * len(args))(*args)
    return libfuse.fuse_main_real(
        len(args), argv, ctypes.byref(ops), ctypes.sizeof(ops), None
    )
