"""`python -m seaweedfs_tpu.mount -filer host:8888 -dir /mnt/weed`
(reference `weed mount`). Foreground; unmount with fusermount -u."""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="seaweedfs_tpu.mount")
    p.add_argument("-filer", default="localhost:8888")
    p.add_argument(
        "-filerGrpc",
        default="",
        help="filer gRPC addr (default: HTTP port + 10000)",
    )
    p.add_argument("-dir", required=True, help="mountpoint")
    p.add_argument(
        "-peerCache",
        action="store_true",
        help="share the chunk cache with other mounts (HRW peer fetch)",
    )
    p.add_argument(
        "-peerIp",
        default="127.0.0.1",
        help="address announced to peer mounts (must be reachable "
        "cross-host; loopback only shares between mounts on one host)",
    )
    a = p.parse_args(argv)
    from .weed_mount import run_mount

    print(f"mounting filer {a.filer} at {a.dir}", flush=True)
    return run_mount(
        a.filer,
        a.dir,
        filer_grpc=a.filerGrpc,
        peer_cache=a.peerCache,
        peer_ip=a.peerIp,
    )


if __name__ == "__main__":
    sys.exit(main())
