"""Admin server: web dashboard + maintenance plane over the master.

Reference: `weed admin` (weed/command/admin.go:196) — a standalone
process serving the dash UI (weed/admin/dash), the maintenance system
views (weed/admin/maintenance: scanner -> queue -> workers), and a
config editor whose policies persist across restarts. Here the
maintenance queue itself lives on the master (worker/control.py), so
this server is a thin, stateless-except-config gRPC client in front of
it — killing the admin never loses queue state.

JSON API (the dashboard polls these; tests drive them directly):
  GET  /api/cluster            cluster stats summary
  GET  /api/topology           DC/rack/node/volume/EC tree
  GET  /api/maintenance        {workers, tasks, config}
  POST /api/maintenance/submit {kind, volume_id[, collection, backend]}
  GET  /api/config             current maintenance policy
  POST /api/config             apply + persist maintenance policy
  GET  /healthz
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse

import grpc

from ..client.master_client import _grpc_addr
from ..pb import cluster_pb2 as pb
from ..pb import rpc
from ..pb import worker_pb2 as wk
from ..utils.glog import logger
from .dashboard import DASHBOARD_HTML

glog = logger("admin")

CONFIG_FIELDS = (
    "ec_auto_fullness",
    "ec_quiet_seconds",
    "garbage_threshold",
    "vacuum_interval_seconds",
    "balance_spread",
    "lifecycle_interval_seconds",
    "ec_balance_interval_seconds",
    "ec_scrub_interval_seconds",
    "ec_rebalance_interval_seconds",
)
STRING_CONFIG_FIELDS = ("lifecycle_filer",)


class AdminServer:
    def __init__(
        self,
        master: str,
        ip: str = "localhost",
        port: int = 23646,
        config_path: str | None = None,
        auth_token: str | None = None,
    ):
        """config_path: where maintenance policy persists (JSON). On
        start, a persisted policy is re-applied to the master — the
        reference keeps admin config in the filer for the same reason:
        the policy must survive both admin and master restarts.

        auth_token: when set, every POST (task submission, config
        editing) must carry `X-Admin-Token: <token>` — the analog of
        the reference's adminUser/adminPassword option. GETs stay open
        (read-only dashboards)."""
        self.master = master
        self.ip = ip
        self.port = port
        self.config_path = config_path
        self.auth_token = auth_token
        self._channel = grpc.insecure_channel(_grpc_addr(master))
        self._master_stub = rpc.master_stub(self._channel)
        self._worker_stub = rpc.worker_stub(self._channel)
        self._http = ThreadingHTTPServer((ip, port), self._handler_class())
        self._http_thread = threading.Thread(
            target=self._http.serve_forever, daemon=True
        )

    # ------------------------------------------------------------ config

    def _load_config(self) -> dict | None:
        if not self.config_path or not os.path.exists(self.config_path):
            return None
        try:
            with open(self.config_path) as f:
                cfg = json.load(f)
            out = {k: float(cfg[k]) for k in CONFIG_FIELDS if k in cfg}
            out.update(
                {
                    k: str(cfg[k])
                    for k in STRING_CONFIG_FIELDS
                    if k in cfg
                }
            )
            return out
        except (OSError, ValueError) as e:
            glog.warning(f"admin: unreadable config {self.config_path}: {e}")
            return None

    def _persist_config(self, cfg: dict) -> None:
        if not self.config_path:
            return
        tmp = self.config_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(cfg, f, indent=2)
        os.replace(tmp, self.config_path)

    def _push_config(self, cfg: dict) -> str:
        """Apply to the master; returns error string ('' = ok)."""
        resp = self._worker_stub.SetMaintenanceConfig(
            wk.MaintenanceConfig(**cfg), timeout=10
        )
        return resp.error

    def apply_persisted_config(self) -> None:
        cfg = self._load_config()
        if cfg:
            try:
                err = self._push_config(cfg)
                if err:
                    glog.warning(f"admin: persisted config rejected: {err}")
            except grpc.RpcError as e:
                glog.warning(
                    f"admin: could not push persisted config: {e.code().name}"
                )

    # -------------------------------------------------------------- api

    def _api_cluster(self) -> dict:
        st = self._master_stub.Statistics(pb.StatisticsRequest(), timeout=10)
        topo = self._master_stub.Topology(pb.TopologyRequest(), timeout=10)
        return {
            "master": self.master,
            "node_count": st.node_count,
            "volume_count": st.volume_count,
            "ec_volume_count": st.ec_volume_count,
            "file_count": st.file_count,
            "used_size": st.used_size,
            "max_volume_id": topo.max_volume_id,
        }

    def _api_topology(self) -> dict:
        topo = self._master_stub.Topology(pb.TopologyRequest(), timeout=10)
        return {
            "max_volume_id": topo.max_volume_id,
            "nodes": [
                {
                    "id": n.id,
                    "rack": n.rack,
                    "data_center": n.data_center,
                    "max_volume_count": n.max_volume_count,
                    "volumes": [
                        {
                            "id": v.id,
                            "collection": v.collection,
                            "size": v.size,
                            "file_count": v.file_count,
                            "deleted_count": v.deleted_count,
                            "read_only": v.read_only,
                            "replica_placement": v.replica_placement,
                            "ttl": v.ttl,
                        }
                        for v in sorted(n.volumes, key=lambda v: v.id)
                    ],
                    "ec_shards": [
                        {
                            "id": e.id,
                            "collection": e.collection,
                            "shard_ids": [
                                i for i in range(32) if e.shard_bits & (1 << i)
                            ],
                            "data_shards": e.data_shards,
                            "parity_shards": e.parity_shards,
                            "generation": e.generation,
                        }
                        for e in sorted(n.ec_shards, key=lambda e: e.id)
                    ],
                }
                for n in topo.nodes
            ],
        }

    def _api_maintenance(self) -> dict:
        tasks = self._worker_stub.ListTasks(wk.ListTasksRequest(), timeout=10)
        workers = self._worker_stub.ListWorkers(
            wk.ListWorkersRequest(), timeout=10
        )
        return {
            "tasks": [
                {
                    "task_id": t.task_id,
                    "kind": t.kind,
                    "volume_id": t.volume_id,
                    "state": t.state,
                    "worker_id": t.worker_id,
                    "progress": t.progress,
                    "error": t.error,
                }
                for t in tasks.tasks
            ],
            "workers": [
                {
                    "worker_id": w.worker_id,
                    "capabilities": list(w.capabilities),
                    "backend": w.backend,
                    "active": w.active,
                    "max_concurrent": w.max_concurrent,
                    # declarative per-job config the dashboard renders
                    # (reference weed/admin/plugin DESIGN)
                    "descriptors": [
                        {
                            "kind": d.kind,
                            "display_name": d.display_name,
                            "description": d.description,
                            "fields": [
                                {
                                    "name": f.name,
                                    "type": f.type,
                                    "default": f.default,
                                    "help": f.help,
                                    "min": f.min,
                                    "max": f.max,
                                }
                                for f in d.fields
                            ],
                        }
                        for d in w.descriptors
                    ],
                }
                for w in workers.workers
            ],
            "config": self._api_get_config(),
        }

    def _api_get_config(self) -> dict:
        cfg = self._worker_stub.GetMaintenanceConfig(
            wk.GetMaintenanceConfigRequest(), timeout=10
        )
        return {
            k: getattr(cfg, k) for k in CONFIG_FIELDS + STRING_CONFIG_FIELDS
        }

    def _api_submit(self, body: dict) -> dict:
        # The dashboard form sends volume_id: null for an empty field
        # (parseInt NaN -> JSON null); reject it cleanly instead of
        # crashing the handler with int(None). Cluster-wide kinds
        # (ec_balance, s3_lifecycle, iceberg) take no volume.
        from ..worker.control import VOLUME_INDEPENDENT_KINDS

        raw_vid = body.get("volume_id")
        if raw_vid is None:
            if str(body.get("kind", "")) in VOLUME_INDEPENDENT_KINDS:
                raw_vid = 0
            else:
                return {"error": "volume_id is required"}
        try:
            volume_id = int(raw_vid)
        except (TypeError, ValueError):
            return {"error": f"volume_id must be an integer, got {raw_vid!r}"}
        req = wk.SubmitTaskRequest(
            kind=str(body.get("kind", "")),
            volume_id=volume_id,
            collection=str(body.get("collection", "")),
            backend=str(body.get("backend", "")),
        )
        params = body.get("params") or {}
        if not isinstance(params, dict):
            return {"error": "params must be an object"}
        for k, v in params.items():
            req.params[str(k)] = str(v)
        resp = self._worker_stub.SubmitTask(req, timeout=10)
        if resp.error:
            return {"error": resp.error}
        return {"task_id": resp.task_id}

    def _api_set_config(self, body: dict) -> dict:
        # partial update: absent knobs keep their master-side values
        # (SetMaintenanceConfig merges per-field), so older dashboards
        # posting only the original four fields still work
        # JSON null = "leave unchanged" (a cleared dashboard input
        # serializes as null) — same as absent
        cfg = {}
        for k in CONFIG_FIELDS:
            if body.get(k) is None:
                continue
            try:
                cfg[k] = float(body[k])
            except (TypeError, ValueError):
                return {"error": f"{k} must be numeric, got {body[k]!r}"}
        for k in STRING_CONFIG_FIELDS:
            if body.get(k) is not None:
                cfg[k] = str(body[k] or "")
        if not cfg:
            return {"error": f"no known config fields in {sorted(body)}"}
        err = self._push_config(cfg)
        if err:
            return {"error": err}
        # persist the master's full post-merge state, not the partial
        # request — otherwise a one-knob update would shrink the file
        # and a restart would silently drop every other knob. The push
        # already succeeded: if this second RPC fails, still persist a
        # best-effort local merge so the applied change is never lost.
        try:
            full = self._api_get_config()
        except grpc.RpcError as e:
            full = {**(self._load_config() or {}), **cfg}
            glog.warning(
                f"admin: config applied but re-read failed "
                f"({e.code().name}); persisting local merge"
            )
        self._persist_config(full)
        return {"config": full}

    # ------------------------------------------------------------- http

    def _handler_class(self):
        admin = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _json(self, code: int, obj: dict) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _dispatch(self, path: str, body: dict | None) -> None:
                try:
                    if path in ("/", "/ui"):
                        page = DASHBOARD_HTML.encode()
                        self.send_response(200)
                        self.send_header(
                            "Content-Type", "text/html; charset=utf-8"
                        )
                        self.send_header("Content-Length", str(len(page)))
                        self.end_headers()
                        self.wfile.write(page)
                    elif path == "/healthz":
                        self._json(200, {"ok": True})
                    elif path == "/api/cluster":
                        self._json(200, admin._api_cluster())
                    elif path == "/api/topology":
                        self._json(200, admin._api_topology())
                    elif path == "/api/maintenance":
                        self._json(200, admin._api_maintenance())
                    elif path == "/api/config" and body is None:
                        self._json(200, admin._api_get_config())
                    elif path == "/api/config":
                        out = admin._api_set_config(body)
                        self._json(400 if "error" in out else 200, out)
                    elif path == "/api/maintenance/submit" and body is not None:
                        out = admin._api_submit(body)
                        self._json(400 if "error" in out else 200, out)
                    else:
                        self._json(404, {"error": "not found"})
                except grpc.RpcError as e:
                    self._json(
                        502,
                        {"error": f"master unreachable: {e.code().name}"},
                    )
                except (TypeError, ValueError, KeyError) as e:
                    # Malformed request bodies must produce a JSON 400,
                    # not a dropped connection.
                    self._json(400, {"error": f"bad request: {e!r}"})

            def do_GET(self):
                self._dispatch(urlparse(self.path).path, None)

            def do_POST(self):
                import hmac

                if admin.auth_token and not hmac.compare_digest(
                    self.headers.get("X-Admin-Token", ""), admin.auth_token
                ):
                    self._json(401, {"error": "missing/invalid X-Admin-Token"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", "0") or 0)
                    body = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    self._json(400, {"error": "invalid JSON body"})
                    return
                self._dispatch(urlparse(self.path).path, body)

        return Handler

    # -------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._http_thread.start()
        self.apply_persisted_config()
        glog.info(f"admin server on http://{self.ip}:{self.port}/")

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()
        self._channel.close()
