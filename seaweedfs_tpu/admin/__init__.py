"""Admin web UI + maintenance plane (reference weed/admin: dash views,
maintenance scanner/queue/worker dashboards, config editor)."""

from .server import AdminServer

__all__ = ["AdminServer"]
