"""Single-page admin dashboard, served inline (no static-file tree).

Functional equivalent of the reference's templ+HTMX admin views
(weed/admin/dash, weed/admin/view): topology browser, maintenance
queue + worker fleet, and a live config editor. Vanilla JS polling the
JSON API — no build step, no external assets, works over curl-grade
HTTP. Everything dynamic is rendered client-side from /api responses,
so the page itself is static and cacheable.
"""

DASHBOARD_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>seaweed-tpu admin</title>
<style>
  body { font-family: system-ui, sans-serif; margin: 0; background: #f6f7f9; color: #1a202c; }
  header { background: #1a2b3c; color: #fff; padding: 10px 24px; display: flex; align-items: baseline; gap: 16px; }
  header h1 { font-size: 18px; margin: 0; }
  header .sub { color: #9fb3c8; font-size: 13px; }
  main { padding: 16px 24px; max-width: 1200px; }
  h2 { font-size: 15px; border-bottom: 1px solid #d7dce2; padding-bottom: 4px; margin-top: 28px; }
  table { border-collapse: collapse; width: 100%; font-size: 13px; background: #fff; }
  th, td { border: 1px solid #e2e8f0; padding: 5px 8px; text-align: left; }
  th { background: #edf2f7; font-weight: 600; }
  .stat { display: inline-block; background: #fff; border: 1px solid #e2e8f0; border-radius: 6px;
          padding: 8px 14px; margin: 4px 8px 4px 0; }
  .stat b { display: block; font-size: 18px; }
  .state-pending { color: #975a16; } .state-assigned { color: #2b6cb0; }
  .state-running { color: #2b6cb0; font-weight: 600; }
  .state-done { color: #276749; } .state-failed { color: #c53030; font-weight: 600; }
  progress { width: 90px; height: 10px; }
  .rack { margin-left: 16px; } .node { margin-left: 32px; margin-bottom: 10px; }
  .dcname { font-weight: 600; margin-top: 10px; }
  form.cfg label { display: inline-block; width: 220px; }
  form.cfg input { width: 90px; margin: 2px 12px 2px 0; }
  #cfgmsg { margin-left: 10px; font-size: 13px; }
  .err { color: #c53030; } .ok { color: #276749; }
  button { background: #2b6cb0; color: #fff; border: 0; border-radius: 4px; padding: 5px 14px; cursor: pointer; }
</style>
</head>
<body>
<header><h1>seaweed-tpu admin</h1><span class="sub" id="masteraddr"></span></header>
<main>
  <div id="stats"></div>

  <h2>maintenance queue</h2>
  <div>
    <form id="submitform" style="margin-bottom:8px">
      kind <select id="taskkind"><option>ec_encode</option><option>vacuum</option><option>balance</option><option>ec_balance</option><option>s3_lifecycle</option><option>iceberg</option></select>
      volume <input id="taskvol" size="6">
      params (k=v,&hellip;) <input id="taskparams" size="28"
        placeholder="source=h:p,target=h:p">
      <button type="submit">submit task</button> <span id="submitmsg"></span>
    </form>
  </div>
  <table id="tasks"><tr><th>task</th><th>kind</th><th>volume</th><th>state</th>
    <th>progress</th><th>worker</th><th>error</th></tr></table>

  <h2>worker fleet</h2>
  <table id="workers"><tr><th>worker</th><th>capabilities</th><th>backend</th><th>load</th></tr></table>

  <h2>maintenance config</h2>
  <form class="cfg" id="cfgform">
    <label>EC auto-encode fullness (0=off)</label><input name="ec_auto_fullness"><br>
    <label>EC quiet seconds</label><input name="ec_quiet_seconds"><br>
    <label>vacuum garbage threshold</label><input name="garbage_threshold"><br>
    <label>vacuum interval seconds</label><input name="vacuum_interval_seconds"><br>
    <label>balance spread (0=off)</label><input name="balance_spread"><br>
    <label>lifecycle interval seconds (0=off)</label><input name="lifecycle_interval_seconds"><br>
    <label>lifecycle filer host:grpcPort</label><input name="lifecycle_filer" data-kind="str"><br>
    <label>ec_balance interval seconds (0=off)</label><input name="ec_balance_interval_seconds"><br>
    <button type="submit">apply &amp; persist</button><span id="cfgmsg"></span>
  </form>

  <h2>topology</h2>
  <div id="topology"></div>
</main>
<script>
const $ = (id) => document.getElementById(id);
const esc = (s) => String(s ?? "").replace(/[&<>"]/g, c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[c]));

async function getJSON(url) { const r = await fetch(url); return r.json(); }

function renderStats(c) {
  $("stats").innerHTML =
    `<span class="stat"><b>${c.node_count}</b>volume servers</span>` +
    `<span class="stat"><b>${c.volume_count}</b>volumes</span>` +
    `<span class="stat"><b>${c.ec_volume_count}</b>EC volumes</span>` +
    `<span class="stat"><b>${c.file_count}</b>files</span>` +
    `<span class="stat"><b>${(c.used_size/1048576).toFixed(1)} MiB</b>used</span>` +
    `<span class="stat"><b>${c.max_volume_id}</b>max volume id</span>`;
}

function renderTasks(tasks) {
  const rows = tasks.map(t =>
    `<tr><td>${esc(t.task_id)}</td><td>${esc(t.kind)}</td><td>${t.volume_id}</td>` +
    `<td class="state-${esc(t.state)}">${esc(t.state)}</td>` +
    `<td><progress max="1" value="${t.progress}"></progress> ${(t.progress*100).toFixed(0)}%</td>` +
    `<td>${esc(t.worker_id) || "-"}</td><td>${esc(t.error) || "-"}</td></tr>`);
  $("tasks").innerHTML =
    `<tr><th>task</th><th>kind</th><th>volume</th><th>state</th><th>progress</th><th>worker</th><th>error</th></tr>` +
    (rows.join("") || `<tr><td colspan="7">no tasks</td></tr>`);
}

function renderWorkers(ws) {
  const rows = ws.map(w =>
    `<tr><td>${esc(w.worker_id)}</td><td>${esc((w.capabilities||[]).join(", "))}</td>` +
    `<td>${esc(w.backend)}</td><td>${w.active}/${w.max_concurrent}</td></tr>`);
  $("workers").innerHTML =
    `<tr><th>worker</th><th>capabilities</th><th>backend</th><th>load</th></tr>` +
    (rows.join("") || `<tr><td colspan="4">no workers connected</td></tr>`);
}

function renderTopology(t) {
  const byDC = {};
  for (const n of t.nodes) {
    const dc = n.data_center || "DefaultDataCenter", rack = n.rack || "DefaultRack";
    ((byDC[dc] ??= {})[rack] ??= []).push(n);
  }
  let html = "";
  for (const [dc, racks] of Object.entries(byDC)) {
    html += `<div class="dcname">&#127970; ${esc(dc)}</div>`;
    for (const [rack, nodes] of Object.entries(racks)) {
      html += `<div class="rack">&#128230; ${esc(rack)}</div>`;
      for (const n of nodes) {
        const vols = n.volumes.map(v =>
          `<tr><td>${v.id}</td><td>${esc(v.collection) || "-"}</td><td>${v.size.toLocaleString()}</td>` +
          `<td>${v.file_count}</td><td>${v.read_only ? "RO" : "RW"}</td>` +
          `<td>${esc(v.replica_placement)}</td><td>${esc(v.ttl) || "-"}</td></tr>`).join("");
        const ecs = n.ec_shards.map(e =>
          `<tr><td>ec ${e.id}</td><td>${esc(e.collection) || "-"}</td>` +
          `<td colspan="3">shards [${e.shard_ids.join(", ")}]</td>` +
          `<td colspan="2">${e.data_shards}+${e.parity_shards} gen ${e.generation}</td></tr>`).join("");
        html += `<div class="node"><b>${esc(n.id)}</b> <small>slots ${n.max_volume_count}</small>` +
          `<table><tr><th>vol</th><th>coll</th><th>size</th><th>files</th><th>mode</th><th>rp</th><th>ttl</th></tr>` +
          (vols + ecs || `<tr><td colspan="7">empty</td></tr>`) + `</table></div>`;
      }
    }
  }
  $("topology").innerHTML = html || "<p>no volume servers registered</p>";
}

let cfgLoaded = false;
async function refresh() {
  try {
    const [cluster, maint, topo] = await Promise.all([
      getJSON("/api/cluster"), getJSON("/api/maintenance"), getJSON("/api/topology")]);
    renderStats(cluster); renderTasks(maint.tasks); renderWorkers(maint.workers);
    renderTopology(topo);
    $("masteraddr").textContent = "master: " + cluster.master;
    if (!cfgLoaded) {  // don't clobber a half-edited form on poll
      for (const [k, v] of Object.entries(maint.config))
        if ($("cfgform").elements[k]) $("cfgform").elements[k].value = v;
      cfgLoaded = true;
    }
  } catch (e) { $("masteraddr").textContent = "refresh failed: " + e; }
}

$("cfgform").addEventListener("submit", async (ev) => {
  ev.preventDefault();
  const body = {};
  for (const el of $("cfgform").elements) {
    if (!el.name) continue;
    if (el.dataset.kind === "str") {
      body[el.name] = el.value || null;  // blank = leave unchanged
    } else {
      const v = parseFloat(el.value);
      if (el.value !== "" && isNaN(v)) {
        $("cfgmsg").textContent = `${el.name}: not a number`;
        $("cfgmsg").className = "err";
        return;
      }
      body[el.name] = el.value === "" ? null : v;  // blank = unchanged
    }
  }
  const r = await fetch("/api/config", {method: "POST",
    headers: {"Content-Type": "application/json"}, body: JSON.stringify(body)});
  const out = await r.json();
  $("cfgmsg").textContent = out.error ? out.error : "applied";
  $("cfgmsg").className = out.error ? "err" : "ok";
  cfgLoaded = false;
});

$("submitform").addEventListener("submit", async (ev) => {
  ev.preventDefault();
  const r = await fetch("/api/maintenance/submit", {method: "POST",
    headers: {"Content-Type": "application/json"},
    body: JSON.stringify({
      kind: $("taskkind").value,
      volume_id: $("taskvol").value === "" ? null : parseInt($("taskvol").value),
      params: Object.fromEntries($("taskparams").value.split(",")
        .filter(kv => kv.includes("=")).map(kv => {
          const i = kv.indexOf("=");
          return [kv.slice(0, i).trim(), kv.slice(i + 1).trim()];
        })),
    })});
  const out = await r.json();
  $("submitmsg").textContent = out.error ? out.error : ("queued " + out.task_id);
  $("submitmsg").className = out.error ? "err" : "ok";
  refresh();
});

refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
