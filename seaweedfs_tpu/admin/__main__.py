"""`python -m seaweedfs_tpu.admin -master host:9333 -port 23646`
(reference `weed admin`): web dashboard + maintenance plane."""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from .server import AdminServer


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="seaweedfs_tpu.admin")
    p.add_argument("-master", default="localhost:9333")
    p.add_argument("-ip", default="localhost")
    p.add_argument("-port", type=int, default=23646)
    p.add_argument(
        "-config",
        default="admin_maintenance.json",
        help="maintenance policy persistence path",
    )
    a = p.parse_args(argv)
    srv = AdminServer(
        master=a.master, ip=a.ip, port=a.port, config_path=a.config
    )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *x: stop.set())
    signal.signal(signal.SIGINT, lambda *x: stop.set())
    srv.start()
    print(f"admin on http://{a.ip}:{a.port}/ -> master {a.master}", flush=True)
    stop.wait()
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
