"""Mesh/sharding helpers for the distributed compute path."""

from .mesh import (
    BLOCK_AXIS,
    MeshRS,
    column_sharding,
    make_mesh,
    pad_cols,
    replicated,
)

__all__ = [
    "BLOCK_AXIS",
    "MeshRS",
    "column_sharding",
    "make_mesh",
    "pad_cols",
    "replicated",
]
