"""Device-mesh helpers for the distributed EC compute path.

EC encode is embarrassingly parallel over the COLUMN (block) dimension:
parity is columnwise-independent, so the natural TPU sharding is data
parallelism over blocks with the (8m x 8k) bit-matrix replicated on
every chip; XLA inserts no collectives for the encode itself, and
cross-device traffic appears only in optional global reductions (the
verify checksum psum) — mirroring how the reference only ever shares
per-shard CRCs between encoder workers, never shard bytes
(weed/storage/erasure_coding).

These helpers back both the production `JaxBackend` (multi-device
encode in ec/backend.py) and the driver's `dryrun_multichip`.
"""

from __future__ import annotations

import os
import threading

import numpy as np

BLOCK_AXIS = "blocks"


def make_mesh(n_devices: int | None = None, devices=None):
    """1-D mesh over local devices (default: all of them)."""
    import jax
    from jax.sharding import Mesh

    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, have {len(devs)}"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (BLOCK_AXIS,))


def column_sharding(mesh):
    """(rows, cols) arrays sharded along cols — the EC block split."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(None, BLOCK_AXIS))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def pad_cols(data: np.ndarray, multiple: int) -> tuple[np.ndarray, int]:
    """Zero-pad columns to a device multiple; returns (padded, orig_n).
    Parity of a zero column is zero, so padding never changes the
    parity of real columns (bit-exactness by construction)."""
    n = data.shape[1]
    rem = n % multiple
    if rem == 0:
        return data, n
    padded = np.zeros((data.shape[0], n + multiple - rem), dtype=data.dtype)
    padded[:, :n] = data
    return padded, n


def pod_pjit_mode() -> str:
    """SEAWEED_EC_POD_PJIT: "auto" (default — the explicit
    NamedSharding/pjit pod encode for the XLA impl, shard_map for the
    Pallas impls whose kernels GSPMD cannot partition), "1" (force
    pjit where traceable), "0" (always shard_map — the pre-gravity
    shape)."""
    return os.environ.get("SEAWEED_EC_POD_PJIT", "auto").strip().lower()


class MeshRS:
    """Reed-Solomon encode/reconstruct over a device mesh with column
    sharding. Bit-exact vs the single-device path: the column split is
    exact and the bit-matrix is replicated.

    Two encode lowerings, selected at construction:

    - **pod-sharded pjit** (XLA impl, the default via
      ``SEAWEED_EC_POD_PJIT=auto``): one ``jax.jit`` over the WHOLE
      mesh with explicit ``NamedSharding`` in/out shardings and a
      ``with_sharding_constraint`` pinning the stripe (block/column)
      axis — GSPMD partitions the bit-matmul itself, which on a
      multi-process TPU pod runs across every process's devices from
      one traced computation (SNIPPETS.md [2]: pjit on multi-process
      platforms), where per-process ``shard_map`` would stop at the
      process boundary. The matmul is columnwise-independent, so the
      partitioner inserts no collectives and the output is bit-exact.
    - **shard_map** (Pallas impls, or ``SEAWEED_EC_POD_PJIT=0``): each
      device runs the FULL single-chip path (fused Pallas kernel) on
      its column slice — the wrapper that works for every impl.
    """

    def __init__(self, rs, mesh):
        import jax
        from jax.sharding import PartitionSpec as P

        try:
            from jax import shard_map
        except ImportError:  # pre-0.8 jax
            from jax.experimental.shard_map import shard_map

        self.rs = rs
        self.mesh = mesh
        self.n_devices = mesh.devices.size
        # Physical identity of every chip one wide batch occupies, in
        # the same "<platform>:<id>" form chip_pool labels per-chip
        # backends with: the residency ledger (ec/device_queue.py)
        # charges a mesh-wide stream one slot on EACH of these, so a
        # wide stream can no longer admit past the per-chip budgets.
        self._device_labels = tuple(
            f"{d.platform}:{d.id}" for d in np.ravel(mesh.devices)
        )
        # jitted shard_map applies, keyed by (m_out, k): the decode
        # coefficient SHAPE is stable per shard-loss set, so each key
        # compiles once and the bit-matrix rides in as a replicated arg.
        # Locked: the device-queue scheduler dispatches several streams'
        # threads into one MeshRS, and a get-or-compile race would
        # compile the same shape twice (wasted minutes on a real mesh).
        self._apply_jits: dict = {}
        self._apply_jits_lock = threading.Lock()
        self._repl = replicated(mesh)
        self._cols = column_sharding(mesh)

        mode = pod_pjit_mode()
        # pjit needs the encode traceable as ordinary jnp ops so GSPMD
        # can partition it; the XLA bit-matmul is, the Pallas kernels
        # are opaque calls — those keep the per-device shard_map.
        self.pod_sharded = mode != "0" and (
            getattr(rs, "impl", "xla") == "xla" or mode == "1"
        )
        if self.pod_sharded:
            cols = self._cols

            def _pod_encode(d):
                # explicit stripe-axis constraint INSIDE the jit: even
                # if XLA would re-layout intermediates, the output
                # parity stays column-sharded exactly like the input —
                # the next pipeline stage (D2H drain) reads each chip's
                # slice without a gather.
                d = jax.lax.with_sharding_constraint(d, cols)
                return jax.lax.with_sharding_constraint(rs.encode(d), cols)

            self._encode = jax.jit(
                _pod_encode, in_shardings=cols, out_shardings=cols
            )
        else:
            # shard_map over the impl's own encode: each device runs
            # the FULL single-chip path (XLA bit-matmul or the fused
            # Pallas kernel) on its column slice.
            self._encode = jax.jit(
                shard_map(
                    rs.encode,
                    mesh=mesh,
                    in_specs=P(None, BLOCK_AXIS),
                    out_specs=P(None, BLOCK_AXIS),
                )
            )

    def device_labels(self) -> tuple[str, ...]:
        """Per-chip "<platform>:<id>" labels this mesh spans (residency
        charging keys — see ec/device_queue._residency_keys)."""
        return self._device_labels

    def put(self, data: np.ndarray):
        """H2D with column sharding (async). Caller pads columns to a
        device multiple first (see pad_cols)."""
        import jax

        return jax.device_put(np.ascontiguousarray(data), self._cols)

    def encode(self, staged):
        """Sharded parity dispatch; returns a device array handle."""
        return self._encode(staged)

    def apply(self, bits: np.ndarray, staged, m_out: int):
        """General GF(256) apply over the column mesh: `bits` is the
        expanded (8*m_out x 8k) bit-matrix, replicated on every chip
        (like the parity matrix in encode), `staged` the column-sharded
        data. Column-independent like encode, so the split is bit-exact
        and no collectives appear. Returns a device handle (async)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        try:
            from jax import shard_map
        except ImportError:  # pre-0.8 jax
            from jax.experimental.shard_map import shard_map

        key = (int(m_out), int(staged.shape[0]))
        with self._apply_jits_lock:
            fn = self._apply_jits.get(key)
        if fn is None:
            # Build OUTSIDE the lock: holding it across a minutes-long
            # mesh compile would block every other stream's already-
            # compiled applies — priority inversion on the foreground
            # path the device queue exists to protect. Two streams
            # racing the same new shape may both build; the insert
            # below keeps one, and jax.jit defers actual compilation
            # to first call anyway.
            rs = self.rs

            def _local(b, d):
                return rs._apply(b, d, m_out)

            fn = jax.jit(
                shard_map(
                    _local,
                    mesh=self.mesh,
                    in_specs=(P(), P(None, BLOCK_AXIS)),
                    out_specs=P(None, BLOCK_AXIS),
                )
            )
            with self._apply_jits_lock:
                fn = self._apply_jits.setdefault(key, fn)
        return fn(jnp.asarray(bits), staged)

    def global_checksum(self, sharded) -> int:
        """psum over the mesh of a uint32 sum — the cheap cross-device
        integrity reduction (rides ICI, never moves shard bytes)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        try:
            from jax import shard_map
        except ImportError:  # pre-0.8 jax
            from jax.experimental.shard_map import shard_map

        def local_sum(x):
            return jax.lax.psum(jnp.sum(x.astype(jnp.uint32)), BLOCK_AXIS)

        return int(
            shard_map(
                local_sum,
                mesh=self.mesh,
                in_specs=P(None, BLOCK_AXIS),
                out_specs=P(),
            )(sharded)
        )
