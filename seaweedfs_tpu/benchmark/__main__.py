"""Load generator (reference `weed benchmark`, weed/command/benchmark.go):
concurrent random writes then reads through the normal client path,
reporting req/s, MB/s and latency percentiles.

  python -m seaweedfs_tpu.benchmark -master host:9333 -n 1000 -size 1024 -c 16
"""

from __future__ import annotations

import argparse
import sys
import threading
import time

import numpy as np

from ..client.operations import Operations


def percentiles(samples: list[float]) -> dict:
    if not samples:
        return {}
    a = np.sort(np.asarray(samples))
    return {
        "p50": float(a[int(len(a) * 0.50)]),
        "p90": float(a[int(len(a) * 0.90)]),
        "p99": float(a[min(int(len(a) * 0.99), len(a) - 1)]),
        "max": float(a[-1]),
    }


def run_phase(name: str, total: int, concurrency: int, work) -> None:
    latencies: list[list[float]] = [[] for _ in range(concurrency)]
    errors = [0] * concurrency
    counter = {"next": 0}
    lock = threading.Lock()

    def worker(wid: int):
        while True:
            with lock:
                i = counter["next"]
                if i >= total:
                    return
                counter["next"] = i + 1
            t0 = time.perf_counter()
            try:
                work(i)
            except Exception:
                errors[wid] += 1
                continue
            latencies[wid].append(time.perf_counter() - t0)

    t0 = time.perf_counter()
    threads = [
        threading.Thread(target=worker, args=(w,)) for w in range(concurrency)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    flat = [x for ws in latencies for x in ws]
    ok = len(flat)
    p = percentiles(flat)
    print(
        f"{name}: {ok}/{total} ok in {dt:.2f}s -> {ok / dt:.1f} req/s"
        + (f", errors {sum(errors)}" if any(errors) else "")
    )
    if p:
        print(
            f"  latency ms: p50 {p['p50'] * 1000:.1f}  p90 {p['p90'] * 1000:.1f}"
            f"  p99 {p['p99'] * 1000:.1f}  max {p['max'] * 1000:.1f}"
        )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="seaweedfs_tpu.benchmark")
    p.add_argument("-master", default="localhost:9333")
    p.add_argument("-n", type=int, default=1000, help="file count")
    p.add_argument("-size", type=int, default=1024, help="bytes per file")
    p.add_argument("-c", type=int, default=16, help="concurrency")
    p.add_argument("-collection", default="")
    p.add_argument("-replication", default="")
    p.add_argument("-readRounds", type=int, default=1)
    a = p.parse_args(argv)

    rng = np.random.default_rng(0)
    payload = rng.integers(0, 256, a.size, np.uint8).tobytes()
    fids: list[str] = [""] * a.n
    clients = [Operations(a.master) for _ in range(a.c)]
    pool = {"next": 0}
    lock = threading.Lock()

    def client_for() -> Operations:
        with lock:
            i = pool["next"]
            pool["next"] = (i + 1) % a.c
        return clients[i]

    def write(i: int):
        fids[i] = client_for().upload(
            payload, collection=a.collection, replication=a.replication
        )

    def read(i: int):
        data = client_for().read(fids[i % a.n])
        if len(data) != a.size:
            raise RuntimeError("short read")

    print(
        f"benchmark: {a.n} x {a.size}B, concurrency {a.c}, master {a.master}"
    )
    run_phase("write", a.n, a.c, write)
    mb = a.n * a.size / 1e6
    for r in range(a.readRounds):
        run_phase("read", a.n, a.c, read)
    print(f"volume data written: {mb:.1f} MB")
    for c in clients:
        c.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
