"""Load generator CLI (weed benchmark analog)."""
