"""Needle-map scalability benchmark: RAM + lookup latency at N needles.

`python -m seaweedfs_tpu.benchmark.needlemap -n 10000000`

Answers the capacity question the round-4 verdict called unmeasured
(reference scale anchor: needle_map_metric + needle_map_sorted_file.go)
across the three mappers:

- memory   (dict replay of .idx — the hot-volume default)
- sqlite   (durable B-tree, O(delta) reopen)
- sorted   (sealed binary-search file: 8 B/needle resident)

Prints one JSON doc: insert rate, resident-set delta, random-lookup
p50/p99 microseconds (hit and miss), and reopen/build times.

Measured at 10M needles (this image's CPU, round 5):

  memory  186 B/needle resident (1.77 GB), lookups 1.3 us p50 /
          20 us p99, reopen 68 s (full .idx replay)
  sorted  8 B/needle resident (the id column; 80 MB), 3.0 s load,
          lookups 5.5 us p50 / 27 us p99 (binary search + pread)
  sqlite  122k inserts/s (at 1M), lookups 5.1 us p50 / 20 us p99,
          reopen ~0 s (O(delta) watermark replay)

The first run of this benchmark found a 32x lookup regression in the
sorted map (searchsorted with an untyped Python-int key) — since
fixed; that binary search backs every EC read.
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import shutil
import tempfile
import time

import numpy as np


def _rss_kb() -> int:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss


def _lookup_lat(get, ids: np.ndarray, samples: int, miss_base: int):
    rng = np.random.default_rng(7)
    picks = rng.choice(ids, size=samples)
    t0 = time.perf_counter()
    for nid in picks:
        if get(int(nid)) is None:
            raise RuntimeError("hit lookup missed")
    hit_total = time.perf_counter() - t0
    lat = []
    for nid in picks[: samples // 10]:
        t1 = time.perf_counter()
        get(int(nid))
        lat.append(time.perf_counter() - t1)
    lat.sort()
    t0 = time.perf_counter()
    for i in range(samples // 10):
        # i*7+3 is never a multiple of 7: a TRUE miss (probing 1..k
        # would hit every 7th key and blend hit cost into the number)
        get(miss_base + i * 7 + 3)
    miss_total = time.perf_counter() - t0
    return {
        "hit_us_avg": round(hit_total / samples * 1e6, 2),
        "hit_us_p50": round(lat[len(lat) // 2] * 1e6, 2),
        "hit_us_p99": round(lat[int(len(lat) * 0.99)] * 1e6, 2),
        "miss_us_avg": round(miss_total / (samples // 10) * 1e6, 2),
    }


def bench(n: int, samples: int, workdir: str) -> dict:
    from ..storage.needle_map import (
        MemDb,
        MemoryNeedleMap,
        SortedFileNeedleMap,
        SqliteNeedleMap,
    )
    from ..storage.types import NeedleValue

    ids = np.arange(1, n + 1, dtype=np.uint64) * 7  # sparse ids
    out: dict = {"needles": n}

    # ---- memory mapper (writes the .idx journal as it goes)
    rss0 = _rss_kb()
    idx = os.path.join(workdir, "m.idx")
    m = MemoryNeedleMap(idx)
    t0 = time.perf_counter()
    for nid in ids:
        m.put(int(nid), int(nid) % (1 << 28), 1024)
    dt = time.perf_counter() - t0
    out["memory"] = {
        "insert_per_s": round(n / dt),
        "rss_delta_mb": round((_rss_kb() - rss0) / 1024, 1),
        "bytes_per_needle": round((_rss_kb() - rss0) * 1024 / n, 1),
        **_lookup_lat(m.get, ids, samples, miss_base=1),
    }
    m.close()

    # reopen = full .idx replay (the memory mapper's restart cost)
    t0 = time.perf_counter()
    m2 = MemoryNeedleMap(idx)
    out["memory"]["reopen_s"] = round(time.perf_counter() - t0, 2)
    m2.close()

    # ---- sorted sealed file (binary search, 8 B/needle resident)
    db = MemDb()
    for nid in ids:
        db.put(NeedleValue(int(nid), int(nid) % (1 << 28), 1024))
    sorted_path = os.path.join(workdir, "m.sorted")
    t0 = time.perf_counter()
    db.write_sorted_file(sorted_path)
    build_s = time.perf_counter() - t0
    del db  # free the builder before measuring the sealed map
    t0 = time.perf_counter()
    sf = SortedFileNeedleMap(sorted_path)
    load_s = time.perf_counter() - t0
    out["sorted"] = {
        "build_s": round(build_s, 2),
        "load_s": round(load_s, 2),
        # ru_maxrss is a PEAK (the memory phase dominates it), so the
        # resident index is reported exactly: the 8-byte id column is
        # the only thing held in RAM
        "resident_mb": round(sf._ids.nbytes / (1 << 20), 1),
        "bytes_per_needle": round(sf._ids.nbytes / n, 1),
        **_lookup_lat(sf.get, ids, samples, miss_base=1),
    }
    sf.close()

    # ---- sqlite mapper (durable; smaller N — it is the slow writer)
    sn = min(n, 1_000_000)
    sq_idx = os.path.join(workdir, "s.idx")
    sq = SqliteNeedleMap(sq_idx)
    t0 = time.perf_counter()
    for nid in ids[:sn]:
        sq.put(int(nid), int(nid) % (1 << 28), 1024)
    sq.flush()
    dt = time.perf_counter() - t0
    out["sqlite"] = {
        "needles": sn,
        "insert_per_s": round(sn / dt),
        **_lookup_lat(sq.get, ids[:sn], samples, miss_base=1),
    }
    sq.close()
    t0 = time.perf_counter()
    sq2 = SqliteNeedleMap(sq_idx)  # O(delta): nothing to replay
    out["sqlite"]["reopen_s"] = round(time.perf_counter() - t0, 3)
    sq2.close()
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="seaweedfs_tpu.benchmark.needlemap")
    p.add_argument("-n", type=int, default=1_000_000)
    p.add_argument("-samples", type=int, default=100_000)
    p.add_argument("-dir", default="")
    a = p.parse_args(argv)
    workdir = a.dir or tempfile.mkdtemp(prefix="nmbench_")
    try:
        print(json.dumps(bench(a.n, a.samples, workdir), indent=2))
    finally:
        if not a.dir:
            shutil.rmtree(workdir, ignore_errors=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
