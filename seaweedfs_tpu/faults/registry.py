"""Deterministic, seedable fault-injection registry.

Production code declares *named fault points* at the seams that guard
durability (disk reads, shard publishes, device encodes, peer RPCs):

    faults.fire("ec.rebuild.before_rename", base=base)      # may raise
    data = faults.mutate("storage.disk.read_at", data, ...) # may corrupt

Both are a single module-level bool check when nothing is injected —
the registry being empty means the fast path does no dict lookup, no
lock, no allocation, and cannot change behavior (asserted by
tests/test_ec_chaos.py::test_disabled_registry_is_noop).

Tests arm points with a *trigger* (nth-call, every-nth,
probability-with-seed, always) and an *action* (raise an IOError,
inject latency, flip seeded bits, tear a write/read short, crash):

    with faults.injected("storage.disk.read_at",
                         faults.bit_flip(seed=7), when=faults.nth_call(3)):
        ...

Determinism: every probabilistic trigger and every byte-corrupting
action owns a private `random.Random(seed)`, so a fault schedule replays
bit-identically from its seed — the property the chaos harness's
"recovers bit-exact or refuses fail-closed" assertions rest on.

Crash semantics: `crash()` raises InjectedCrash (a BaseException — an
ordinary `except Exception` recovery path cannot swallow a simulated
process death), while `hard_exit()` calls os._exit so not even cleanup
handlers run — the faithful model of power loss inside a publish
window, used via a forked child (see tests/test_ec_chaos.py).
"""

from __future__ import annotations

import os
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator


class InjectedFault(Exception):
    """Base for injected non-crash failures."""


class InjectedIOError(InjectedFault, IOError):
    """Injected I/O failure; inherits IOError so production handlers
    classify it exactly like a real disk error."""


class InjectedCrash(BaseException):
    """Simulated process death. Deliberately NOT an Exception: recovery
    code that catches Exception must not be able to 'survive' a crash."""


# --------------------------------------------------------------- triggers
#
# A trigger is a zero-arg callable evaluated once per arrival at the
# fault point; True means the action fires for this call. Each factory
# returns a fresh stateful closure, so one trigger instance must not be
# shared across faults.


def always() -> Callable[[], bool]:
    return lambda: True


def nth_call(n: int) -> Callable[[], bool]:
    """Fire on exactly the nth arrival (1-based), never again."""
    state = {"calls": 0}

    def check() -> bool:
        state["calls"] += 1
        return state["calls"] == n

    return check


def every(n: int) -> Callable[[], bool]:
    """Fire on every nth arrival."""
    state = {"calls": 0}

    def check() -> bool:
        state["calls"] += 1
        return state["calls"] % n == 0

    return check


def probability(p: float, seed: int = 0) -> Callable[[], bool]:
    """Fire with probability p per arrival, deterministically from seed."""
    rng = random.Random(seed)
    return lambda: rng.random() < p


# ---------------------------------------------------------------- actions
#
# Fire-actions take the call context dict and either return None or
# raise. Mutate-actions additionally take the byte payload and return
# the (possibly corrupted) replacement.


def io_error(msg: str = "injected I/O error") -> Callable[[dict], None]:
    def act(ctx: dict) -> None:
        raise InjectedIOError(f"{msg} at {ctx.get('point', '?')}")

    return act


def latency(seconds: float, sleep: Callable[[float], None] = time.sleep):
    def act(ctx: dict) -> None:
        sleep(seconds)

    return act


def crash(msg: str = "injected crash") -> Callable[[dict], None]:
    def act(ctx: dict) -> None:
        raise InjectedCrash(f"{msg} at {ctx.get('point', '?')}")

    return act


def hard_exit(code: int = 137) -> Callable[[dict], None]:
    """Immediate process death: no finally blocks, no atexit — the
    publish-window crash model. Only sane inside a forked child."""

    def act(ctx: dict) -> None:
        os._exit(code)

    return act


def bit_flip(seed: int = 0, flips: int = 1) -> Callable[[dict, bytes], bytes]:
    """Flip `flips` seeded-random bits of the payload (no-op on empty)."""
    rng = random.Random(seed)

    def act(ctx: dict, data: bytes) -> bytes:
        if not data:
            return data
        buf = bytearray(data)
        for _ in range(flips):
            i = rng.randrange(len(buf))
            buf[i] ^= 1 << rng.randrange(8)
        return bytes(buf)

    return act


def truncate(keep_fraction: float = 0.5) -> Callable[[dict, bytes], bytes]:
    """Torn read/write: keep only a prefix of the payload."""

    def act(ctx: dict, data: bytes) -> bytes:
        return data[: int(len(data) * keep_fraction)]

    return act


def zero_fill() -> Callable[[dict, bytes], bytes]:
    """Return an all-zero payload of the same length (dropped DMA)."""

    def act(ctx: dict, data: bytes) -> bytes:
        return b"\x00" * len(data)

    return act


# --------------------------------------------------------------- registry


@dataclass
class _Fault:
    point: str
    action: Callable
    trigger: Callable[[], bool]
    count: int | None  # max fires; None = unlimited
    mutates: bool
    fired: int = 0
    hits: int = 0  # arrivals while armed (trigger evaluated)


@dataclass
class FaultHandle:
    """Returned by inject(); usable to remove the fault and observe it."""

    _registry: "FaultRegistry"
    _fault: _Fault = field(repr=False)

    @property
    def fired(self) -> int:
        return self._fault.fired

    @property
    def hits(self) -> int:
        return self._fault.hits

    def remove(self) -> None:
        self._registry.remove(self)


class FaultRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._faults: dict[str, list[_Fault]] = {}
        # Plain-bool fast-path flag, read unlocked in fire()/mutate().
        # inject() flips it under the lock after the fault is stored, so
        # an armed fault is never missed; a racing reader at worst takes
        # one extra locked lookup against an already-empty table.
        self.armed = False

    def inject(
        self,
        point: str,
        action: Callable,
        when: Callable[[], bool] | None = None,
        count: int | None = None,
        mutates: bool | None = None,
    ) -> FaultHandle:
        """Arm `action` at `point`. `when` defaults to always();
        `count` caps total fires. Mutation is auto-detected from the
        action arity unless `mutates` is passed."""
        if mutates is None:
            import inspect

            try:
                mutates = len(inspect.signature(action).parameters) >= 2
            except (TypeError, ValueError):
                mutates = False
        f = _Fault(
            point=point,
            action=action,
            trigger=when or always(),
            count=count,
            mutates=bool(mutates),
        )
        with self._lock:
            self._faults.setdefault(point, []).append(f)
            self.armed = True
        return FaultHandle(self, f)

    def remove(self, handle: FaultHandle) -> None:
        with self._lock:
            lst = self._faults.get(handle._fault.point)
            if lst and handle._fault in lst:
                lst.remove(handle._fault)
                if not lst:
                    del self._faults[handle._fault.point]
            self.armed = bool(self._faults)

    def clear(self) -> None:
        with self._lock:
            self._faults.clear()
            self.armed = False

    def _due(self, point: str, mutating: bool) -> list[_Fault]:
        """Trigger-evaluate every fault at `point`; return those firing
        now. Runs under the lock: triggers are cheap and stateful."""
        due = []
        with self._lock:
            for f in self._faults.get(point, ()):
                if f.mutates != mutating:
                    continue
                f.hits += 1
                if f.count is not None and f.fired >= f.count:
                    continue
                if f.trigger():
                    f.fired += 1
                    due.append(f)
        return due

    def fire(self, point: str, **ctx: Any) -> None:
        """Evaluate non-mutating faults at `point` (may raise/sleep)."""
        for f in self._due(point, mutating=False):
            ctx["point"] = point
            f.action(ctx)

    def mutate(self, point: str, data: bytes, **ctx: Any) -> bytes:
        """Run mutating faults at `point` over `data`."""
        for f in self._due(point, mutating=True):
            ctx["point"] = point
            data = f.action(ctx, data)
        return data

    def counters(self) -> dict[str, int]:
        """point -> total fires, for assertions and ops introspection."""
        with self._lock:
            out: dict[str, int] = {}
            for point, lst in self._faults.items():
                out[point] = sum(f.fired for f in lst)
            return out

    def armed_points(self) -> frozenset[str]:
        """The point names currently armed. Lets transport routers make
        NAMESPACE decisions instead of the all-or-nothing `armed` bool:
        the net plane refuses service while chaos targets storage-layer
        points (the Python fallback carries those), but keeps serving
        when the armed points live on the plane's own seams — otherwise
        its crash windows could never be exercised."""
        with self._lock:
            return frozenset(self._faults)


# Module-level singleton + free functions: the production call sites use
# these, so the disabled fast path is one global-bool check deep.

REGISTRY = FaultRegistry()


def fire(point: str, **ctx: Any) -> None:
    if not REGISTRY.armed:
        return
    REGISTRY.fire(point, **ctx)


def mutate(point: str, data: bytes, **ctx: Any) -> bytes:
    if not REGISTRY.armed:
        return data
    return REGISTRY.mutate(point, data, **ctx)


def inject(
    point: str,
    action: Callable,
    when: Callable[[], bool] | None = None,
    count: int | None = None,
    mutates: bool | None = None,
) -> FaultHandle:
    return REGISTRY.inject(point, action, when=when, count=count, mutates=mutates)


def clear() -> None:
    REGISTRY.clear()


def active() -> bool:
    return REGISTRY.armed


def armed_points() -> frozenset[str]:
    if not REGISTRY.armed:
        return frozenset()
    return REGISTRY.armed_points()


@contextmanager
def injected(
    point: str,
    action: Callable,
    when: Callable[[], bool] | None = None,
    count: int | None = None,
    mutates: bool | None = None,
) -> Iterator[FaultHandle]:
    h = inject(point, action, when=when, count=count, mutates=mutates)
    try:
        yield h
    finally:
        h.remove()
