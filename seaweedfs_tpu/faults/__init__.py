"""Deterministic fault injection for durability testing.

See registry.py for the model: named fault points in production code,
seeded triggers + actions armed by tests, a single-bool no-op fast path
when nothing is injected.
"""

from .registry import (
    REGISTRY,
    FaultHandle,
    FaultRegistry,
    InjectedCrash,
    InjectedFault,
    InjectedIOError,
    active,
    always,
    bit_flip,
    clear,
    crash,
    every,
    fire,
    hard_exit,
    inject,
    injected,
    io_error,
    latency,
    mutate,
    nth_call,
    probability,
    truncate,
    zero_fill,
)

__all__ = [
    "REGISTRY",
    "FaultHandle",
    "FaultRegistry",
    "InjectedCrash",
    "InjectedFault",
    "InjectedIOError",
    "active",
    "always",
    "bit_flip",
    "clear",
    "crash",
    "every",
    "fire",
    "hard_exit",
    "inject",
    "injected",
    "io_error",
    "latency",
    "mutate",
    "nth_call",
    "probability",
    "truncate",
    "zero_fill",
]
