"""GF(2^8) arithmetic and Reed-Solomon matrices, klauspost-compatible.

This is the CPU/numpy *reference* implementation that every accelerated
path (XLA bit-plane matmul, Pallas TPU kernel, C++ native) must match
bit-for-bit.

Compatibility target: klauspost/reedsolomon v1.14.1 with default options,
as used by the reference at weed/storage/erasure_coding/ec_context.go:45
(`reedsolomon.New(dataShards, parityShards)`), i.e.:

- field GF(2^8) with primitive polynomial x^8+x^4+x^3+x^2+1 (0x11D),
  generator element 2 (same log/exp tables as Backblaze JavaReedSolomon);
- systematic generator matrix built from an extended Vandermonde matrix:
  vm = vandermonde(totalShards, dataShards)
  matrix = vm * inverse(vm[0:dataShards, 0:dataShards])
  so the top k rows are the identity and the bottom m rows are the
  parity coefficients.

Because GF arithmetic is exact integer math, "bit-exact" reduces to
(a) identical matrix construction and (b) correct field arithmetic —
both are locked by golden vectors in tests/test_gf256.py.
"""

from __future__ import annotations

import functools

import numpy as np

GF_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1
FIELD_SIZE = 256


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(255, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    return exp, log


EXP_TABLE, LOG_TABLE = _build_tables()


@functools.cache
def _mul_table() -> np.ndarray:
    """Full 256x256 GF multiplication table (64KB)."""
    a = np.arange(256)
    la = LOG_TABLE[a][:, None]  # (256,1)
    lb = LOG_TABLE[a][None, :]  # (1,256)
    prod = EXP_TABLE[(la + lb) % 255]
    prod = prod.copy()
    prod[0, :] = 0
    prod[:, 0] = 0
    return prod.astype(np.uint8)


def gal_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(EXP_TABLE[(int(LOG_TABLE[a]) + int(LOG_TABLE[b])) % 255])


def gal_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(256) division by zero")
    if a == 0:
        return 0
    return int(EXP_TABLE[(int(LOG_TABLE[a]) - int(LOG_TABLE[b])) % 255])


def gal_exp(a: int, n: int) -> int:
    """a**n in GF(256); matches klauspost galExp (a=0 -> 0, n=0 -> 1)."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(EXP_TABLE[(int(LOG_TABLE[a]) * n) % 255])


def gal_inverse(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(256) inverse of zero")
    return int(EXP_TABLE[(255 - int(LOG_TABLE[a])) % 255])


# ---------------------------------------------------------------------------
# Matrices over GF(256) — stored as 2D uint8 numpy arrays.
# ---------------------------------------------------------------------------


def identity_matrix(n: int) -> np.ndarray:
    return np.eye(n, dtype=np.uint8)


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """m[r][c] = r**c in GF(256) (klauspost vandermonde())."""
    m = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            m[r, c] = gal_exp(r, c)
    return m


def matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(256)."""
    if a.shape[1] != b.shape[0]:
        raise ValueError(f"shape mismatch {a.shape} @ {b.shape}")
    mt = _mul_table()
    # out[i,j] = XOR_k mul(a[i,k], b[k,j])
    prods = mt[a[:, :, None], b[None, :, :]]  # (I,K,J)
    return np.bitwise_xor.reduce(prods, axis=1)


def invert(m: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inversion over GF(256); raises on singular input."""
    n = m.shape[0]
    if m.shape[0] != m.shape[1]:
        raise ValueError("only square matrices can be inverted")
    mt = _mul_table()
    work = np.concatenate([m.astype(np.uint8), identity_matrix(n)], axis=1)
    for col in range(n):
        if work[col, col] == 0:
            pivot = -1
            for r in range(col + 1, n):
                if work[r, col] != 0:
                    pivot = r
                    break
            if pivot < 0:
                raise np.linalg.LinAlgError("matrix is singular over GF(256)")
            work[[col, pivot]] = work[[pivot, col]]
        inv_pivot = gal_inverse(int(work[col, col]))
        work[col] = mt[inv_pivot, work[col]]
        for r in range(n):
            if r != col and work[r, col] != 0:
                work[r] ^= mt[int(work[r, col]), work[col]]
    return work[:, n:].copy()


def build_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """Systematic RS generator matrix, klauspost buildMatrix() exactly.

    Top `data_shards` rows are the identity; the remaining rows are the
    parity coefficients.
    """
    vm = vandermonde(total_shards, data_shards)
    top = vm[:data_shards, :data_shards]
    return matmul(vm, invert(top))


def parity_rows(data_shards: int, parity_shards: int) -> np.ndarray:
    """The (parity_shards x data_shards) coefficient block."""
    return build_matrix(data_shards, data_shards + parity_shards)[data_shards:]


# ---------------------------------------------------------------------------
# GF(2) bit-plane expansion: multiplying by a GF(256) constant is a linear
# map over GF(2)^8, so an (m x k) GF(256) matrix expands to an
# (8m x 8k) 0/1 matrix. byte-wise RS encode == bit-wise XOR matmul, which
# the TPU runs as an integer matmul followed by &1 (ops/rs_jax.py).
# Bit order: bit i (LSB=0) of output byte = XOR over inputs of
# bitmatrix[8*row + i, 8*col + j] * (bit j of input byte).
# ---------------------------------------------------------------------------


def constant_bit_matrix(c: int) -> np.ndarray:
    """8x8 GF(2) matrix of y = c*x: column j = bits of gal_mul(c, 1<<j)."""
    m = np.zeros((8, 8), dtype=np.uint8)
    for j in range(8):
        prod = gal_mul(c, 1 << j)
        for i in range(8):
            m[i, j] = (prod >> i) & 1
    return m


def expand_bit_matrix(coeffs: np.ndarray) -> np.ndarray:
    """(m x k) GF(256) matrix -> (8m x 8k) GF(2) matrix."""
    m, k = coeffs.shape
    out = np.zeros((8 * m, 8 * k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            out[8 * i : 8 * i + 8, 8 * j : 8 * j + 8] = constant_bit_matrix(
                int(coeffs[i, j])
            )
    return out


# ---------------------------------------------------------------------------
# Reference (numpy) Reed-Solomon codec.
# ---------------------------------------------------------------------------


def matrix_apply(coeffs: np.ndarray, data: np.ndarray) -> np.ndarray:
    """out[i] = XOR_j gf_mul(coeffs[i,j], data[j]); data is (k, n) uint8."""
    mt = _mul_table()
    k = coeffs.shape[1]
    if data.shape[0] != k:
        raise ValueError(f"coeffs expect {k} rows, got {data.shape[0]}")
    out = np.zeros((coeffs.shape[0], data.shape[1]), dtype=np.uint8)
    for i in range(coeffs.shape[0]):
        acc = out[i]
        for j in range(k):
            c = int(coeffs[i, j])
            if c == 0:
                continue
            if c == 1:
                acc ^= data[j]
            else:
                acc ^= mt[c, data[j]]
    return out


class ReedSolomon:
    """klauspost-equivalent RS codec over equal-length byte shards.

    Mirrors the subset of github.com/klauspost/reedsolomon the reference
    uses: Encode, Verify, Reconstruct, ReconstructData
    (weed/storage/erasure_coding/ec_encoder.go + store_ec.go call sites).
    """

    def __init__(self, data_shards: int, parity_shards: int):
        if data_shards <= 0 or parity_shards <= 0:
            raise ValueError("shard counts must be positive")
        if data_shards + parity_shards > 256:
            raise ValueError("too many shards for GF(256)")
        self.k = data_shards
        self.m = parity_shards
        self.n = data_shards + parity_shards
        self.matrix = build_matrix(self.k, self.n)
        self.parity = self.matrix[self.k :]

    def encode(self, data: np.ndarray) -> np.ndarray:
        """(k, n_bytes) data -> (m, n_bytes) parity."""
        return matrix_apply(self.parity, np.ascontiguousarray(data, dtype=np.uint8))

    def verify(self, shards: np.ndarray) -> bool:
        """shards is (k+m, n_bytes); True iff parity matches data."""
        expect = self.encode(shards[: self.k])
        return bool(np.array_equal(expect, shards[self.k :]))

    def _decode_matrix(self, present: list[int]) -> np.ndarray:
        """Inverse of the k x k submatrix for the first k present shards."""
        rows = present[: self.k]
        if len(rows) < self.k:
            raise ValueError(
                f"need at least {self.k} shards, have {len(present)}"
            )
        sub = self.matrix[rows, :]
        return invert(sub)

    def reconstruct(
        self, shards: dict[int, np.ndarray], data_only: bool = False
    ) -> dict[int, np.ndarray]:
        """Recover missing shards from any >=k present ones.

        `shards` maps shard index -> bytes for present shards. Returns a
        dict of the recovered shards (data first, then parity unless
        data_only). Mirrors klauspost Reconstruct/ReconstructData.
        """
        present = sorted(shards)
        if len(present) < self.k:
            raise ValueError(
                f"need at least {self.k} shards, have {len(present)}"
            )
        missing_data = [i for i in range(self.k) if i not in shards]
        missing_parity = [i for i in range(self.k, self.n) if i not in shards]
        out: dict[int, np.ndarray] = {}
        if missing_data:
            dec = self._decode_matrix(present)
            src = np.stack([shards[i] for i in present[: self.k]])
            rows = dec[missing_data, :]
            recovered = matrix_apply(rows, src)
            for idx, row in zip(missing_data, recovered):
                out[idx] = row
        if missing_parity and not data_only:
            full_data = np.stack(
                [shards[i] if i in shards else out[i] for i in range(self.k)]
            )
            rows = self.parity[[i - self.k for i in missing_parity], :]
            recovered = matrix_apply(rows, full_data)
            for idx, row in zip(missing_parity, recovered):
                out[idx] = row
        return out
