"""Fused Pallas TPU kernel for GF(2^8) Reed-Solomon bit-plane matmuls.

The XLA path in ops/rs_jax.py materialises the (8k, n) bit expansion in
HBM (~8x traffic). This kernel keeps the expansion in VMEM: each grid
step DMAs a byte tile, unpacks the 8 bit-planes, runs 8 small MXU
matmuls against contiguous column blocks of the *bit-major* matrix
(ops/rs_jax.bit_matrix_bitmajor layout), packs the output bits back to
bytes, and writes the parity tile — HBM traffic stays ~1x in + 1x out.

Byte-packing trick (pack_width W in {1, 2, 4}): W consecutive bytes are
processed as one uint(8W) lane. Plane j of a word is `(w >> j) & MASK`
with MASK = 0x0101.. — each byte's bit j stays in its own byte lane.
Matmul sums are <= 8k <= 2048 per byte lane, so no carries cross byte
boundaries and the packed accumulator word holds each byte's exact sum.
Parity bits come back out with `(acc & MASK) << i`. Everything is
endian-agnostic because pack and unpack mirror each other.

Exactness: f32 accumulators are exact for packed values < 2^24, which
bounds W*8-bit words to W <= 2 (max sum 8k * 0x00010001 < 2^24 for
k <= 16... actually 80 * 65537 ~ 5.2e6 << 2^24). W=4 requires integer
matmul accumulation and is gated behind pack_width=4.

Reference hot loop being replaced:
weed/storage/erasure_coding/ec_encoder.go:427 (encodeDataOneBatch).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Default word-column tile (lanes of packed words). VMEM use is dominated
# by the f32 planes/accumulator: ~ (8m + k) * TILE_N * 4B.
TILE_N = 16384

_WORD_DTYPES = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}
_MASKS = {1: 0x01, 2: 0x0101, 4: 0x01010101}


def _rs_kernel(k: int, m: int, pack_width: int, b_ref, d_ref, out_ref):
    """b_ref: (8m, 8k) f32 bit-major; d_ref: (k, TN) uintW words."""
    # All integer work is int32: Mosaic lacks uint32<->f32 casts, and
    # arithmetic right-shift is safe because the masked bit positions
    # (0, 8, 16, 24) sit below any sign-extension for shifts <= 7.
    mask = _MASKS[pack_width]
    acc_dtype = jnp.int32 if pack_width == 4 else jnp.float32
    d = d_ref[:].astype(jnp.int32)
    acc = jnp.zeros((8 * m, d.shape[1]), dtype=acc_dtype)
    for j in range(8):
        plane = ((d >> j) & mask).astype(acc_dtype)
        b_cols = b_ref[:, j * k : (j + 1) * k].astype(acc_dtype)
        acc = acc + jax.lax.dot_general(
            b_cols,
            plane,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=acc_dtype,
        )
    acci = acc.astype(jnp.int32)
    out = jnp.zeros((m, d.shape[1]), dtype=jnp.int32)
    for i in range(8):
        out = out | ((acci[i * m : (i + 1) * m] & mask) << i)
    out_ref[:] = out.astype(_WORD_DTYPES[pack_width])


@functools.partial(
    jax.jit, static_argnames=("k", "m", "tile_n", "pack_width", "interpret")
)
def apply_bitmajor_pallas(
    b,
    data,
    *,
    k: int,
    m: int,
    tile_n: int = TILE_N,
    pack_width: int = 2,
    interpret: bool = False,
):
    """(8m x 8k) bit-major GF(2) matrix applied to (k, n) uint8 -> (m, n).

    n is padded to a tile multiple internally (RS of zero bytes is zero,
    so padding never corrupts real columns).
    """
    if pack_width not in _WORD_DTYPES:
        raise ValueError(f"pack_width must be 1, 2 or 4, got {pack_width}")
    n = data.shape[1]
    bytes_per_tile = tile_n * pack_width
    pad = (-n) % bytes_per_tile
    if pad:
        data = jnp.pad(data, ((0, 0), (0, pad)))
    n_padded = data.shape[1]
    if pack_width > 1:
        words = jax.lax.bitcast_convert_type(
            data.reshape(k, n_padded // pack_width, pack_width),
            _WORD_DTYPES[pack_width],
        )
    else:
        words = data
    grid = (words.shape[1] // tile_n,)
    out_words = pl.pallas_call(
        functools.partial(_rs_kernel, k, m, pack_width),
        out_shape=jax.ShapeDtypeStruct((m, words.shape[1]), _WORD_DTYPES[pack_width]),
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * m, 8 * k), lambda i: (0, 0)),
            pl.BlockSpec((k, tile_n), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((m, tile_n), lambda i: (0, i)),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * 8 * m * 8 * k * words.shape[1],
            bytes_accessed=(k + m) * n_padded + 64 * m * k * 4,
            transcendentals=0,
        ),
    )(b.astype(jnp.float32), words)
    if pack_width > 1:
        out = jax.lax.bitcast_convert_type(out_words, jnp.uint8).reshape(
            m, n_padded
        )
    else:
        out = out_words
    return out[:, :n] if pad else out


def is_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon") or any(
            d.platform in ("tpu", "axon") for d in jax.devices()
        )
    except Exception:
        return False
