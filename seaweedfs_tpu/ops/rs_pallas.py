"""Fused Pallas TPU kernel for GF(2^8) Reed-Solomon bit-plane matmuls.

The XLA path in ops/rs_jax.py materialises the (8k, n) bit expansion in
HBM (~8x traffic). This kernel keeps the expansion in VMEM: each grid
step DMAs a byte tile, unpacks the 8 bit-planes, runs 8 small MXU
matmuls against contiguous column blocks of the *bit-major* matrix
(ops/rs_jax.bit_matrix_bitmajor layout), packs the output bits back to
bytes, and writes the parity tile — HBM traffic stays ~1x in + 1x out.

Byte-packing trick (pack_width W in {1, 2, 4}): W consecutive bytes are
processed as one uint(8W) lane. Plane j of a word is `(w >> j) & MASK`
with MASK = 0x0101.. — each byte's bit j stays in its own byte lane.
Matmul sums are <= 8k <= 2048 per byte lane, so no carries cross byte
boundaries and the packed accumulator word holds each byte's exact sum.
Parity bits come back out with `(acc & MASK) << i`. Everything is
endian-agnostic because pack and unpack mirror each other.

Exactness — MEASURED ON REAL v5e HARDWARE, not just interpret mode:
the MXU executes "f32" matmuls as bf16 passes (8-bit mantissa) unless
precision=HIGHEST is requested. Packed pw=2 sums reach 80*0x0101=20560,
which bf16 silently rounds — the low byte of every output word corrupts
while interpret mode (true f32) passes. Consequences baked in here:

- pack_width=1 (sums <= 8k <= 128, exact even in bf16) is the DEFAULT,
  run as a single contraction-8k dot in int8 (exact integer MXU path,
  ~3x the f32 j-loop throughput on v5e);
- pack_width=2 f32 dots force precision=HIGHEST (exact, slower);
- pack_width=4 would need >24-bit exact accumulation — rejected.

Reference hot loop being replaced:
weed/storage/erasure_coding/ec_encoder.go:427 (encodeDataOneBatch).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (memory spaces)

from . import gf256

# Default word-column tile. Measured sweet spot on v5e for the pw=1
# int8 single-dot kernel (8192 beat 16384 by ~25%); VMEM use is
# dominated by the (8k, TN) plane block + (8m, TN) accumulator.
TILE_N = 8192

_WORD_DTYPES = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32}
_MASKS = {1: 0x01, 2: 0x0101, 4: 0x01010101}


def _rs_kernel(k: int, m: int, pack_width: int, b_ref, d_ref, out_ref):
    """b_ref: (8m, 8k) bit-major; d_ref: (k, TN) uintW words.

    One contraction-(8k) dot per tile, not 8 contraction-k dots: the MXU
    is weight-stationary, so contraction length is utilization (80/128
    vs 10/128 for the default 10+4 codec — measured ~3x on v5e).

    All integer lane work is int32: Mosaic lacks uint32<->f32 casts,
    int8-domain shifts hang its remote compiler (observed on v5e), and
    arithmetic right-shift is safe because the masked bit positions
    (0, 8, 16, 24) sit below any sign-extension for shifts <= 7.
    """
    mask = _MASKS[pack_width]
    if pack_width == 4:
        raise NotImplementedError(
            "pack_width=4 needs >24-bit exact matmul accumulation, which "
            "the TPU MXU does not provide (int32 dots unsupported, f32 "
            "dots are inexact past 2^24)"
        )
    d = d_ref[:].astype(jnp.int32)
    planes = jnp.concatenate([(d >> j) & mask for j in range(8)], axis=0)
    if pack_width == 1:
        # 0/1 planes fit int8: exact integer MXU path, ~2x f32 rate.
        acc = jax.lax.dot_general(
            b_ref[:].astype(jnp.int8),
            planes.astype(jnp.int8),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        acci = acc
    else:
        # Packed sums reach 8k * 0x0101 (~20k): exact only if the MXU
        # really accumulates f32 — HIGHEST forces the multi-pass f32
        # path (default precision runs bf16 passes and corrupts the low
        # byte of every word; caught by the bit-exactness suite).
        acc = jax.lax.dot_general(
            b_ref[:].astype(jnp.float32),
            planes.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        acci = acc.astype(jnp.int32)
    out = jnp.zeros((m, d.shape[1]), dtype=jnp.int32)
    for i in range(8):
        out = out | ((acci[i * m : (i + 1) * m] & mask) << i)
    out_ref[:] = out.astype(_WORD_DTYPES[pack_width])


def _pallas_apply(
    kernel,
    b,
    data,
    *,
    k: int,
    out_rows: int,
    keep_rows: int,
    b_block: tuple,
    tile_n: int,
    pack_width: int,
    interpret: bool,
):
    """Shared pad → pack-to-words → pallas_call → unpack scaffolding.

    `out_rows` is the kernel's output block height (possibly padded);
    `keep_rows` is how many real parity rows the caller gets back.
    n is padded to a tile multiple internally (RS of zero bytes is zero,
    so padding never corrupts real columns).
    """
    if pack_width not in _WORD_DTYPES:
        raise ValueError(f"pack_width must be 1, 2 or 4, got {pack_width}")
    n = data.shape[1]
    bytes_per_tile = tile_n * pack_width
    pad = (-n) % bytes_per_tile
    if pad:
        data = jnp.pad(data, ((0, 0), (0, pad)))
    n_padded = data.shape[1]
    if pack_width > 1:
        words = jax.lax.bitcast_convert_type(
            data.reshape(k, n_padded // pack_width, pack_width),
            _WORD_DTYPES[pack_width],
        )
    else:
        words = data
    grid = (words.shape[1] // tile_n,)
    zeros = (0,) * len(b_block)
    out_words = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(
            (out_rows, words.shape[1]), _WORD_DTYPES[pack_width]
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(b_block, lambda i: zeros),
            pl.BlockSpec((k, tile_n), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((out_rows, tile_n), lambda i: (0, i)),
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=2 * 8 * out_rows * 8 * k * words.shape[1],
            bytes_accessed=(k + out_rows) * n_padded + 64 * out_rows * k * 4,
            transcendentals=0,
        ),
    )(b.astype(jnp.float32), words)
    if pack_width > 1:
        out = jax.lax.bitcast_convert_type(out_words, jnp.uint8).reshape(
            out_rows, n_padded
        )
    else:
        out = out_words
    return out[:keep_rows, :n]


@functools.partial(
    jax.jit, static_argnames=("k", "m", "tile_n", "pack_width", "interpret")
)
def apply_bitmajor_pallas(
    b,
    data,
    *,
    k: int,
    m: int,
    tile_n: int = TILE_N,
    pack_width: int = 1,
    interpret: bool = False,
):
    """(8m x 8k) bit-major GF(2) matrix applied to (k, n) uint8 -> (m, n)."""
    return _pallas_apply(
        functools.partial(_rs_kernel, k, m, pack_width),
        b,
        data,
        k=k,
        out_rows=m,
        keep_rows=m,
        b_block=(8 * m, 8 * k),
        tile_n=tile_n,
        pack_width=pack_width,
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# Lane-aligned variant.
#
# The compact kernel above slices the (8m, 8k) bit-matrix on the LANE
# dimension at j*k offsets (k=10 for the default codec) and writes
# (m=4, TN) output blocks — both below Mosaic's minimum tile shapes
# ((8,128) f32 / (16,128) 16-bit / (32,128) 8-bit; see
# /opt/skills/guides/pallas_guide.md "Tiling Constraints"). Interpret
# mode accepts that; real-hardware Mosaic may not. This variant keeps
# every lane dimension a multiple of 128 and never slices lanes:
#
# - the matrix is pre-transposed host-side into 8 per-input-bit planes
#   bT[j] of shape (k, 8*m_pad), m_pad = ceil16(m), so the lane dim is
#   8*m_pad (a 128 multiple) and the j-planes are indexed on the leading
#   dim, not lane-sliced;
# - each plane matmul contracts the SUBLANE dim of both operands
#   (bT[j]: (k, 8*m_pad) x plane: (k, TN) -> (8*m_pad, TN)), so the odd
#   k=10 only ever appears as a contraction length;
# - the output block is (m_pad, TN) with m_pad padded to the out word
#   dtype's min sublane count (32/16/8 for 8/16/32-bit words); the
#   caller slices the m real rows off afterwards.
#
# Cost of alignment: the out write is m_pad/m wider than needed
# (16 vs 4 rows for 10+4) — ~1.2x of the input bytes instead of 0.4x.
# ---------------------------------------------------------------------------

# Word-column tile for the aligned kernel. VMEM is dominated by the
# (8*m_pad, TN) f32 accumulator: 128 * TN * 4B = 2 MiB at TN=4096.
TILE_N_ALIGNED = 4096


# Mosaic minimum sublane counts by word width (see the tiling table in
# the pallas guide): the output block height must not go below these.
_MIN_SUBLANES = {1: 32, 2: 16, 4: 8}


def _aligned_m_pad(m: int, pack_width: int) -> int:
    """Output rows padded to BOTH a 16 multiple (lane dim 8*m_pad must be
    a 128 multiple) and the min sublane count of the out word dtype."""
    gran = max(16, _MIN_SUBLANES[pack_width])
    return ((m + gran - 1) // gran) * gran


def bit_matrix_planes(coeffs: np.ndarray, pack_width: int = 1) -> np.ndarray:
    """(m x k) GF(256) coeffs -> (8, k, 8*m_pad) f32 plane stack.

    bT[j, c, i*m_pad + r] = bit (i) of gf_mul coefficient row r applied
    to input-bit j of byte-column c — i.e. expand_bit_matrix's entry
    [8r+i, 8c+j], padded so the lane dim is a multiple of 128 and the
    kernel's (m_pad, TN) output block is sublane-legal for the word
    dtype pack_width selects.
    """
    coeffs = np.asarray(coeffs, dtype=np.uint8)
    m, k = coeffs.shape
    m_pad = _aligned_m_pad(m, pack_width)
    b = gf256.expand_bit_matrix(coeffs).reshape(m, 8, k, 8)  # [r, i, c, j]
    out = np.zeros((8, k, 8, m_pad), dtype=np.float32)
    out[:, :, :, :m] = b.transpose(3, 2, 1, 0)  # [j, c, i, r]
    return out.reshape(8, k, 8 * m_pad)


def _rs_kernel_aligned(k: int, m_pad: int, pack_width: int, b_ref, d_ref, out_ref):
    """b_ref: (8, k, 8*m_pad); d_ref: (k, TN) uintW -> (m_pad, TN).

    Same single-contraction-(8k) + exactness rules as _rs_kernel (int8
    dot for pw=1, f32 HIGHEST for pw=2): the planes are stacked on the
    sublane axis and the j dimension of b collapses into the contraction.
    """
    mask = _MASKS[pack_width]
    if pack_width == 4:
        raise NotImplementedError(
            "pack_width=4 needs >24-bit exact matmul accumulation"
        )
    d = d_ref[:].astype(jnp.int32)
    planes = jnp.concatenate([(d >> j) & mask for j in range(8)], axis=0)
    b2 = b_ref[:].reshape(8 * k, 8 * m_pad)  # rows j*k+c match plane order
    if pack_width == 1:
        acc = jax.lax.dot_general(
            b2.astype(jnp.int8),
            planes.astype(jnp.int8),
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        acci = acc
    else:
        # Packed sums exceed 8 bits: the MXU's default bf16 passes would
        # corrupt them — force the exact multi-pass f32 path.
        acc = jax.lax.dot_general(
            b2.astype(jnp.float32),
            planes.astype(jnp.float32),
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        acci = acc.astype(jnp.int32)
    out = jnp.zeros((m_pad, d.shape[1]), dtype=jnp.int32)
    for i in range(8):
        out = out | ((acci[i * m_pad : (i + 1) * m_pad] & mask) << i)
    out_ref[:] = out.astype(_WORD_DTYPES[pack_width])


@functools.partial(
    jax.jit, static_argnames=("k", "m", "tile_n", "pack_width", "interpret")
)
def apply_planes_pallas(
    b_planes,
    data,
    *,
    k: int,
    m: int,
    tile_n: int = TILE_N_ALIGNED,
    pack_width: int = 1,
    interpret: bool = False,
):
    """Aligned-layout twin of apply_bitmajor_pallas.

    b_planes: (8, k, 8*m_pad) from bit_matrix_planes; data (k, n) uint8
    -> (m, n) uint8.
    """
    if pack_width not in _WORD_DTYPES:
        raise ValueError(f"pack_width must be 1, 2 or 4, got {pack_width}")
    m_pad = b_planes.shape[2] // 8
    if m_pad % _aligned_m_pad(1, pack_width):
        raise ValueError(
            f"b_planes m_pad={m_pad} is not sublane-legal for "
            f"pack_width={pack_width}; build it with "
            f"bit_matrix_planes(coeffs, pack_width={pack_width})"
        )
    if m > m_pad:
        raise ValueError(
            f"m={m} exceeds the {m_pad} rows b_planes encodes"
        )
    return _pallas_apply(
        functools.partial(_rs_kernel_aligned, k, m_pad, pack_width),
        b_planes,
        data,
        k=k,
        out_rows=m_pad,
        keep_rows=m,
        b_block=(8, k, 8 * m_pad),
        tile_n=tile_n,
        pack_width=pack_width,
        interpret=interpret,
    )


# NOTE: device-presence decisions live in utils/devices.py
# (watchdogged subprocess probe) — an in-process jax.devices() call
# hangs forever when the TPU relay is down.
