"""Reed-Solomon GF(2^8) encode/reconstruct as XLA matmuls (TPU MXU).

The reference's hot loop (weed/storage/erasure_coding/ec_encoder.go:427
encodeDataOneBatch) calls klauspost's SIMD GF(2^8) multiply-accumulate.
On TPU there is no byte-gather ALU path, but GF(256) multiplication by a
constant is a *linear map over GF(2)^8*. An (m x k) GF(256) coefficient
matrix therefore expands to an (8m x 8k) 0/1 matrix B, and

    parity_bits = (B @ data_bits) mod 2

is an ordinary integer matmul — exactly what the MXU does — followed by
a cheap `& 1`. Accumulation values are bounded by 8k <= 2048 so f32/i32
accumulators are exact, and the result is bit-identical to the CPU path.

Two layouts are provided:

- `_apply_bits` (used by RSJax.encode/reconstruct): straightforward XLA
  path (unpack -> (8k, n) bits -> matmul -> pack). XLA fuses the
  shifts/masks around the matmul; HBM traffic is ~8x the byte count
  (bits stored as int8).
- `_apply_bits_bitmajor` + `bit_matrix_bitmajor`: a bit-major
  permutation of B so that unpack/pack touch only contiguous row/column
  blocks — the layout the fused Pallas kernel builds on to keep HBM
  traffic at 1x.
"""

from __future__ import annotations

import collections
import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256

# Accumulator dtype: int32 matmuls hit the MXU int8 path on v5e+; f32 is
# the safe fallback everywhere (values <= 2048 are exact in f32).
_ACC_DTYPE = jnp.float32


def bit_matrix(coeffs: np.ndarray) -> np.ndarray:
    """(m x k) GF(256) coeffs -> (8m x 8k) GF(2) matrix (byte-major)."""
    return gf256.expand_bit_matrix(np.asarray(coeffs, dtype=np.uint8))


def bit_matrix_bitmajor(coeffs: np.ndarray) -> np.ndarray:
    """Bit-major permutation of `bit_matrix`.

    Rows ordered bit-major: row (i*m + r) is output-bit i of byte-row r.
    Cols ordered bit-major: col (j*k + c) is input-bit j of byte-col c.
    With this layout, input bit-plane j of all k shards is the contiguous
    column block [j*k, (j+1)*k) and output bit-plane i is the contiguous
    row block [i*m, (i+1)*m) — no strided access inside a kernel.
    """
    m, k = np.asarray(coeffs).shape
    b = bit_matrix(coeffs)
    return (
        b.reshape(m, 8, k, 8).transpose(1, 0, 3, 2).reshape(8 * m, 8 * k).copy()
    )


@functools.partial(jax.jit, static_argnames=())
def _apply_bits(b: jax.Array, data: jax.Array) -> jax.Array:
    """b: (8m, 8k) f32; data: (k, n) uint8 -> (m, n) uint8."""
    k = data.shape[0]
    m = b.shape[0] // 8
    bits = (data[:, None, :] >> jnp.arange(8, dtype=jnp.uint8)[None, :, None]) & 1
    bits = bits.reshape(8 * k, -1).astype(_ACC_DTYPE)
    acc = jnp.matmul(b, bits, preferred_element_type=_ACC_DTYPE)
    pbits = acc.astype(jnp.int32) & 1
    pbits = pbits.reshape(m, 8, -1)
    out = (pbits << jnp.arange(8, dtype=jnp.int32)[None, :, None]).sum(
        axis=1, dtype=jnp.int32
    )
    return out.astype(jnp.uint8)


@functools.partial(jax.jit, donate_argnums=())
def _apply_bits_bitmajor(b: jax.Array, data: jax.Array) -> jax.Array:
    """Same contract as _apply_bits but with bit-major b (see above)."""
    k = data.shape[0]
    m = b.shape[0] // 8
    d = data.astype(jnp.int32)
    acc = jnp.zeros((8 * m, data.shape[1]), dtype=_ACC_DTYPE)
    for j in range(8):
        plane = ((d >> j) & 1).astype(_ACC_DTYPE)
        acc = acc + jnp.matmul(
            b[:, j * k : (j + 1) * k], plane, preferred_element_type=_ACC_DTYPE
        )
    out = jnp.zeros((m, data.shape[1]), dtype=jnp.int32)
    acci = acc.astype(jnp.int32)
    for i in range(8):
        out = out | ((acci[i * m : (i + 1) * m] & 1) << i)
    return out.astype(jnp.uint8)


class RSJax:
    """Jitted RS codec. All GF matrix work happens host-side (numpy);
    the device only ever sees 0/1 matmuls.

    Mirrors the call surface the reference uses (Encode / Reconstruct /
    ReconstructData, weed/storage/erasure_coding + store_ec.go).
    """

    def __init__(
        self,
        data_shards: int,
        parity_shards: int,
        impl: str = "xla",
        interpret: bool = False,
        tile_n: int | None = None,
    ):
        """impl: "xla" (portable), "pallas" (fused TPU kernel, compact
        layout, 1x HBM traffic), or "pallas_aligned" (lane-aligned
        Mosaic-conservative layout — see rs_pallas.py); `interpret=True`
        runs the pallas kernels off-TPU for tests."""
        if impl not in ("xla", "pallas", "pallas_aligned"):
            raise ValueError(f"unknown impl {impl!r}")
        self.k = data_shards
        self.m = parity_shards
        self.n = data_shards + parity_shards
        self.impl = impl
        self.interpret = interpret
        self.tile_n = tile_n
        self._ref = gf256.ReedSolomon(data_shards, parity_shards)
        self.matrix = self._ref.matrix
        if impl == "pallas":
            expand = bit_matrix_bitmajor
        elif impl == "pallas_aligned":
            from . import rs_pallas

            expand = rs_pallas.bit_matrix_planes
        else:
            expand = bit_matrix
        self._expand = expand
        # numpy, not a device array: constructing an RSJax must not
        # initialize the jax backend (a hung TPU relay would block the
        # caller — e.g. __graft_entry__.entry() — before any watchdog
        # can intervene). jit converts at call time; the matrix is tiny
        # (8m x 8k floats), so the per-call transfer is noise.
        self._parity_bits = np.asarray(
            expand(self._ref.parity), dtype=_ACC_DTYPE
        )
        # Bounded: shard-loss patterns are diverse in a long-lived volume
        # server; each entry pins an (8m x 8k) bit-matrix.
        self._decode_bits_cache: "collections.OrderedDict[tuple, np.ndarray]" = (
            collections.OrderedDict()
        )
        self._decode_cache_limit = 64
        # Raw-coefficient apply cache (the rebuild/degraded-read path
        # precomputes its decode coefficients once per shard-loss set and
        # then applies them to every batch — the expansion must not be
        # paid per batch).
        self._coeff_bits_cache: "collections.OrderedDict[bytes, np.ndarray]" = (
            collections.OrderedDict()
        )
        # The device-queue scheduler multiplexes several streams'
        # pipeline threads into ONE RSJax; move_to_end/popitem sequences
        # on the OrderedDict caches are not atomic under concurrent
        # lookups with different coefficient sets.
        self._cache_lock = threading.Lock()

    # -- encode ------------------------------------------------------------

    def _apply(self, bits: np.ndarray, data: jax.Array, m_out: int) -> jax.Array:
        if self.impl in ("pallas", "pallas_aligned"):
            from . import rs_pallas

            kwargs = {}
            if self.tile_n is not None:
                kwargs["tile_n"] = self.tile_n
            fn = (
                rs_pallas.apply_planes_pallas
                if self.impl == "pallas_aligned"
                else rs_pallas.apply_bitmajor_pallas
            )
            return fn(
                bits,
                data,
                k=int(data.shape[0]),
                m=m_out,
                interpret=self.interpret,
                **kwargs,
            )
        return _apply_bits(bits, data)

    def encode(self, data) -> jax.Array:
        """(k, n) uint8 data shards -> (m, n) uint8 parity shards."""
        data = jnp.asarray(data, dtype=jnp.uint8)
        if data.shape[0] != self.k:
            raise ValueError(f"expected {self.k} data rows, got {data.shape[0]}")
        return self._apply(self._parity_bits, data, self.m)

    # -- reconstruct -------------------------------------------------------

    def _rows_bits(self, out_rows: tuple[int, ...], src_rows: tuple[int, ...]) -> np.ndarray:
        """Bit-matrix mapping shards[src_rows] -> shards[out_rows]."""
        key = (out_rows, src_rows)
        with self._cache_lock:
            cached = self._decode_bits_cache.get(key)
            if cached is not None:
                self._decode_bits_cache.move_to_end(key)
                return cached
        sub = self.matrix[list(src_rows), :]
        inv = gf256.invert(sub)  # (k, k): src shards -> data shards
        want = gf256.matmul(self.matrix[list(out_rows), :], inv)
        bits = np.asarray(self._expand(want), dtype=_ACC_DTYPE)
        with self._cache_lock:
            self._decode_bits_cache[key] = bits
            if len(self._decode_bits_cache) > self._decode_cache_limit:
                self._decode_bits_cache.popitem(last=False)
        return bits

    def reconstruct(
        self,
        shards: dict[int, jax.Array],
        data_only: bool = False,
        want: list[int] | None = None,
    ):
        """Recover missing shards from any >=k present ones (device matmul).

        `want` restricts the output to specific shard ids (fewer matrix
        rows); default regenerates every missing shard."""
        present = tuple(sorted(shards))
        if len(present) < self.k:
            raise ValueError(f"need {self.k} shards, have {len(present)}")
        if want is not None:
            targets = want
        else:
            targets = range(self.k if data_only else self.n)
        missing = tuple(i for i in targets if i not in shards)
        if not missing:
            return {}
        src = present[: self.k]
        bits = self._rows_bits(missing, src)
        data = jnp.stack([jnp.asarray(shards[i], dtype=jnp.uint8) for i in src])
        out = self._apply(bits, data, len(missing))
        return {idx: out[i] for i, idx in enumerate(missing)}

    # -- general apply -----------------------------------------------------

    def coeff_bits(self, coeffs: np.ndarray) -> np.ndarray:
        """Expanded bit-matrix for an arbitrary (m_out x k) GF(256)
        coefficient matrix, cached by content (host numpy; converted at
        call time like _parity_bits so construction stays hang-free)."""
        coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
        key = coeffs.shape[0].to_bytes(4, "little") + coeffs.tobytes()
        with self._cache_lock:
            cached = self._coeff_bits_cache.get(key)
            if cached is not None:
                self._coeff_bits_cache.move_to_end(key)
                return cached
        bits = np.asarray(self._expand(coeffs), dtype=_ACC_DTYPE)
        with self._cache_lock:
            self._coeff_bits_cache[key] = bits
            if len(self._coeff_bits_cache) > self._decode_cache_limit:
                self._coeff_bits_cache.popitem(last=False)
        return bits

    def apply(self, coeffs: np.ndarray, data) -> jax.Array:
        """out[r] = sum_j coeffs[r,j] * data[j] over GF(256), dispatched
        on the device WITHOUT blocking (the staged-apply primitive: the
        caller decides when to force the result with np.asarray)."""
        coeffs = np.ascontiguousarray(coeffs, dtype=np.uint8)
        if coeffs.ndim != 2 or coeffs.shape[1] != len(data):
            raise ValueError(
                f"coeffs {coeffs.shape} do not match {len(data)} data rows"
            )
        bits = jnp.asarray(self.coeff_bits(coeffs))
        return self._apply(bits, jnp.asarray(data, dtype=jnp.uint8), coeffs.shape[0])

    def verify(self, shards) -> bool:
        shards = jnp.asarray(shards, dtype=jnp.uint8)
        parity = self.encode(shards[: self.k])
        return bool(jnp.array_equal(parity, shards[self.k :]))
