"""FilerStore SPI + embedded backends.

Reference: weed/filer/filerstore.go (insert/update/find/delete/list + KV)
with ~25 pluggable backends; here sqlite (the reference's
abstract_sql schema shape: directory + name + meta blob) and an
in-memory dict store. More backends slot in behind the same SPI.
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Iterator, Optional, Protocol

from .entry import Entry


class FilerStoreError(Exception):
    pass


class NotFound(FilerStoreError):
    pass


class FilerStore(Protocol):
    def insert(self, entry: Entry) -> None: ...
    def update(self, entry: Entry) -> None: ...
    def find(self, directory: str, name: str) -> Entry: ...
    def delete(self, directory: str, name: str) -> None: ...
    def delete_folder_children(self, directory: str) -> None: ...
    def list(
        self, directory: str, start_from: str = "", limit: int = 1024,
        prefix: str = "",
    ) -> Iterator[Entry]: ...
    def kv_put(self, key: bytes, value: bytes) -> None: ...
    def kv_get(self, key: bytes) -> Optional[bytes]: ...
    def kv_delete(self, key: bytes) -> None: ...
    def kv_put_if_absent(self, key: bytes, value: bytes) -> bytes: ...
    def close(self) -> None: ...


class MemoryStore:
    """Dict-backed store for tests and ephemeral filers."""

    def __init__(self):
        self._dirs: dict[str, dict[str, bytes]] = {}
        self._kv: dict[bytes, bytes] = {}
        self._lock = threading.RLock()

    def insert(self, entry: Entry) -> None:
        with self._lock:
            self._dirs.setdefault(entry.directory, {})[entry.name] = entry.to_bytes()

    update = insert

    def find(self, directory: str, name: str) -> Entry:
        with self._lock:
            raw = self._dirs.get(directory, {}).get(name)
        if raw is None:
            raise NotFound(f"{directory}/{name}")
        return Entry.from_bytes(directory, raw)

    def delete(self, directory: str, name: str) -> None:
        with self._lock:
            self._dirs.get(directory, {}).pop(name, None)

    def delete_folder_children(self, directory: str) -> None:
        with self._lock:
            prefix = directory if directory.endswith("/") else directory + "/"
            for d in [d for d in self._dirs if d == directory or d.startswith(prefix)]:
                del self._dirs[d]

    def list(self, directory, start_from="", limit=1024, prefix=""):
        with self._lock:
            names = sorted(self._dirs.get(directory, {}))
        n = 0
        for name in names:
            if name <= start_from if start_from else False:
                continue
            if prefix and not name.startswith(prefix):
                continue
            if n >= limit:
                return
            yield self.find(directory, name)
            n += 1

    def kv_put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._kv[key] = value

    def kv_get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._kv.get(key)

    def kv_delete(self, key: bytes) -> None:
        with self._lock:
            self._kv.pop(key, None)

    def kv_put_if_absent(self, key: bytes, value: bytes) -> bytes:
        """Atomic create-if-absent; returns the value that WON (the
        existing one, or `value` if the key was unset)."""
        with self._lock:
            return self._kv.setdefault(key, value)

    def close(self) -> None:
        pass


# Imported AFTER NotFound is defined: abstract_sql_store imports it
# back from this module (deliberate one-way-at-runtime cycle).
from .abstract_sql_store import AbstractSqlStore  # noqa: E402


class SqliteStore(AbstractSqlStore):
    """SQLite through the abstract-SQL template (reference
    weed/filer/sqlite riding weed/filer/abstract_sql): one row per
    entry keyed (directory, name), meta = protobuf blob. Any other
    PEP-249 driver is the same subclass shape with its dialect."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
        self.path = path

        def connect() -> sqlite3.Connection:
            con = sqlite3.connect(path, timeout=30)
            con.execute("PRAGMA journal_mode=WAL")
            con.execute("PRAGMA synchronous=NORMAL")
            return con

        super().__init__(connect)
