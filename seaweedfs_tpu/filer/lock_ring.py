"""Distributed lock ring across filers.

Reference: weed/cluster/lock_manager/ (+ filer_pb DistributedLock) —
the reference rings named exclusive leases across the live filers and
moves them when membership changes, so no single filer's death takes
the lock service down.

Design here: rendezvous (HRW) hashing assigns each lock name to the
highest-scoring LIVE filer; every filer serves the DistributedLock RPC
and forwards (one hop, loop-guarded) when it is not the owner. Lease
semantics reuse the master's LockManager (token renewal, never-shorten,
TTL expiry). Two things make locks SURVIVE membership changes:

- transfer on change: a mover thread pushes held leases whose slot
  moved (a new filer joined, or a dead one was noticed) to the new
  owner with their token + remaining TTL;
- renewal re-creation: a client renewing with its token after the
  owning filer DIED reaches the successor, which has no lease for the
  name and simply re-creates it under the presented token — the holder
  keeps mutual exclusion as long as it renews within its TTL.
"""

from __future__ import annotations

import hashlib
import threading
import time

import grpc

from ..pb import filer_pb2 as fpb
from ..pb import rpc
from ..server.cluster_lock import LockManager
from ..utils.glog import logger

log = logger("dlm")


def _score(member: str, name: str) -> int:
    return int.from_bytes(
        hashlib.sha1(f"{member}|{name}".encode()).digest()[:8], "big"
    )


class LockRing:
    """Membership + liveness view and request routing for one filer.

    `self_addr`/`members` are filer gRPC host:port addresses. Liveness
    is probed with cheap no-forward status RPCs; a member is dead after
    a failed probe/forward and alive again after a successful one.
    """

    # After a member dies, FRESH acquires of names it owned are denied
    # for this long: the dead filer's lease table died with it, and a
    # new owner granted immediately could coexist with the original
    # holder (who keeps renewing into the successor). Renewals with a
    # token pass — that's the survival path. Holders using TTLs longer
    # than this grace can still be raced; keep TTLs <= the grace.
    FAILOVER_GRACE = 15.0

    def __init__(
        self,
        self_addr: str,
        peers: list[str],
        locks: LockManager | None = None,
        probe_interval: float = 1.0,
    ):
        # NOTE: self_addr must be spelled EXACTLY as the peers list it
        # (localhost vs 127.0.0.1 vs hostname): HRW hashes the strings,
        # and a spelling mismatch silently splits the ring.
        self.self_addr = self_addr
        self.members = sorted({self_addr, *peers})
        self.locks = locks or LockManager()
        self.probe_interval = probe_interval
        self._alive: dict[str, bool] = {m: True for m in self.members}
        self._died_at: dict[str, float] = {}
        # names explicitly RELEASED here: a clean unlock proves the
        # name is free, so the failover grace need not hold it
        self._released_at: dict[str, float] = {}
        self._channels: dict[str, grpc.Channel] = {}
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._lock = threading.Lock()

    # ------------------------------------------------------------ routing

    def live_members(self) -> list[str]:
        with self._lock:
            return [m for m in self.members if self._alive.get(m, False)]

    def candidates(self, name: str) -> list[str]:
        """ALL members by HRW score (owner first); the serving fallback
        order when owners are unreachable."""
        return sorted(self.members, key=lambda m: _score(m, name), reverse=True)

    def owner_for(self, name: str) -> str:
        """Highest-scoring LIVE member (self counts as live)."""
        live = set(self.live_members()) | {self.self_addr}
        for m in self.candidates(name):
            if m in live:
                return m
        return self.self_addr

    def _stub(self, member: str):
        with self._lock:
            ch = self._channels.get(member)
            if ch is None:
                ch = grpc.insecure_channel(member)
                self._channels[member] = ch
        return rpc.filer_stub(ch)

    def mark(self, member: str, alive: bool) -> None:
        with self._lock:
            was = self._alive.get(member)
            self._alive[member] = alive
            if not alive and was:
                self._died_at[member] = time.monotonic()
        if was is not None and was != alive:
            log.info(
                f"dlm {self.self_addr}: member {member} "
                f"{'alive' if alive else 'DEAD'}"
            )

    def _in_failover_grace(self, member: str) -> bool:
        with self._lock:
            if self._alive.get(member, False):
                return False
            died = self._died_at.get(member)
        return died is None or time.monotonic() - died < self.FAILOVER_GRACE

    # ----------------------------------------------------------- serving

    def handle(self, request: fpb.DlmRequest) -> fpb.DlmResponse:
        """Serve or forward one DLM op."""
        if request.op == "status":
            return fpb.DlmResponse(
                ok=True,
                locks=[
                    fpb.DlmLockRow(name=n, owner=o, remaining=r)
                    for n, o, r in self.locks.status()
                ],
            )
        owner = self.owner_for(request.name)
        if owner != self.self_addr and not request.no_forward:
            # one-hop forward: LIVE candidates in HRW order first, then
            # dead ones as a last resort (a hard-down top member must
            # not cost every op a connect timeout)
            cands_all = self.candidates(request.name)
            above = cands_all[: cands_all.index(self.self_addr)]
            live = set(self.live_members())
            ordered = [c for c in above if c in live] + [
                c for c in above if c not in live
            ]
            for member in ordered:
                fwd = fpb.DlmRequest()
                fwd.CopyFrom(request)
                fwd.no_forward = True
                try:
                    resp = self._stub(member).DistributedLock(fwd, timeout=5)
                    self.mark(member, True)
                    return resp
                except grpc.RpcError:
                    self.mark(member, False)
                    continue
        return self._serve_local(request)

    def _serve_local(self, request: fpb.DlmRequest) -> fpb.DlmResponse:
        op = request.op
        if op == "lock" and not request.token:
            # Serving a FRESH acquire as the failover successor: the
            # dead owner's lease table died with it — granting
            # immediately could seat a second owner next to a holder
            # who is still renewing. Hold new grants through the grace
            # unless the name was explicitly released here (a clean
            # unlock proves it free) or a live lease already exists
            # (normal held-by denial is the right answer).
            top = self.candidates(request.name)[0]
            if (
                top != self.self_addr
                and self._in_failover_grace(top)
                and request.name not in self.locks._leases  # noqa: SLF001
                and (
                    time.monotonic()
                    - self._released_at.get(request.name, -1e9)
                    > self.FAILOVER_GRACE
                )
            ):
                return fpb.DlmResponse(
                    error=f"ring owner {top} in failover grace; retry"
                )
        if op in ("lock", "renew", "transfer"):
            ok, token, holder, remaining = self.locks.acquire(
                request.name,
                request.owner,
                request.ttl_seconds or 60.0,
                request.token,
            )
            return fpb.DlmResponse(
                ok=ok,
                token=token,
                holder=holder,
                remaining=remaining,
                error="" if ok else f"held by {holder}",
            )
        if op == "unlock":
            ok = self.locks.release(request.name, request.token)
            if ok:
                self._released_at[request.name] = time.monotonic()
            return fpb.DlmResponse(
                ok=ok, error="" if ok else "not held by this token"
            )
        return fpb.DlmResponse(error=f"bad op {op!r}")

    # ------------------------------------------- liveness + lock movement

    def start(self) -> None:
        t = threading.Thread(target=self._probe_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        # join before closing channels: an RPC issued on a channel
        # closed mid-flight raises ValueError out of the probe thread
        for t in self._threads:
            t.join(timeout=3)
        with self._lock:
            for ch in self._channels.values():
                ch.close()
            self._channels.clear()

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval):
            for m in self.members:
                if m == self.self_addr:
                    continue
                try:
                    self._stub(m).DistributedLock(
                        fpb.DlmRequest(op="status", no_forward=True),
                        timeout=2,
                    )
                    self.mark(m, True)
                except (grpc.RpcError, ValueError):
                    # ValueError: channel closed by a concurrent stop()
                    if self._stop.is_set():
                        return
                    self.mark(m, False)
            try:
                self._move_misplaced()
            except Exception as e:  # noqa: BLE001 — movement is best-effort
                log.warning(f"dlm lock move failed: {e!r}")

    def _move_misplaced(self) -> None:
        """Transfer held leases whose ring slot is no longer ours
        (reference lock_manager transfer-on-membership-change)."""
        for name, owner, remaining in self.locks.status():
            target = self.owner_for(name)
            if target == self.self_addr:
                continue
            lease = self.locks._leases.get(name)  # noqa: SLF001 — same pkg
            if lease is None:
                continue
            try:
                resp = self._stub(target).DistributedLock(
                    fpb.DlmRequest(
                        op="transfer",
                        name=name,
                        owner=owner,
                        ttl_seconds=max(remaining, 1.0),
                        token=lease.token,
                        no_forward=True,
                    ),
                    timeout=5,
                )
            except grpc.RpcError:
                self.mark(target, False)
                continue
            if resp.ok:
                self.locks.release(name, lease.token)
                log.v(1, f"dlm: moved lock {name!r} -> {target}")


class DlmClient:
    """Client-side router: computes the ring owner, falls through dead
    members, and renews held locks (DistributedLockClient analog)."""

    def __init__(self, filers: list[str]):
        self.members = sorted(set(filers))
        self._channels: dict[str, grpc.Channel] = {}
        self._lock = threading.Lock()  # shared across gRPC handler threads

    def close(self) -> None:
        with self._lock:
            for ch in self._channels.values():
                ch.close()
            self._channels.clear()

    def _stub(self, member: str):
        with self._lock:
            ch = self._channels.get(member)
            if ch is None:
                ch = grpc.insecure_channel(member)
                self._channels[member] = ch
        return rpc.filer_stub(ch)

    def _call(self, req: fpb.DlmRequest) -> fpb.DlmResponse:
        order = sorted(
            self.members, key=lambda m: _score(m, req.name), reverse=True
        )
        last: Exception | None = None
        for member in order:
            try:
                return self._stub(member).DistributedLock(req, timeout=5)
            except grpc.RpcError as e:
                last = e
                continue
        raise ConnectionError(f"no filer reachable for {req.name!r}: {last}")

    def lock(
        self, name: str, owner: str, ttl: float = 60.0, token: str = ""
    ) -> fpb.DlmResponse:
        return self._call(
            fpb.DlmRequest(
                op="lock", name=name, owner=owner, ttl_seconds=ttl, token=token
            )
        )

    def renew(self, name: str, owner: str, token: str, ttl: float = 60.0):
        return self._call(
            fpb.DlmRequest(
                op="renew", name=name, owner=owner, ttl_seconds=ttl, token=token
            )
        )

    def unlock(self, name: str, token: str) -> fpb.DlmResponse:
        return self._call(
            fpb.DlmRequest(op="unlock", name=name, token=token)
        )

    def status(self) -> list[tuple[str, str, float]]:
        """Union of live leases across every reachable filer (short
        per-member timeout: this rides admin RPCs and must not stall
        for seconds per dead filer)."""
        rows: dict[str, tuple[str, str, float]] = {}
        for member in self.members:
            try:
                resp = self._stub(member).DistributedLock(
                    fpb.DlmRequest(op="status", no_forward=True), timeout=1.5
                )
            except grpc.RpcError:
                continue
            for r in resp.locks:
                rows[r.name] = (r.name, r.owner, r.remaining)
        return sorted(rows.values())
