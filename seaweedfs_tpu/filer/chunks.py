"""Chunk interval resolution: which bytes of which chunk are visible.

Files are ordered FileChunk lists; overlapping writes are resolved by
modification time — the latest write wins (reference
weed/filer/filechunks.go ViewFromChunks / interval_list.go).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..pb import filer_pb2 as fpb


@dataclass(frozen=True)
class ChunkView:
    fid: str
    offset_in_chunk: int  # where in the chunk this view starts
    size: int
    logical_offset: int  # where in the file this view lands


def visible_intervals(chunks: list[fpb.FileChunk]) -> list[tuple[int, int, fpb.FileChunk]]:
    """-> [(start, stop, chunk)] non-overlapping, sorted by start."""
    intervals: list[tuple[int, int, fpb.FileChunk]] = []
    for c in sorted(chunks, key=lambda c: (c.modified_ts_ns, c.offset)):
        start, stop = c.offset, c.offset + c.size
        if stop <= start:
            continue
        updated: list[tuple[int, int, fpb.FileChunk]] = []
        for s, e, old in intervals:
            if e <= start or s >= stop:  # disjoint
                updated.append((s, e, old))
                continue
            if s < start:  # left remainder survives
                updated.append((s, start, old))
            if e > stop:  # right remainder survives
                updated.append((stop, e, old))
        updated.append((start, stop, c))
        updated.sort(key=lambda t: t[0])
        intervals = updated
    return intervals


def read_chunk_views(
    chunks: list[fpb.FileChunk], offset: int, size: int
) -> list[ChunkView]:
    """Views covering file range [offset, offset+size); gaps (sparse
    regions) are simply absent — callers zero-fill."""
    stop = offset + size
    views = []
    for s, e, c in visible_intervals(chunks):
        lo = max(s, offset)
        hi = min(e, stop)
        if lo >= hi:
            continue
        views.append(
            ChunkView(
                fid=c.fid,
                offset_in_chunk=lo - c.offset,
                size=hi - lo,
                logical_offset=lo,
            )
        )
    return views


def total_size(chunks: list[fpb.FileChunk]) -> int:
    return max((c.offset + c.size for c in chunks), default=0)
