"""TUS 1.0 resumable uploads over the filer.

Reference: weed/server/filer_server_tus_*.go — creation + patch + head
+ termination. Upload state survives filer restarts: each session is a
filer entry at /.tus/<id> whose extended attrs carry
{target, length, offset}; every PATCH body lands as a chunked part
file under /.tus/<id>.parts/, and completion SPLICES the part chunk
lists into the target entry (no data re-copy — the same fid-splicing
S3 multipart-complete uses).
"""

from __future__ import annotations

import json
import uuid

from .entry import Entry, new_entry
from .filer import Filer, FilerError
from .filer_store import NotFound

TUS_ROOT = "/.tus"
TUS_VERSION = "1.0.0"
TUS_EXTENSIONS = "creation,termination"


class TusError(Exception):
    def __init__(self, status: int, message: str):
        self.status = status
        super().__init__(message)


class TusManager:
    def __init__(self, filer: Filer):
        self.filer = filer
        # serializes PATCH application per manager: a retried duplicate
        # final PATCH must not double-run completion (which would GC
        # the chunks the first completion's target references)
        import threading

        self._lock = threading.Lock()

    # ------------------------------------------------------------ state

    def _session_path(self, upload_id: str) -> str:
        if "/" in upload_id or upload_id.startswith("."):
            raise TusError(404, "bad upload id")
        return f"{TUS_ROOT}/{upload_id}"

    def _load(self, upload_id: str) -> tuple[Entry, dict]:
        try:
            entry = self.filer.find_entry(self._session_path(upload_id))
        except NotFound:
            raise TusError(404, "unknown upload") from None
        try:
            state = json.loads(entry.extended.get("tus", b"{}"))
        except ValueError:
            raise TusError(500, "corrupt upload state") from None
        if "offset" not in state or "length" not in state:
            # an entry under /.tus that is not a session (e.g. the
            # .parts directory) must 404, not KeyError the handler
            raise TusError(404, "not an upload session")
        return entry, state

    # ------------------------------------------------------- operations

    def create(self, target_path: str, length: int) -> str:
        if length < 0:
            raise TusError(400, "Upload-Length required")
        try:
            existing = self.filer.find_entry(target_path)
            if existing.is_directory:
                # refuse now, not at the final PATCH: a doomed upload
                # should fail before any bytes move
                raise TusError(409, f"{target_path} is a directory")
        except NotFound:
            pass
        upload_id = uuid.uuid4().hex
        entry = new_entry(self._session_path(upload_id), mode=0o600)
        entry.extended["tus"] = json.dumps(
            {"target": target_path, "length": length, "offset": 0}
        ).encode()
        self.filer.create_entry(entry)
        return upload_id

    def head(self, upload_id: str) -> dict:
        _entry, state = self._load(upload_id)
        return state

    def patch(self, upload_id: str, offset: int, data: bytes) -> int:
        """Returns the new offset; completes the upload when the final
        byte lands. Serialized: concurrent duplicate PATCHes (client
        retries) must not double-complete."""
        with self._lock:
            _entry, state = self._load(upload_id)
            if offset != state["offset"]:
                raise TusError(
                    409, f"offset mismatch (have {state['offset']})"
                )
            if offset + len(data) > state["length"]:
                raise TusError(413, "body exceeds Upload-Length")
            new_offset = offset + len(data)
            if data:
                # parts are forced to chunked storage: completion
                # splices chunk lists, which inlined content lacks
                self.filer.write_file(
                    f"{self._session_path(upload_id)}.parts/{offset:020d}",
                    data,
                    inline=False,
                )
            if new_offset == state["length"]:
                # complete FIRST; only then persist/advance — a failed
                # completion leaves the offset at the previous value so
                # the client's retry re-lands the final part
                state["offset"] = new_offset
                self._complete(upload_id, state)
            elif data:
                state["offset"] = new_offset
                self._store_state(upload_id, state)
            return new_offset

    def terminate(self, upload_id: str) -> None:
        self._load(upload_id)  # 404 if unknown
        self.filer.delete_entry(
            f"{self._session_path(upload_id)}.parts", recursive=True
        )
        self.filer.delete_entry(self._session_path(upload_id))

    # ---------------------------------------------------------- helpers

    def _store_state(self, upload_id: str, state: dict) -> None:
        def mutate(entry: Entry) -> None:
            entry.extended["tus"] = json.dumps(state).encode()

        self.filer.mutate_entry(self._session_path(upload_id), mutate)

    def _complete(self, upload_id: str, state: dict) -> None:
        parts_dir = f"{self._session_path(upload_id)}.parts"
        combined = []
        pos = 0
        for part in self.filer.list_entries(parts_dir, limit=1_000_000):
            for c in self.filer.resolve_chunks(part):
                nc = type(c)()
                nc.CopyFrom(c)
                nc.offset = pos + (c.offset)
                combined.append(nc)
            pos += part.file_size
        if pos != state["length"]:
            raise TusError(500, "parts do not sum to Upload-Length")
        target = new_entry(state["target"], mode=0o644)
        target.chunks = combined
        target.attr.file_size = pos
        old = None
        try:
            old = self.filer.find_entry(state["target"])
        except NotFound:
            pass
        self.filer.create_entry(target)
        if old is not None:
            self.filer._release_entry_chunks(old)
        # drop part ENTRIES but keep their chunks — the target owns
        # them now
        for part in list(self.filer.list_entries(parts_dir, limit=1_000_000)):
            self.filer.delete_entry(part.full_path, gc_chunks=False)
        try:
            self.filer.delete_entry(parts_dir)
        except FilerError:
            pass
        self.filer.delete_entry(self._session_path(upload_id))
